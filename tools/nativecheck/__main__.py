"""CLI: ``python -m tools.nativecheck [--json] [repo_root]``.

Text mode prints every finding as ``file:line: [rule] message`` (waived
findings annotated with their justification) and exits nonzero when any
finding is unwaived or any waiver is stale — the tier-1 contract.

``--json`` emits one stable JSON document instead, for CI gates and
editor integrations that should not scrape text (schema below is
versioned and pinned by tests/test_nativecheck.py):

    {"schema": 1, "ok": bool, "elapsed_s": float,
     "unwaived": int, "waived": int, "stale": int,
     "findings": [{"rule", "file", "line", "site", "message",
                   "waived_by"  # null when unwaived
                  }, ...],                       # sorted (file, line)
     "stale_waivers": [{"rule", "site", "why"}, ...]}

Exit status is identical in both modes.
"""

import json
import sys
import time

from .rules import run


def main(argv: list) -> int:
    args = [a for a in argv[1:] if a != "--json"]
    as_json = "--json" in argv[1:]
    repo = args[0] if args else "."
    t0 = time.monotonic()
    res = run(repo)
    dt = time.monotonic() - t0
    findings = sorted(res.findings, key=lambda f: (f.file, f.line))
    n_unwaived = len(res.unwaived)
    n_waived = len(res.findings) - n_unwaived
    if as_json:
        doc = {
            "schema": 1,
            "ok": res.ok,
            "elapsed_s": round(dt, 3),
            "unwaived": n_unwaived,
            "waived": n_waived,
            "stale": len(res.stale_waivers),
            "findings": [
                {"rule": f.rule, "file": f.file, "line": f.line,
                 "site": f.site, "message": f.message,
                 "waived_by": f.waived_by}
                for f in findings],
            "stale_waivers": [
                {"rule": w.get("rule"), "site": w.get("site"),
                 "why": w.get("why")}
                for w in res.stale_waivers],
        }
        print(json.dumps(doc, indent=1))
        return 0 if res.ok else 1
    for f in findings:
        mark = f" [waived: {f.waived_by}]" if f.waived_by else ""
        print(f"{f.file}:{f.line}: [{f.rule}] {f.message}{mark}")
    for w in res.stale_waivers:
        print(f"waivers.py:0: [waivers] stale waiver "
              f"{w.get('rule')}:{w.get('site')} — matches no finding; "
              f"delete it")
    print(f"nativecheck: {n_unwaived} unwaived finding(s), {n_waived} "
          f"waived, {len(res.stale_waivers)} stale waiver(s) "
          f"[{dt:.2f}s]")
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
