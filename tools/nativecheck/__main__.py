"""CLI: ``python -m tools.nativecheck [repo_root]``.

Prints every finding as ``file:line: [rule] message`` (waived findings
annotated with their justification) and exits nonzero when any finding
is unwaived or any waiver is stale — the tier-1 contract."""

import sys
import time

from .rules import run


def main(argv: list) -> int:
    repo = argv[1] if len(argv) > 1 else "."
    t0 = time.monotonic()
    res = run(repo)
    for f in sorted(res.findings, key=lambda f: (f.file, f.line)):
        mark = f" [waived: {f.waived_by}]" if f.waived_by else ""
        print(f"{f.file}:{f.line}: [{f.rule}] {f.message}{mark}")
    for w in res.stale_waivers:
        print(f"waivers.py:0: [waivers] stale waiver "
              f"{w.get('rule')}:{w.get('site')} — matches no finding; "
              f"delete it")
    n_unwaived = len(res.unwaived)
    n_waived = len(res.findings) - n_unwaived
    dt = time.monotonic() - t0
    print(f"nativecheck: {n_unwaived} unwaived finding(s), {n_waived} "
          f"waived, {len(res.stale_waivers)} stale waiver(s) "
          f"[{dt:.2f}s]")
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
