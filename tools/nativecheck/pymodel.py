"""Python-side source model for the fold-discipline rule (rule 4).

The native server's ``_on_*`` kind-folds run on N concurrent poll
threads when sharded (PR 7): every fold that touches shared server
state must take that state's lock. The shared state is ANNOTATED at its
initialization site and the rule checks the folds mechanically:

  self.ack_plane = {...}          # @guards(_ack_lock)
  def _exemplar(self, ...):       # @locked(_tele_lock)

Semantics (deliberately strict — restructure the code rather than
teach the checker aliasing):

- scope = every method named ``_on_*`` plus every method TRANSITIVELY
  reachable from one through ``self.X()`` calls (round 17 — the old
  one-hop scope left a second callee hop unchecked; waivers are the
  pressure valve if the closure over-fires);
- ANY mention of a guarded attribute inside a scoped method must be
  lexically within a ``with self.<lock>:`` block naming the guarding
  lock — or the method is annotated ``@locked(<lock>)`` (the
  caller-holds contract), in which case its CALL SITES are checked
  instead;
- ``__init__`` is exempt (construction precedes concurrency).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

_ANNOT_RE = re.compile(r"#.*?@(guards|locked)\(([^)]*)\)")
_ATTR_RE = re.compile(r"self\.(\w+)\s*[:=]")


@dataclass
class PyMethod:
    name: str
    node: ast.FunctionDef
    locked: str | None = None      # @locked(<lock>) annotation
    locked_line: int = 0           # 1-based line carrying it


@dataclass
class PyClassModel:
    file: str
    guarded: dict = field(default_factory=dict)   # attr -> lock name
    guarded_lines: dict = field(default_factory=dict)  # attr -> line
    methods: dict = field(default_factory=dict)   # name -> PyMethod
    rlocks: set = field(default_factory=set)      # threading.RLock attrs


class PySource:
    def __init__(self, path: str, text: str | None = None,
                 class_name: str = "NativeBrokerServer"):
        self.path = path
        if text is None:
            with open(path) as f:
                text = f.read()
        self.text = text
        self.lines = text.split("\n")
        self.tree = ast.parse(text)
        self._method_index: dict = {}
        self.model = self._build(class_name)

    def _annotation_on(self, line: int) -> tuple[str, str, int] | None:
        """@guards/@locked annotation trailing on ``line`` (1-based) or
        on the comment line directly above it."""
        for probe in (line, line - 1):
            if 1 <= probe <= len(self.lines):
                m = _ANNOT_RE.search(self.lines[probe - 1])
                if m:
                    return m.group(1), m.group(2).strip(), probe
        return None

    def _build(self, class_name: str) -> PyClassModel:
        cls = None
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == class_name:
                cls = node
                break
        model = PyClassModel(file=self.path)
        if cls is None:
            return model
        for node in cls.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            ann = self._annotation_on(node.lineno)
            locked = ann[1] if ann and ann[0] == "locked" else None
            model.methods[node.name] = PyMethod(
                node.name, node, locked, ann[2] if locked else 0)
        # guarded attrs: any `self.X = ...` line in the class body
        # carrying a @guards annotation (typically in __init__)
        start = cls.lineno
        end = max((getattr(n, "end_lineno", start) for n in cls.body),
                  default=start)
        rlock_re = re.compile(r"self\.(\w+)\s*=\s*threading\.RLock\(")
        for line in range(start, end + 1):
            rm = rlock_re.search(self.lines[line - 1])
            if rm:
                model.rlocks.add(rm.group(1))
            m = _ANNOT_RE.search(self.lines[line - 1])
            if not m or m.group(1) != "guards":
                continue
            # the annotated statement: this line, or the next code line
            target = line
            am = _ATTR_RE.search(self.lines[target - 1])
            while am is None and target < end:
                target += 1
                am = _ATTR_RE.search(self.lines[target - 1])
            if am:
                model.guarded[am.group(1)] = m.group(2).strip()
                model.guarded_lines[am.group(1)] = line
        return model

    # -- rule-4 views --------------------------------------------------------

    def scoped_methods(self) -> dict[str, PyMethod]:
        """``_on_*`` methods plus every method transitively reachable
        from one through ``self.X()`` calls (round 17: the full
        closure within the file — a fold's guarded-state touch two
        callee hops down is no longer invisible)."""
        model = self.model
        scoped: dict[str, PyMethod] = {
            n: m for n, m in model.methods.items() if n.startswith("_on_")}
        frontier = list(scoped.values())
        while frontier:
            m = frontier.pop()
            for callee in self._self_calls(m.node):
                if callee in model.methods and callee not in scoped:
                    scoped[callee] = model.methods[callee]
                    frontier.append(model.methods[callee])
        return scoped

    def transitive_acquires(self, name: str,
                            _seen: set | None = None) -> set:
        """Every lock attr a call to method ``name`` may acquire —
        directly or through transitive ``self.X()`` callees (the
        lock-order rule's interprocedural view)."""
        seen = _seen if _seen is not None else set()
        if name in seen:
            return set()
        seen.add(name)
        m = self.model.methods.get(name)
        if m is None:
            return set()
        out = {w for w, _a, _b in self.with_regions(m.node)}
        for callee in self._index(m.node)["calls"]:
            out |= self.transitive_acquires(callee, seen)
        return out

    @staticmethod
    def _self_calls(node: ast.AST):
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "self"):
                yield sub.func.attr

    def _index(self, fn: ast.FunctionDef) -> dict:
        """ONE walk per method (memoized — check_pyfold consults this
        per guarded attr and per @locked callee): with-regions, every
        self.<attr> mention line, every self.<name>() call line."""
        cached = self._method_index.get(id(fn))
        if cached is not None:
            return cached
        withs: list = []
        attrs: dict = {}
        calls: dict = {}
        for sub in ast.walk(fn):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    ctx = item.context_expr
                    if (isinstance(ctx, ast.Attribute)
                            and isinstance(ctx.value, ast.Name)
                            and ctx.value.id == "self"):
                        withs.append((ctx.attr, sub.body[0].lineno,
                                      sub.end_lineno))
            elif (isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"):
                attrs.setdefault(sub.attr, []).append(sub.lineno)
            elif (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "self"):
                calls.setdefault(sub.func.attr, []).append(sub.lineno)
        idx = {"withs": withs, "attrs": attrs, "calls": calls}
        self._method_index[id(fn)] = idx
        return idx

    def with_regions(self, fn: ast.FunctionDef) -> list[tuple[str, int, int]]:
        """(lock attr, first body line, last body line) for every
        ``with self.<lock>:`` in the method."""
        return self._index(fn)["withs"]

    def attr_mentions(self, fn: ast.FunctionDef, attr: str) -> list[int]:
        """Line numbers of every ``self.<attr>`` mention in the body."""
        return self._index(fn)["attrs"].get(attr, [])

    def locked_calls(self, fn: ast.FunctionDef,
                     callee: str) -> list[int]:
        """Line numbers of every ``self.<callee>(...)`` call."""
        return self._index(fn)["calls"].get(callee, [])
