"""nativecheck: a compiler-free concurrency & contract analyzer for
the C++ native plane (tools/nativecheck).

Entry points:
- ``python -m tools.nativecheck``  — CLI, nonzero exit on unwaived
  findings or stale waivers (tier-1 wires it via
  tests/test_nativecheck.py);
- ``tools.nativecheck.rules.run(repo)`` — programmatic API;
- ``tools.nativecheck.model`` — the shared C++ source model the legacy
  lints (test_stats_lint / test_native_wire_lint) also build on.
"""

from .rules import Finding, Result, run  # noqa: F401
