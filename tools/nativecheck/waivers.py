"""nativecheck waivers: deliberate, justified exceptions to the rules.

Each entry names the rule, the exact finding site key, and a one-line
justification. A waiver that matches no live finding FAILS the check
(stale-waiver hygiene): when the code stops violating, the waiver must
be deleted, so this file can never silently rot into a blanket
allowlist. Keep justifications honest — they are the documented
contract for why the violation is the design.
"""

WAIVERS = [
    # -- plane: the durable store's fsync contract ---------------------------
    # FlushDirty orders every socket write of a read batch BEHIND the
    # durable batch append + policy msync (host.cc round 10): a QoS1
    # PUBACK on the wire must imply the message is on disk, so the
    # poll thread paying the (batched, once-per-flush) msync IS the
    # durability design — the 120k-msyncs wedge this analyzer exists
    # to prevent was PER-ENTRY consumes, which now batch per record on
    # Python threads.
    {"rule": "plane", "site": "store.h:SyncSeg",
     "why": "PUBACK-after-fsync durability contract: one batched msync "
            "per flush on the poll thread is the round-10 design"},
    # AppendFrame rolls to a fresh segment when the active one fills:
    # an open/ftruncate/mmap on the poll thread, amortized over a whole
    # segment (default 4 MB) of appends.
    {"rule": "plane", "site": "store.h:Roll",
     "why": "segment roll (open+ftruncate+mmap) amortized over a whole "
            "segment of batched appends; same contract as SyncSeg"},

    # -- ladder: receivers of already-admitted publishes ---------------------
    # The trunk receiver cannot punt a publish that already left its
    # origin node (the sender ran the ladder); FanOut degrades its
    # cross-shard legs per-destination through the RingRoom re-check
    # instead (host.cc TrunkFanOut comment).
    {"rule": "ladder", "site": "host.cc:TrunkFanOut->FanOut",
     "why": "trunk receiver: the PUBLISHING node ran the ladder; FanOut "
            "degrades per-destination via its RingRoom re-check"},
    # Ring consumers apply entries the producer shard already admitted
    # (ShardAdmit ran before the entry was shipped).
    {"rule": "ladder", "site": "host.cc:ApplyShardBatch->TrunkEnqueue",
     "why": "ring consumer: the producing shard ran ShardAdmit before "
            "shipping the trunk-forward entry"},
]

# The declared lock-acquisition order (rule 8, round 17). Every edge
# the analyzer OBSERVES in the global graph (lock_guard scopes + `with
# self._lock` regions, call-graph propagated across both languages)
# must be declared here; every edge declared here must still be
# observed (stale edges fail, the waiver-hygiene discipline). The
# chain below is the PR 9 _durable_token docstring, now enforced.
# Reentrant self-acquisition of an RLock is the lock's own semantics
# and needs no entry; a self-edge on a plain Lock always fails.
LOCK_ORDER = [
    # subscribe events fold shared-group state, then reconcile the
    # C++ install under the mirror lock (_reconcile_shared)
    {"order": "_shared_lock < _mirror_lock",
     "why": "_on_shared_event holds _shared_lock across "
            "_reconcile_shared, which takes _mirror_lock for the punt "
            "refcounts"},
    # the sub-event fold runs whole under the reentrant _mirror_lock
    # and mints durable tokens inside it (_durable_token)
    {"order": "_mirror_lock < _durable_lock",
     "why": "_on_sub_event holds _mirror_lock across "
            "_on_sub_event_locked -> _durable_token, which writes the "
            "reverse map under _durable_lock; never acquire "
            "_mirror_lock while holding _durable_lock"},
    # kind-10 folds resolve closed-conn info for disconnected sessions
    {"order": "_durable_lock < _closed_lock",
     "why": "_on_durable_locked (@locked(_durable_lock)) resolves "
            "conninfo through _conninfo_for, which reads _closed_conns "
            "under _closed_lock"},
    # the span fold attributes ingress spans to (possibly just-closed)
    # publisher conns
    {"order": "_tele_lock < _closed_lock",
     "why": "_on_spans holds _tele_lock across _conninfo_for's "
            "_closed_conns read"},
]
