"""nativecheck rules: five checked invariants over the native plane.

Rule catalog (see README "Static analysis of the native plane"):

  plane    — plane propagation: no function reachable from a
             ``@plane(poll)`` root through the call graph may be
             ``@blocking`` or ``@plane(control)`` (the
             msync/fsync-on-the-poll-thread class).
  lockset  — every access to a ``@guards(<mu>)``-annotated field is
             inside a ``lock_guard(<mu>)`` block or in a function
             annotated ``@locked(<mu>)`` (Eraser-style, lexical).
  ladder   — within a function, every call to an ``@admit-gated``
             side-effect function lexically FOLLOWS an
             ``@admit-check`` call (ladder decisions BEFORE side
             effects — the PR 4/7 contract).
  pyfold   — every ``_on_*`` kind-fold in broker/native_server.py that
             mentions a ``# @guards(<lock>)`` attribute does so under
             ``with self.<lock>:`` (multi-producer safety, PR 7).
  fault    — faultline coverage (round 15): every C++ fault-injection
             fire site names its ``fault.h`` site with an
             ``@fault(<site>)`` annotation, every declared site has at
             least one annotated fire site AND is exercised by at
             least one test, and the Python ``FAULT_SITES`` tuple
             matches the enum exactly (the sanitizer-lint pattern:
             a typo'd site name must fail the build, never arm
             nothing).
  waivers  — waiver hygiene: every waiver names a known rule, carries
             a justification, and matches a live finding (a stale
             waiver is drift in the other direction).

Findings carry a stable site key ``<rule>:<site>`` that waivers match
exactly. ``run()`` accepts text overrides so the mutation self-test can
re-analyze seeded-bad variants without touching the tree.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass

from .model import CppModel, enumerators, snake
from .pymodel import PySource

CPP_FILES = ("host.cc", "store.h", "trunk.h", "ring.h", "router.h",
             "sn.h", "ws.h", "frame.h", "fault.h", "wheel.h", "park.h")
PY_FOLD_FILE = os.path.join("emqx_tpu", "broker", "native_server.py")

RULES = ("plane", "lockset", "ladder", "pyfold", "fault", "waivers")


@dataclass(frozen=True)
class Finding:
    rule: str
    file: str
    line: int
    site: str          # waiver-matchable key, e.g. "host.cc:TrunkFanOut->FanOut"
    message: str
    waived_by: str | None = None   # justification when a waiver matched

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.site}"


@dataclass
class Result:
    findings: list          # every Finding, waived or not
    stale_waivers: list     # waiver dicts that matched nothing

    @property
    def unwaived(self) -> list:
        return [f for f in self.findings if f.waived_by is None]

    @property
    def ok(self) -> bool:
        return not self.unwaived and not self.stale_waivers

    def keys(self) -> frozenset:
        """Canonical comparison view: every finding key (suffixed when
        waived) plus stale-waiver keys — the 'rule result' the
        load-bearing test diffs."""
        out = {f.key + ("|waived" if f.waived_by else "")
               for f in self.findings}
        out |= {f"stale:{w['rule']}:{w['site']}" for w in self.stale_waivers}
        return frozenset(out)


_PY_CACHE: dict = {}


def _cached_py(path: str, text: str | None) -> PySource:
    if text is None:
        with open(path) as f:
            text = f.read()
    key = (path, hash(text))
    src = _PY_CACHE.get(key)
    if src is None or src.text != text:
        src = PySource(path, text=text)
        _PY_CACHE[key] = src
    return src


def cpp_paths(repo: str) -> list[str]:
    src = os.path.join(repo, "emqx_tpu", "native", "src")
    return [os.path.join(src, f) for f in CPP_FILES]


def build_cpp_model(repo: str,
                    overrides: dict[str, str] | None = None) -> CppModel:
    return CppModel(cpp_paths(repo), overrides=overrides)


# -- rule: plane --------------------------------------------------------------

def check_plane(model: CppModel) -> list[Finding]:
    out: list[Finding] = []
    roots = list(model.annotated("plane", "poll"))
    if not roots:
        out.append(Finding(
            "plane", "host.cc", 1, "host.cc:<no-poll-root>",
            "no @plane(poll) root found — the plane rule has nothing "
            "to propagate from"))
        return out
    # BFS over the call graph from the poll roots; remember one example
    # path per function for the finding message
    seen: dict[int, list] = {}
    queue: list = []
    for r in roots:
        seen[id(r)] = [r.name]
        queue.append(r)
    while queue:
        fn = queue.pop()
        path = seen[id(fn)]
        for callee, _off in model.call_edges(fn):
            if id(callee) in seen:
                continue
            seen[id(callee)] = path + [callee.name]
            queue.append(callee)
    flagged = set()
    for fn in list(model.functions()):
        if id(fn) not in seen:
            continue
        bad = None
        if "blocking" in fn.annotations:
            bad = "@blocking"
        elif fn.annotation("plane") == "control":
            bad = "@plane(control)"
        if bad and fn.name not in flagged:
            # key on the callee endpoint: one waiver covers every path
            # to a deliberately-blocking function (the fsync contract)
            flagged.add(fn.name)
            path = " -> ".join(seen[id(fn)])
            out.append(Finding(
                "plane", fn.file, fn.line, f"{fn.file}:{fn.name}",
                f"{bad} function {fn.name} is reachable from the poll "
                f"plane: {path}"))
    return out


# -- rule: lockset ------------------------------------------------------------

def check_lockset(model: CppModel) -> list[Finding]:
    out: list[Finding] = []
    for src, fld in model.fields_annotated("guards"):
        mu = fld.annotations["guards"].arg
        for fn in src.functions:
            if fn.annotation("locked") == mu:
                continue
            accesses = src.field_accesses(fn, fld.name)
            if not accesses:
                continue
            locks = [s for s in src.lock_sites(fn) if s[0] == mu]
            for off in accesses:
                if any(lo <= off < end for _m, lo, end in locks):
                    continue
                out.append(Finding(
                    "lockset", src.name, src.line_of(off),
                    f"{src.name}:{fn.name}:{fld.name}",
                    f"{fn.name} accesses {fld.name} (guarded by {mu}) "
                    f"outside any {mu} lock scope and is not "
                    f"@locked({mu})"))
                break  # one finding per (function, field)
    return out


# -- rule: ladder -------------------------------------------------------------

def check_ladder(model: CppModel) -> list[Finding]:
    out: list[Finding] = []
    gated = {fn.name for fn in model.annotated("admit-gated")}
    checks = {fn.name for fn in model.annotated("admit-check")}
    if not gated or not checks:
        return out
    seen_sites = set()
    for fn in model.functions():
        if fn.name in gated or fn.name in checks:
            continue
        src = model.source_of(fn)
        calls = src.calls(fn)
        check_offs = [off for name, off in calls if name in checks]
        for name, off in calls:
            if name not in gated:
                continue
            site = f"{fn.file}:{fn.name}->{name}"
            if site in seen_sites:
                continue
            if not any(co < off for co in check_offs):
                seen_sites.add(site)
                out.append(Finding(
                    "ladder", fn.file, src.line_of(off), site,
                    f"{fn.name} calls @admit-gated {name} with no "
                    f"@admit-check (ShardAdmit/TrunkEligible/RingRoom) "
                    f"lexically before it — ladder decisions must "
                    f"precede side effects"))
    return out


# -- rule: pyfold -------------------------------------------------------------

def check_pyfold(py: PySource) -> list[Finding]:
    out: list[Finding] = []
    model = py.model
    fname = os.path.basename(py.path)
    scoped = py.scoped_methods()
    for name, meth in scoped.items():
        if name == "__init__":
            continue
        regions_all = py.with_regions(meth.node)
        for attr, lock in model.guarded.items():
            if meth.locked == lock:
                continue
            regions = [(a, b) for w, a, b in regions_all if w == lock]
            for line in py.attr_mentions(meth.node, attr):
                if any(a <= line <= b for a, b in regions):
                    continue
                out.append(Finding(
                    "pyfold", fname, line, f"{fname}:{name}:{attr}",
                    f"{name} touches self.{attr} (guarded by {lock}) "
                    f"outside `with self.{lock}:` and is not "
                    f"@locked({lock})"))
                break
        # calls into @locked helpers must hold their lock
        for callee_name, callee in model.methods.items():
            if callee.locked is None or callee_name == name:
                continue
            if meth.locked == callee.locked:
                continue
            regions = [(a, b) for w, a, b in regions_all
                       if w == callee.locked]
            for line in py.locked_calls(meth.node, callee_name):
                if any(a <= line <= b for a, b in regions):
                    continue
                out.append(Finding(
                    "pyfold", fname, line,
                    f"{fname}:{name}->{callee_name}",
                    f"{name} calls @locked({callee.locked}) helper "
                    f"{callee_name} outside `with self."
                    f"{callee.locked}:`"))
                break
    return out


# -- rule: fault (faultline coverage, round 15) -------------------------------
# The sanitizer-lint pattern applied to fault injection: fault.h's Site
# enum is the canonical catalog, every C++ FIRE site (a line using a
# kSite token together with the firing vocabulary) must carry a
# matching // @fault(<site>) within its preceding 4 lines, every
# declared site needs >= 1 such fire site AND a test that names it, and
# native/__init__.py's FAULT_SITES must mirror the enum exactly. A site
# that exists only on one side — or a chaos lever no test ever pulls —
# fails the build.

_FAULT_TOKEN_RE = re.compile(r"\bkSite([A-Z]\w*)\b")
_FAULT_ANN_RE = re.compile(r"@fault\(([a-z0-9_]+)\)")
# only lines that DECIDE a firing are fire sites; arm/forwarding
# plumbing (FaultArm routing store sites) names kSite tokens too
_FIRE_VOCAB = ("Fire(", "FaultHit(", "FaultRecv(", "FaultSend(",
               "armed(")
_PY_SITES_RE = re.compile(r"FAULT_SITES = \(([^)]*)\)", re.S)

_TESTS_BLOB_CACHE: dict = {}


def _tests_blob(repo: str) -> str:
    # keyed by the directory's (name, mtime, size) signature so a
    # long-lived process (editor integration) sees edits — a stale
    # blob would keep passing a site whose test was deleted
    tdir = os.path.join(repo, "tests")
    names = (sorted(f for f in os.listdir(tdir) if f.endswith(".py"))
             if os.path.isdir(tdir) else [])
    sig = []
    for f in names:
        try:
            st = os.stat(os.path.join(tdir, f))
            sig.append((f, st.st_mtime_ns, st.st_size))
        except OSError:
            pass
    key = (repo, tuple(sig))
    blob = _TESTS_BLOB_CACHE.get(key)
    if blob is None:
        parts = []
        for f in names:
            try:
                with open(os.path.join(tdir, f)) as fh:
                    parts.append(fh.read())
            except OSError:
                pass
        blob = "\n".join(parts)
        _TESTS_BLOB_CACHE.clear()       # one live entry per process
        _TESTS_BLOB_CACHE[key] = blob
    return blob


def check_fault(model: CppModel, repo: str) -> list[Finding]:
    out: list[Finding] = []
    fh = model.sources.get("fault.h")
    if fh is None:
        return [Finding("fault", "fault.h", 1, "fault.h:<missing>",
                        "fault.h is absent — the fault rule has no "
                        "site catalog")]
    sites = [snake(s) for s in enumerators(fh.text, "Site", "kSite")
             if s != "Count"]
    covered: set = set()
    for src in model.sources.values():
        if src.name == "fault.h":
            continue
        raw_lines = src.text.split("\n")
        code_lines = src.code.split("\n")
        for i, cl in enumerate(code_lines):
            toks = [snake(m.group(1))
                    for m in _FAULT_TOKEN_RE.finditer(cl)
                    if m.group(1) != "Count"]
            if not toks or not any(v in cl for v in _FIRE_VOCAB):
                continue
            anns: set = set()
            for back in range(0, 5):
                if i - back < 0:
                    break
                anns.update(_FAULT_ANN_RE.findall(raw_lines[i - back]))
            for name in toks:
                if name in anns:
                    covered.add(name)
                else:
                    out.append(Finding(
                        "fault", src.name, i + 1,
                        f"{src.name}:{i + 1}:{name}",
                        f"fault fire site for {name} lacks a matching "
                        f"// @fault({name}) annotation nearby"))
        # unknown site names in annotations anywhere
        for j, raw in enumerate(raw_lines):
            for name in _FAULT_ANN_RE.findall(raw):
                if name not in sites:
                    out.append(Finding(
                        "fault", src.name, j + 1,
                        f"{src.name}:{j + 1}:@fault({name})",
                        f"@fault({name}) names no fault.h site "
                        f"(valid: {sites})"))
    for s in sites:
        if s not in covered:
            out.append(Finding(
                "fault", "fault.h", 1, f"fault.h:{s}",
                f"fault site {s} is declared but has no annotated C++ "
                f"fire site"))
    blob = _tests_blob(repo)
    for s in sites:
        if not re.search(rf"\b{s}\b", blob):
            out.append(Finding(
                "fault", "tests", 0, f"tests:{s}",
                f"fault site {s} is never exercised by any test under "
                f"tests/ (name it in an arm/assert)"))
    # Python parity: a site name armable from Python must exist in C++
    # and vice versa, same order (the mechanical STAT_NAMES discipline)
    nat = os.path.join(repo, "emqx_tpu", "native", "__init__.py")
    try:
        with open(nat) as f:
            m = _PY_SITES_RE.search(f.read())
    except OSError:
        m = None
    py_sites = re.findall(r'"([a-z0-9_]+)"', m.group(1)) if m else []
    if py_sites != sites:
        out.append(Finding(
            "fault", "__init__.py", 0, "native/__init__.py:FAULT_SITES",
            f"native.FAULT_SITES {py_sites} drifted from fault.h Site "
            f"enum {sites}"))
    return out


# -- rule: waivers (hygiene) + assembly ---------------------------------------

def apply_waivers(findings: list, waivers: list) -> Result:
    out: list[Finding] = []
    used = [False] * len(waivers)
    extra: list[Finding] = []
    by_key: dict[str, int] = {}
    for i, w in enumerate(waivers):
        if w.get("rule") not in RULES or not w.get("site") \
                or not str(w.get("why", "")).strip():
            extra.append(Finding(
                "waivers", "waivers.py", 0,
                f"waivers.py:{w.get('rule')}:{w.get('site')}",
                f"malformed waiver {w!r}: needs a known rule, a site, "
                f"and a non-empty why"))
            used[i] = True  # malformed: never matches, already reported
            continue
        by_key[f"{w['rule']}:{w['site']}"] = i
    for f in findings:
        i = by_key.get(f.key)
        if i is not None:
            used[i] = True
            out.append(Finding(f.rule, f.file, f.line, f.site, f.message,
                               waived_by=str(waivers[i]["why"])))
        else:
            out.append(f)
    stale = [w for i, w in enumerate(waivers) if not used[i]]
    return Result(findings=out + extra, stale_waivers=stale)


def run(repo: str, overrides: dict[str, str] | None = None,
        waivers: list | None = None) -> Result:
    """Analyze the tree (with optional per-file text overrides, keyed
    by basename for C++ sources and by "native_server.py" for the
    Python fold file) and apply waivers."""
    overrides = overrides or {}
    if waivers is None:
        from .waivers import WAIVERS as waivers
    model = build_cpp_model(repo, overrides=overrides)
    py = _cached_py(os.path.join(repo, PY_FOLD_FILE),
                    overrides.get("native_server.py"))
    findings = (check_plane(model) + check_lockset(model)
                + check_ladder(model) + check_pyfold(py)
                + check_fault(model, repo))
    return apply_waivers(findings, waivers)
