"""nativecheck rules: five checked invariants over the native plane.

Rule catalog (see README "Static analysis of the native plane"):

  plane    — plane propagation: no function reachable from a
             ``@plane(poll)`` root through the call graph may be
             ``@blocking`` or ``@plane(control)`` (the
             msync/fsync-on-the-poll-thread class).
  lockset  — every access to a ``@guards(<mu>)``-annotated field is
             inside a ``lock_guard(<mu>)`` block or in a function
             annotated ``@locked(<mu>)`` (Eraser-style, lexical).
  ladder   — within a function, every call to an ``@admit-gated``
             side-effect function lexically FOLLOWS an
             ``@admit-check`` call (ladder decisions BEFORE side
             effects — the PR 4/7 contract).
  pyfold   — every ``_on_*`` kind-fold in broker/native_server.py that
             mentions a ``# @guards(<lock>)`` attribute does so under
             ``with self.<lock>:`` (multi-producer safety, PR 7).
  fault    — faultline coverage (round 15): every C++ fault-injection
             fire site names its ``fault.h`` site with an
             ``@fault(<site>)`` annotation, every declared site has at
             least one annotated fire site AND is exercised by at
             least one test, and the Python ``FAULT_SITES`` tuple
             matches the enum exactly (the sanitizer-lint pattern:
             a typo'd site name must fail the build, never arm
             nothing).
  atomics  — memory-order discipline (round 17): every ``std::atomic``
             field carries ``@atomic(<discipline>: why)`` and every
             load/store/RMW site passes an explicit
             ``std::memory_order_*`` within the discipline (a bare
             seq_cst-defaulted access always flags). Structural legs:
             ``@published(<idx>)`` data may never be touched lexically
             AFTER a release store of its index in the same function
             (the SPSC write-then-publish shape, ring.h), and the
             generation-handle protocol (wheel.h/park.h): ``@gen-check``
             validators compare generations, ``@gen-bump`` recyclers
             bump them, ``@gen-checked`` consumers validate FIRST, and
             ``@gen-handle`` fields only flow into checked consumers.
  lock-order — the global lock-acquisition graph (lock_guard scopes +
             ``with self._lock`` regions, both languages, call-graph
             propagated) must match the ``LOCK_ORDER`` edges declared
             in waivers.py: an undeclared nesting, a declared-but-
             never-observed edge, a cycle, or a self-acquisition of a
             non-reentrant lock is a finding (the PR 9
             _shared_lock -> _mirror_lock -> _durable_lock docstring,
             enforced).
  tap-bound — every append into a ``@bounded`` poll-cycle event buffer
             happens in a ``@bounded(<buf>)`` writer whose append is
             lexically preceded by a chunk-or-flush margin check
             against the buffer cap (the kind-6 header-seed and
             kind-10 4096-token lessons, static).
  waivers  — waiver hygiene: every waiver names a known rule, carries
             a justification, and matches a live finding (a stale
             waiver is drift in the other direction).

Findings carry a stable site key ``<rule>:<site>`` that waivers match
exactly. ``run()`` accepts text overrides so the mutation self-test can
re-analyze seeded-bad variants without touching the tree.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass

from .model import CppModel, _MEMORY_ORDER_RE, enumerators, snake
from .pymodel import PySource

CPP_FILES = ("host.cc", "store.h", "trunk.h", "ring.h", "router.h",
             "sn.h", "ws.h", "frame.h", "fault.h", "wheel.h", "park.h",
             "coap.h")
PY_FOLD_FILE = os.path.join("emqx_tpu", "broker", "native_server.py")

RULES = ("plane", "lockset", "ladder", "pyfold", "fault",
         "atomics", "lock-order", "tap-bound", "waivers")


@dataclass(frozen=True)
class Finding:
    rule: str
    file: str
    line: int
    site: str          # waiver-matchable key, e.g. "host.cc:TrunkFanOut->FanOut"
    message: str
    waived_by: str | None = None   # justification when a waiver matched

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.site}"


@dataclass
class Result:
    findings: list          # every Finding, waived or not
    stale_waivers: list     # waiver dicts that matched nothing

    @property
    def unwaived(self) -> list:
        return [f for f in self.findings if f.waived_by is None]

    @property
    def ok(self) -> bool:
        return not self.unwaived and not self.stale_waivers

    def keys(self) -> frozenset:
        """Canonical comparison view: every finding key (suffixed when
        waived) plus stale-waiver keys — the 'rule result' the
        load-bearing test diffs."""
        out = {f.key + ("|waived" if f.waived_by else "")
               for f in self.findings}
        out |= {f"stale:{w['rule']}:{w['site']}" for w in self.stale_waivers}
        return frozenset(out)


_PY_CACHE: dict = {}


def _cached_py(path: str, text: str | None) -> PySource:
    if text is None:
        with open(path) as f:
            text = f.read()
    key = (path, hash(text))
    src = _PY_CACHE.get(key)
    if src is None or src.text != text:
        src = PySource(path, text=text)
        _PY_CACHE[key] = src
    return src


def cpp_paths(repo: str) -> list[str]:
    src = os.path.join(repo, "emqx_tpu", "native", "src")
    return [os.path.join(src, f) for f in CPP_FILES]


def build_cpp_model(repo: str,
                    overrides: dict[str, str] | None = None) -> CppModel:
    return CppModel(cpp_paths(repo), overrides=overrides)


# -- rule: plane --------------------------------------------------------------

def check_plane(model: CppModel) -> list[Finding]:
    out: list[Finding] = []
    roots = list(model.annotated("plane", "poll"))
    if not roots:
        out.append(Finding(
            "plane", "host.cc", 1, "host.cc:<no-poll-root>",
            "no @plane(poll) root found — the plane rule has nothing "
            "to propagate from"))
        return out
    # BFS over the call graph from the poll roots; remember one example
    # path per function for the finding message
    seen: dict[int, list] = {}
    queue: list = []
    for r in roots:
        seen[id(r)] = [r.name]
        queue.append(r)
    while queue:
        fn = queue.pop()
        path = seen[id(fn)]
        for callee, _off in model.call_edges(fn):
            if id(callee) in seen:
                continue
            seen[id(callee)] = path + [callee.name]
            queue.append(callee)
    flagged = set()
    for fn in list(model.functions()):
        if id(fn) not in seen:
            continue
        bad = None
        if "blocking" in fn.annotations:
            bad = "@blocking"
        elif fn.annotation("plane") == "control":
            bad = "@plane(control)"
        if bad and fn.name not in flagged:
            # key on the callee endpoint: one waiver covers every path
            # to a deliberately-blocking function (the fsync contract)
            flagged.add(fn.name)
            path = " -> ".join(seen[id(fn)])
            out.append(Finding(
                "plane", fn.file, fn.line, f"{fn.file}:{fn.name}",
                f"{bad} function {fn.name} is reachable from the poll "
                f"plane: {path}"))
    return out


# -- rule: lockset ------------------------------------------------------------

def check_lockset(model: CppModel) -> list[Finding]:
    out: list[Finding] = []
    for src, fld in model.fields_annotated("guards"):
        mu = fld.annotations["guards"].arg
        for fn in src.functions:
            if fn.annotation("locked") == mu:
                continue
            accesses = src.field_accesses(fn, fld.name)
            if not accesses:
                continue
            locks = [s for s in src.lock_sites(fn) if s[0] == mu]
            for off in accesses:
                if any(lo <= off < end for _m, lo, end in locks):
                    continue
                out.append(Finding(
                    "lockset", src.name, src.line_of(off),
                    f"{src.name}:{fn.name}:{fld.name}",
                    f"{fn.name} accesses {fld.name} (guarded by {mu}) "
                    f"outside any {mu} lock scope and is not "
                    f"@locked({mu})"))
                break  # one finding per (function, field)
    return out


# -- rule: ladder -------------------------------------------------------------

def check_ladder(model: CppModel) -> list[Finding]:
    out: list[Finding] = []
    gated = {fn.name for fn in model.annotated("admit-gated")}
    checks = {fn.name for fn in model.annotated("admit-check")}
    if not gated or not checks:
        return out
    seen_sites = set()
    for fn in model.functions():
        if fn.name in gated or fn.name in checks:
            continue
        src = model.source_of(fn)
        calls = src.calls(fn)
        check_offs = [off for name, off in calls if name in checks]
        for name, off in calls:
            if name not in gated:
                continue
            site = f"{fn.file}:{fn.name}->{name}"
            if site in seen_sites:
                continue
            if not any(co < off for co in check_offs):
                seen_sites.add(site)
                out.append(Finding(
                    "ladder", fn.file, src.line_of(off), site,
                    f"{fn.name} calls @admit-gated {name} with no "
                    f"@admit-check (ShardAdmit/TrunkEligible/RingRoom) "
                    f"lexically before it — ladder decisions must "
                    f"precede side effects"))
    return out


# -- rule: pyfold -------------------------------------------------------------

def check_pyfold(py: PySource) -> list[Finding]:
    out: list[Finding] = []
    model = py.model
    fname = os.path.basename(py.path)
    scoped = py.scoped_methods()
    for name, meth in scoped.items():
        if name == "__init__":
            continue
        regions_all = py.with_regions(meth.node)
        for attr, lock in model.guarded.items():
            if meth.locked == lock:
                continue
            regions = [(a, b) for w, a, b in regions_all if w == lock]
            for line in py.attr_mentions(meth.node, attr):
                if any(a <= line <= b for a, b in regions):
                    continue
                out.append(Finding(
                    "pyfold", fname, line, f"{fname}:{name}:{attr}",
                    f"{name} touches self.{attr} (guarded by {lock}) "
                    f"outside `with self.{lock}:` and is not "
                    f"@locked({lock})"))
                break
        # calls into @locked helpers must hold their lock
        for callee_name, callee in model.methods.items():
            if callee.locked is None or callee_name == name:
                continue
            if meth.locked == callee.locked:
                continue
            regions = [(a, b) for w, a, b in regions_all
                       if w == callee.locked]
            for line in py.locked_calls(meth.node, callee_name):
                if any(a <= line <= b for a, b in regions):
                    continue
                out.append(Finding(
                    "pyfold", fname, line,
                    f"{fname}:{name}->{callee_name}",
                    f"{name} calls @locked({callee.locked}) helper "
                    f"{callee_name} outside `with self."
                    f"{callee.locked}:`"))
                break
    return out


# -- rule: fault (faultline coverage, round 15) -------------------------------
# The sanitizer-lint pattern applied to fault injection: fault.h's Site
# enum is the canonical catalog, every C++ FIRE site (a line using a
# kSite token together with the firing vocabulary) must carry a
# matching // @fault(<site>) within its preceding 4 lines, every
# declared site needs >= 1 such fire site AND a test that names it, and
# native/__init__.py's FAULT_SITES must mirror the enum exactly. A site
# that exists only on one side — or a chaos lever no test ever pulls —
# fails the build.

_FAULT_TOKEN_RE = re.compile(r"\bkSite([A-Z]\w*)\b")
_FAULT_ANN_RE = re.compile(r"@fault\(([a-z0-9_]+)\)")
# only lines that DECIDE a firing are fire sites; arm/forwarding
# plumbing (FaultArm routing store sites) names kSite tokens too
_FIRE_VOCAB = ("Fire(", "FaultHit(", "FaultRecv(", "FaultSend(",
               "armed(")
_PY_SITES_RE = re.compile(r"FAULT_SITES = \(([^)]*)\)", re.S)

_TESTS_BLOB_CACHE: dict = {}   # key -> (blob, {site: covered} memo)
_PY_SITES_CACHE: dict = {}     # (path, mtime_ns) -> FAULT_SITES list


def _tests_blob(repo: str) -> str:
    # keyed by the directory's (name, mtime, size) signature so a
    # long-lived process (editor integration) sees edits — a stale
    # blob would keep passing a site whose test was deleted
    tdir = os.path.join(repo, "tests")
    names = (sorted(f for f in os.listdir(tdir) if f.endswith(".py"))
             if os.path.isdir(tdir) else [])
    sig = []
    for f in names:
        try:
            st = os.stat(os.path.join(tdir, f))
            sig.append((f, st.st_mtime_ns, st.st_size))
        except OSError:
            pass
    key = (repo, tuple(sig))
    ent = _TESTS_BLOB_CACHE.get(key)
    if ent is None:
        parts = []
        for f in names:
            try:
                with open(os.path.join(tdir, f)) as fh:
                    parts.append(fh.read())
            except OSError:
                pass
        ent = ("\n".join(parts), {})
        _TESTS_BLOB_CACHE.clear()       # one live entry per process
        _TESTS_BLOB_CACHE[key] = ent
    return ent[0]


def check_fault(model: CppModel, repo: str) -> list[Finding]:
    out: list[Finding] = []
    fh = model.sources.get("fault.h")
    if fh is None:
        return [Finding("fault", "fault.h", 1, "fault.h:<missing>",
                        "fault.h is absent — the fault rule has no "
                        "site catalog")]
    sites = [snake(s) for s in enumerators(fh.text, "Site", "kSite")
             if s != "Count"]
    covered: set = set()
    for src in model.sources.values():
        if src.name == "fault.h":
            continue
        raw_lines = src.text.split("\n")
        code_lines = src.code.split("\n")
        for i, cl in enumerate(code_lines):
            toks = [snake(m.group(1))
                    for m in _FAULT_TOKEN_RE.finditer(cl)
                    if m.group(1) != "Count"]
            if not toks or not any(v in cl for v in _FIRE_VOCAB):
                continue
            anns: set = set()
            for back in range(0, 5):
                if i - back < 0:
                    break
                anns.update(_FAULT_ANN_RE.findall(raw_lines[i - back]))
            for name in toks:
                if name in anns:
                    covered.add(name)
                else:
                    out.append(Finding(
                        "fault", src.name, i + 1,
                        f"{src.name}:{i + 1}:{name}",
                        f"fault fire site for {name} lacks a matching "
                        f"// @fault({name}) annotation nearby"))
        # unknown site names in annotations anywhere
        for j, raw in enumerate(raw_lines):
            for name in _FAULT_ANN_RE.findall(raw):
                if name not in sites:
                    out.append(Finding(
                        "fault", src.name, j + 1,
                        f"{src.name}:{j + 1}:@fault({name})",
                        f"@fault({name}) names no fault.h site "
                        f"(valid: {sites})"))
    for s in sites:
        if s not in covered:
            out.append(Finding(
                "fault", "fault.h", 1, f"fault.h:{s}",
                f"fault site {s} is declared but has no annotated C++ "
                f"fire site"))
    blob = _tests_blob(repo)
    # the per-site coverage memo lives WITH its blob in the cache
    # entry, so it can never outlive (or be confused across) blobs
    cover = next(c for b, c in _TESTS_BLOB_CACHE.values() if b is blob)
    for s in sites:
        hit = cover.get(s)
        if hit is None:
            hit = cover[s] = bool(re.search(rf"\b{s}\b", blob))
        if not hit:
            out.append(Finding(
                "fault", "tests", 0, f"tests:{s}",
                f"fault site {s} is never exercised by any test under "
                f"tests/ (name it in an arm/assert)"))
    # Python parity: a site name armable from Python must exist in C++
    # and vice versa, same order (the mechanical STAT_NAMES discipline)
    nat = os.path.join(repo, "emqx_tpu", "native", "__init__.py")
    try:
        key = (nat, os.stat(nat).st_mtime_ns)
        py_sites = _PY_SITES_CACHE.get(key)
        if py_sites is None:
            with open(nat) as f:
                m = _PY_SITES_RE.search(f.read())
            py_sites = (re.findall(r'"([a-z0-9_]+)"', m.group(1))
                        if m else [])
            _PY_SITES_CACHE.clear()
            _PY_SITES_CACHE[key] = py_sites
    except OSError:
        py_sites = []
    if py_sites != sites:
        out.append(Finding(
            "fault", "__init__.py", 0, "native/__init__.py:FAULT_SITES",
            f"native.FAULT_SITES {py_sites} drifted from fault.h Site "
            f"enum {sites}"))
    return out


# -- rule: atomics (memory-order + SPSC + generation handles, round 17) -------
# The lock-free surfaces the Eraser-style lockset rule is blind to:
# every std::atomic field declares its ordering discipline and every
# access site's EXPLICIT memory_order argument is checked against it.
# A bare access (seq_cst silently defaulted — almost always an
# unconsidered ordering, and a fence nobody asked for on the hot path)
# always flags. Two structural legs ride along: the SPSC
# publish/consume shape (data writes lexically precede the index's
# release store) and the wheel/park generation-handle protocol.

_DISCIPLINES = {
    "relaxed": {"load": {"relaxed"}, "store": {"relaxed"},
                "rmw": {"relaxed"}},
    # publish/consume pairing: stores release (relaxed allowed for
    # pre-publication init), loads acquire (relaxed allowed for the
    # owner side's own-index reads — the SPSC shape)
    "acq_rel": {"load": {"acquire", "relaxed"},
                "store": {"release", "relaxed"},
                "rmw": {"acq_rel", "acquire", "release", "relaxed"}},
    "acquire": {"load": {"acquire"}, "store": set(), "rmw": {"acquire"}},
    "release": {"load": {"relaxed"}, "store": {"release"},
                "rmw": {"release"}},
}


def _op_class(op: str) -> str:
    if op == "load":
        return "load"
    if op == "store":
        return "store"
    return "rmw"


def check_atomics(model: CppModel) -> list[Finding]:
    out: list[Finding] = []
    # leg 1: every atomic declaration is annotated with a valid
    # discipline + why
    disc_of: dict[str, tuple[str, str]] = {}   # field -> (disc, file)
    for src in model.sources.values():
        ann_fields = {f.name: f for f in src.fields
                      if "atomic" in f.annotations}
        for name, line in src.atomic_decls():
            fld = ann_fields.get(name)
            if fld is None:
                out.append(Finding(
                    "atomics", src.name, line, f"{src.name}:{name}",
                    f"std::atomic field {name} lacks an "
                    f"@atomic(<discipline>: why) annotation"))
                continue
            arg = fld.annotations["atomic"].arg
            disc, _, why = arg.partition(":")
            disc = disc.strip()
            if disc not in _DISCIPLINES or not why.strip():
                out.append(Finding(
                    "atomics", src.name, fld.line,
                    f"{src.name}:{name}:@atomic",
                    f"@atomic({arg}) on {name}: needs "
                    f"'<relaxed|acquire|release|acq_rel>: why'"))
                continue
            # access sites are matched by NAME across files (that is
            # what lets host.cc's group_->alive hit ring.h's field), so
            # two files declaring the same atomic name under different
            # disciplines would be checked against whichever file was
            # scanned last — make the ambiguity loud instead
            prev = disc_of.get(name)
            if prev is not None and prev[0] != disc:
                out.append(Finding(
                    "atomics", src.name, fld.line,
                    f"{src.name}:{name}:ambiguous",
                    f"atomic field name {name} is declared "
                    f"@atomic({disc}) here but @atomic({prev[0]}) in "
                    f"{prev[1]} — accesses resolve by name, so rename "
                    f"one field or align the disciplines"))
                continue
            disc_of[name] = (disc, src.name)
    # leg 2: every access site uses an explicit in-discipline order
    for src in model.sources.values():
        for name, op, off, orders in src.atomic_accesses(set(disc_of)):
            disc = disc_of[name][0]
            line = src.line_of(off)
            if not orders:
                out.append(Finding(
                    "atomics", src.name, line,
                    f"{src.name}:{line}:{name}",
                    f"bare {name}.{op}() — seq_cst silently defaulted; "
                    f"pass an explicit std::memory_order_* within the "
                    f"declared @atomic({disc}) discipline"))
                continue
            allowed = (_DISCIPLINES[disc][_op_class(op)]
                       | (_DISCIPLINES[disc]["load"]
                          if op.startswith("compare_exchange") else set()))
            for mo in orders:
                if mo not in allowed:
                    out.append(Finding(
                        "atomics", src.name, line,
                        f"{src.name}:{line}:{name}",
                        f"{name}.{op}(memory_order_{mo}) violates the "
                        f"declared @atomic({disc}) discipline "
                        f"(allowed: {sorted(allowed)})"))
                    break
    # leg 3: @published data precedes its index publish lexically
    for src, fld in model.fields_annotated("published"):
        idx = {n.strip() for n in
               re.split(r"[,\s]+", fld.annotations["published"].arg)
               if n.strip()}
        rel_re = re.compile(
            r"\b(" + "|".join(re.escape(n) for n in sorted(idx))
            + r")\s*\.\s*store\s*\(")
        for fn in src.functions:
            for m in rel_re.finditer(src.code, fn.body_start, fn.body_end):
                close = src._match_paren(m.end() - 1)
                if "release" not in _MEMORY_ORDER_RE.findall(
                        src.code[m.end():max(m.end(), close)]):
                    continue
                late = [o for o in src.field_accesses(fn, fld.name)
                        if o > m.start()]
                if late:
                    line = src.line_of(late[0])
                    out.append(Finding(
                        "atomics", src.name, line,
                        f"{src.name}:{fn.name}:{fld.name}",
                        f"{fn.name} touches @published {fld.name} AFTER "
                        f"the release store of {m.group(1)} — data "
                        f"writes must lexically precede the index "
                        f"publish (SPSC contract)"))
                    break
    # leg 4: the generation-handle protocol
    gen_checks = {f.name for f in model.annotated("gen-check")}
    for fn in model.annotated("gen-check"):
        src = model.source_of(fn)
        body = src.body_code(fn)
        if not re.search(r"\bgen\b", body) or ">> 32" not in body:
            out.append(Finding(
                "atomics", fn.file, fn.line, f"{fn.file}:{fn.name}",
                f"@gen-check {fn.name} never compares a generation "
                f"against the handle's high word"))
    for fn in model.annotated("gen-bump"):
        src = model.source_of(fn)
        if not re.search(r"\bgen\s*(?:\+\+|\+=)",
                         src.body_code(fn)):
            out.append(Finding(
                "atomics", fn.file, fn.line, f"{fn.file}:{fn.name}",
                f"@gen-bump {fn.name} never bumps the generation — the "
                f"ABA guard is gone"))
    for fn in model.annotated("gen-checked"):
        src = model.source_of(fn)
        first = next(((n, o) for n, o in src.calls(fn)
                      if model.by_name.get(n)), None)
        if first is None or first[0] not in gen_checks:
            out.append(Finding(
                "atomics", fn.file, fn.line, f"{fn.file}:{fn.name}",
                f"@gen-checked {fn.name} must call a @gen-check "
                f"validator before anything else touches the slot"))
        if not any(model.source_of(f2).name == fn.file
                   for f2 in model.annotated("gen-check")):
            out.append(Finding(
                "atomics", fn.file, fn.line,
                f"{fn.file}:{fn.name}:no-validator",
                f"{fn.file} has @gen-checked consumers but no "
                f"@gen-check validator"))
    # a file with a validator must also have the ABA bump half
    for fn in model.annotated("gen-check"):
        if not any(model.source_of(f2).name == fn.file
                   for f2 in model.annotated("gen-bump")):
            out.append(Finding(
                "atomics", fn.file, fn.line,
                f"{fn.file}:{fn.name}:no-bump",
                f"{fn.file} has a @gen-check validator but no @gen-bump "
                f"recycler — stale handles would never die"))
    ok_callees = gen_checks | {f.name for f in model.annotated("gen-checked")}
    for hsrc, hfld in model.fields_annotated("gen-handle"):
        for src in model.sources.values():
            for fn in src.functions:
                for callee, off in src.call_arg_uses(fn, hfld.name):
                    if callee in ok_callees:
                        continue
                    line = src.line_of(off)
                    out.append(Finding(
                        "atomics", src.name, line,
                        f"{src.name}:{fn.name}:{hfld.name}",
                        f"{fn.name} passes @gen-handle {hfld.name} to "
                        f"{callee}(), which is not a @gen-check/"
                        f"@gen-checked consumer — a stale handle could "
                        f"act on a recycled slot"))
    return out


# -- rule: lock-order ---------------------------------------------------------
# Build the global lock-acquisition graph: C++ lock_guard scopes
# (locks qualified as "<file>:<mutex>") and Python `with self._lock`
# regions, with call-graph propagation in both languages (a lock held
# across a call inherits every lock the callee may transitively take).
# The PR 9 docstring contract — _shared_lock -> _mirror_lock ->
# _durable_lock — becomes the checked LOCK_ORDER config: undeclared
# nesting, stale declared edges, cycles, and self-acquisition of
# non-reentrant locks are findings.

_ORDER_SEP_RE = re.compile(r"\s*<\s*")


def _cpp_transitive_acquires(model: CppModel, fn, memo: dict,
                             stack: set) -> tuple:
    """(locks transitively acquirable from ``fn``, clean). A walk
    truncated by a call cycle through the current stack is NOT clean
    and must never be memoized: the cycle member's partial set would
    poison every later query and silently hide real nesting edges.
    (Top-level results stay complete regardless — every cycle node
    contributes its direct locks at its own frame.)"""
    hit = memo.get(id(fn))
    if hit is not None:
        return hit, True
    if id(fn) in stack:
        return set(), False
    stack.add(id(fn))
    src = model.source_of(fn)
    out = {f"{fn.file}:{m}" for m, _lo, _end in src.lock_sites(fn)}
    clean = True
    for callee, _off in model.call_edges(fn):
        sub, sub_clean = _cpp_transitive_acquires(model, callee, memo,
                                                  stack)
        out |= sub
        clean = clean and sub_clean
    stack.discard(id(fn))
    if clean:
        memo[id(fn)] = out
    return out, clean


def check_lock_order(model: CppModel, py: PySource,
                     lock_order: list) -> list[Finding]:
    out: list[Finding] = []
    # observed edges: (outer, inner) -> (file, line, witness)
    observed: dict[tuple, tuple] = {}

    def note(a, b, file, line, witness):
        observed.setdefault((a, b), (file, line, witness))

    memo: dict = {}
    for fn in model.functions():
        src = model.source_of(fn)
        sites = [(f"{fn.file}:{m}", lo, end)
                 for m, lo, end in src.lock_sites(fn)]
        locked = fn.annotation("locked")
        if locked:
            held = f"{fn.file}:{locked}"
            for inner in _cpp_transitive_acquires(model, fn, memo,
                                                  set())[0]:
                note(held, inner, fn.file, fn.line,
                     f"{fn.name} (@locked)")
        for lname, lo, end in sites:
            for l2, lo2, _e2 in sites:
                if lo < lo2 < end:
                    note(lname, l2, fn.file, src.line_of(lo2), fn.name)
            for callee, off in model.call_edges(fn):
                if lo < off < end:
                    for l2 in _cpp_transitive_acquires(
                            model, callee, memo, set())[0]:
                        note(lname, l2, fn.file, src.line_of(off),
                             f"{fn.name}->{callee.name}")
    pmodel = py.model
    fname = os.path.basename(py.path)
    for name, meth in pmodel.methods.items():
        regs = py.with_regions(meth.node)
        idx = py._index(meth.node)
        if meth.locked:
            for inner in py.transitive_acquires(name):
                note(meth.locked, inner, fname, meth.node.lineno,
                     f"{name} (@locked)")
        for w, a, b in regs:
            for w2, a2, b2 in regs:
                if (a, b) != (a2, b2) and a < a2 and b2 <= b:
                    note(w, w2, fname, a2, name)
            for callee, lines in idx["calls"].items():
                for ln in lines:
                    if a <= ln <= b:
                        for l2 in py.transitive_acquires(callee):
                            note(w, l2, fname, ln, f"{name}->{callee}")
                        break
    # declared edges from the LOCK_ORDER config ("a < b < c" chains)
    declared: dict[tuple, str] = {}
    for ent in lock_order:
        order = str(ent.get("order", ""))
        why = str(ent.get("why", "")).strip()
        locks = _ORDER_SEP_RE.split(order)
        if len(locks) < 2 or not all(locks) or not why:
            out.append(Finding(
                "lock-order", "waivers.py", 0,
                f"waivers.py:{order}",
                f"malformed LOCK_ORDER entry {ent!r}: needs "
                f"'a < b' (optionally chained) and a non-empty why"))
            continue
        for a, b in zip(locks, locks[1:]):
            declared[(a, b)] = why
    # reentrant self-edges are the lock's documented semantics, not
    # nesting; a self-edge on a plain Lock is a guaranteed deadlock
    for (a, b), (file, line, witness) in sorted(observed.items()):
        if a == b:
            bare = b.rsplit(":", 1)[-1]
            if bare in pmodel.rlocks:
                continue
            out.append(Finding(
                "lock-order", file, line, f"{a}<{b}",
                f"{witness} re-acquires non-reentrant {a} while "
                f"holding it — self-deadlock"))
        elif (a, b) not in declared:
            out.append(Finding(
                "lock-order", file, line, f"{a}<{b}",
                f"{witness} acquires {b} while holding {a}: undeclared "
                f"nesting — declare '{a} < {b}' in LOCK_ORDER or "
                f"restructure"))
    for (a, b), why in sorted(declared.items()):
        if (a, b) not in observed:
            out.append(Finding(
                "lock-order", "waivers.py", 0, f"stale:{a}<{b}",
                f"declared lock order '{a} < {b}' is never observed — "
                f"delete the LOCK_ORDER entry"))
    # cycles over observed + declared edges (self-edges handled above)
    graph: dict = {}
    for a, b in list(observed) + list(declared):
        if a != b:
            graph.setdefault(a, set()).add(b)
    state: dict = {}

    def dfs(n, path):
        state[n] = 1
        for nxt in sorted(graph.get(n, ())):
            if state.get(nxt) == 1:
                cyc = path[path.index(nxt):] + [nxt] \
                    if nxt in path else [n, nxt]
                out.append(Finding(
                    "lock-order", "waivers.py", 0,
                    "cycle:" + "<".join(cyc),
                    f"lock-order cycle: {' -> '.join(cyc)} — a "
                    f"deadlock waiting for its interleaving"))
            elif state.get(nxt, 0) == 0:
                dfs(nxt, path + [nxt])
        state[n] = 2

    for n in sorted(graph):
        if state.get(n, 0) == 0:
            dfs(n, [n])
    return out


# -- rule: tap-bound ----------------------------------------------------------
# Every poll-cycle event buffer (@bounded field) takes appends only in
# its @bounded(<buf>) writers, and a writer's first append is lexically
# preceded by a margin check (an if on <buf>.size() whose hit flushes).
# This is the static form of two bugs that each cost a review pass:
# the kind-6 header-seed-after-flush corruption and the kind-10 entry
# that outgrew the whole poll buffer and was dropped silently.

def check_tap_bound(model: CppModel) -> list[Finding]:
    out: list[Finding] = []
    declared: dict[str, str] = {}    # buf -> declaring file
    for src, fld in model.fields_annotated("bounded"):
        if not fld.annotations["bounded"].arg:
            declared[fld.name] = src.name
    writers: dict[str, list] = {}    # buf -> [CppFunction]
    for fn in model.annotated("bounded"):
        buf = fn.annotation("bounded")
        if not buf:
            continue
        if buf not in declared:
            out.append(Finding(
                "tap-bound", fn.file, fn.line,
                f"{fn.file}:{fn.name}:@bounded",
                f"@bounded({buf}) on {fn.name} names no @bounded "
                f"buffer field (declared: {sorted(declared)})"))
            continue
        writers.setdefault(buf, []).append(fn)
        src = model.source_of(fn)
        app_re = re.compile(rf"\b{re.escape(buf)}\s*\.\s*append\s*\(")
        appends = [m.start() for m in
                   app_re.finditer(src.code, fn.body_start, fn.body_end)]
        if not appends:
            out.append(Finding(
                "tap-bound", fn.file, fn.line,
                f"{fn.file}:{fn.name}:no-append",
                f"@bounded({buf}) writer {fn.name} never appends to "
                f"{buf} — dead annotation"))
            continue
        # the margin check: an if condition naming <buf>.size() whose
        # controlled statement flushes, lexically before the first
        # append
        guarded = False
        if_re = re.compile(r"\bif\s*\(")
        for im in if_re.finditer(src.code, fn.body_start, appends[0]):
            close = src._match_paren(im.end() - 1)
            if close < 0 or close > appends[0]:
                continue
            cond = src.code[im.end():close]
            if re.search(rf"\b{re.escape(buf)}\s*\.\s*size\s*\(\s*\)",
                         cond) and ">" in cond \
                    and re.search(r"\bFlush\w*\s*\(",
                                  src.code[close:appends[0]]):
                guarded = True
                break
        if not guarded:
            line = src.line_of(appends[0])
            out.append(Finding(
                "tap-bound", fn.file, line,
                f"{fn.file}:{fn.name}:{buf}",
                f"{fn.name} appends to @bounded {buf} with no "
                f"chunk-or-flush margin check (if on {buf}.size() "
                f"that flushes) lexically before the append — an "
                f"oversized record gets dropped whole by Poll"))
    for buf, file in sorted(declared.items()):
        wfns = {id(f) for f in writers.get(buf, ())}
        app_re = re.compile(rf"\b{re.escape(buf)}\s*\.\s*append\s*\(")
        for src in model.sources.values():
            for m in app_re.finditer(src.code):
                holder = next((f for f in src.functions
                               if f.body_start <= m.start() < f.body_end),
                              None)
                if holder is not None and id(holder) in wfns:
                    continue
                line = src.line_of(m.start())
                hname = holder.name if holder else "<toplevel>"
                out.append(Finding(
                    "tap-bound", src.name, line,
                    f"{src.name}:{hname}:{buf}",
                    f"{hname} appends to @bounded {buf} outside its "
                    f"@bounded({buf}) writer — the margin discipline "
                    f"is bypassed"))
    return out


# -- rule: waivers (hygiene) + assembly ---------------------------------------

def apply_waivers(findings: list, waivers: list) -> Result:
    out: list[Finding] = []
    used = [False] * len(waivers)
    extra: list[Finding] = []
    by_key: dict[str, int] = {}
    for i, w in enumerate(waivers):
        if w.get("rule") not in RULES or not w.get("site") \
                or not str(w.get("why", "")).strip():
            extra.append(Finding(
                "waivers", "waivers.py", 0,
                f"waivers.py:{w.get('rule')}:{w.get('site')}",
                f"malformed waiver {w!r}: needs a known rule, a site, "
                f"and a non-empty why"))
            used[i] = True  # malformed: never matches, already reported
            continue
        by_key[f"{w['rule']}:{w['site']}"] = i
    for f in findings:
        i = by_key.get(f.key)
        if i is not None:
            used[i] = True
            out.append(Finding(f.rule, f.file, f.line, f.site, f.message,
                               waived_by=str(waivers[i]["why"])))
        else:
            out.append(f)
    stale = [w for i, w in enumerate(waivers) if not used[i]]
    return Result(findings=out + extra, stale_waivers=stale)


def run(repo: str, overrides: dict[str, str] | None = None,
        waivers: list | None = None,
        lock_order: list | None = None) -> Result:
    """Analyze the tree (with optional per-file text overrides, keyed
    by basename for C++ sources and by "native_server.py" for the
    Python fold file) and apply waivers. ``lock_order`` overrides the
    declared LOCK_ORDER edges (the mutation self-test's seam)."""
    overrides = overrides or {}
    if waivers is None:
        from .waivers import WAIVERS as waivers
    if lock_order is None:
        from .waivers import LOCK_ORDER as lock_order
    model = build_cpp_model(repo, overrides=overrides)
    py = _cached_py(os.path.join(repo, PY_FOLD_FILE),
                    overrides.get("native_server.py"))
    findings = (check_plane(model) + check_lockset(model)
                + check_ladder(model) + check_pyfold(py)
                + check_fault(model, repo) + check_atomics(model)
                + check_lock_order(model, py, lock_order)
                + check_tap_bound(model))
    return apply_waivers(findings, waivers)
