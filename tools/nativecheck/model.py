"""Shared C++ source model for the native plane's static checks.

No compiler, pure stdlib: the same source-reading discipline
``tests/test_stats_lint.py`` and ``tests/test_native_wire_lint.py``
proved out (parse the sources directly, fail the build on drift) grown
into one reusable model that those lints AND the nativecheck rules
(tools/nativecheck/rules.py) share:

- ``strip()``: comment/string-stripping that PRESERVES offsets (every
  stripped char becomes a space), so a position in the stripped text is
  a position in the raw text and line numbers survive;
- function extraction: every function/method definition with its body
  extent (brace-matched on the stripped text);
- an intra-model call graph (name-based: ``store_->AppendBatch(`` and
  ``trunk::AppendRecord(`` resolve by the trailing identifier, which is
  what a header-only codebase with unique-enough names needs);
- ``lock_guard``/``unique_lock`` acquisition sites with their lexical
  block scope;
- ``// @annotation`` parsing (see ANNOTATION GRAMMAR below) attached to
  the function or field the comment line precedes or trails;
- the enum/wire-comment helpers the two legacy lints used to duplicate.

ANNOTATION GRAMMAR (one per comment, ``//`` comments only):

  // @plane(poll|control|any)   function runs on the poll thread only /
                                must only run before the poll thread
                                starts (or from management threads) /
                                is thread-safe
  // @blocking                  function may block the calling thread
                                (msync, disk open, ...)
  // @guards(mu_)               field: every access must hold ``mu_``
  // @locked(mu_)               function: runs with ``mu_`` held (or
                                with exclusivity equivalent to it —
                                constructors/destructors); callers are
                                checked instead
  // @admit-gated               function has publish side effects that
                                must lexically FOLLOW an admit check
  // @admit-check               function is a ladder admission check
                                (ShardAdmit / RingRoom / TrunkEligible)

Round-17 additions (nativecheck v2 — rules 7-9):

  // @atomic(relaxed: why)      std::atomic field: every load/store/RMW
     @atomic(acq_rel: why)      site must pass an EXPLICIT
     @atomic(acquire: why)      std::memory_order_* argument within the
     @atomic(release: why)      declared discipline (bare seq_cst-
                                defaulted accesses always flag); the
                                why is mandatory — it documents what
                                the ordering protects
  // @published(idx, ...)       field holds data published by release
                                stores of the named index atomics: no
                                access to it may lexically FOLLOW such
                                a store in the same function (the SPSC
                                write-data-then-publish-index shape)
  // @gen-check                 function validates a generation handle
                                (must compare .gen against the handle's
                                high word)
  // @gen-bump                  function recycles a slot (must bump the
                                generation — the ABA guard)
  // @gen-checked               function consumes a raw handle and must
                                call a @gen-check validator FIRST
  // @gen-handle                field holds a generation handle: call
                                uses may only flow into @gen-checked /
                                @gen-check functions
  // @bounded                   field: a poll-cycle event buffer with a
                                margin discipline (needs a writer)
  // @bounded(<buf>)            function: the buffer's writer — every
                                append is preceded by a chunk-or-flush
                                margin check against the buffer cap
"""

from __future__ import annotations

import bisect
import os
import re
from dataclasses import dataclass, field

# C++ keywords and common non-function tokens that precede '(' but
# never name a function definition or a call edge we care about.
_KEYWORDS = frozenset((
    "if", "for", "while", "switch", "return", "sizeof", "catch",
    "alignas", "alignof", "decltype", "static_assert", "static_cast",
    "reinterpret_cast", "const_cast", "dynamic_cast", "new", "delete",
    "throw", "assert", "defined", "noexcept", "typeid", "alignas",
))

_ANNOT_RE = re.compile(
    r"@(plane|guards|blocking|locked|admit-gated|admit-check"
    r"|atomic|published|gen-checked|gen-check|gen-bump|gen-handle"
    r"|bounded)"
    r"(?:\(([^)]*)\))?")

_CLASS_RE = re.compile(r"\b(?:class|struct)\s+([A-Za-z_]\w*)[^;{()]*\{")

_ATOMIC_DECL_RE = re.compile(
    r"\batomic\s*<[^;>]*>\s*([A-Za-z_]\w*)\s*(?:\[[^\]]*\])?\s*[{=;]")

# one atomic load/store/RMW access: field name (possibly indexed), op
_ATOMIC_OPS = ("load", "store", "exchange", "fetch_add", "fetch_sub",
               "fetch_or", "fetch_and", "fetch_xor",
               "compare_exchange_weak", "compare_exchange_strong")
_MEMORY_ORDER_RE = re.compile(r"\bmemory_order_(\w+)")

_LOCK_RE = re.compile(
    r"\b(?:std\s*::\s*)?(?:lock_guard|unique_lock|scoped_lock)\s*"
    r"<[^>;]*>\s*\w+\s*\(\s*([A-Za-z_]\w*)\s*\)")

_CALL_RE = re.compile(r"(?<!\w)(~?[A-Za-z_]\w*)\s*\(")

_FIELD_DECL_RE = re.compile(
    r"\b([A-Za-z_]\w*)\s*(?:\[[^\]]*\])?\s*(?:=[^;]*|\{[^;]*)?;")


def strip(src: str) -> str:
    """Blank out comments and string/char literals, preserving length
    and newlines so offsets/line numbers stay valid."""
    out = list(src)
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c == "/" and i + 1 < n and src[i + 1] == "/":
            j = src.find("\n", i)
            j = n if j < 0 else j
            for k in range(i, j):
                out[k] = " "
            i = j
        elif c == "/" and i + 1 < n and src[i + 1] == "*":
            j = src.find("*/", i + 2)
            j = n - 2 if j < 0 else j
            for k in range(i, j + 2):
                if out[k] != "\n":
                    out[k] = " "
            i = j + 2
        elif c == '"' or c == "'":
            q = c
            j = i + 1
            while j < n:
                if src[j] == "\\":
                    j += 2
                    continue
                if src[j] == q:
                    break
                j += 1
            for k in range(i + 1, min(j, n)):
                if out[k] != "\n":
                    out[k] = " "
            i = j + 1
        else:
            i += 1
    return "".join(out)


@dataclass
class Annotation:
    kind: str           # plane / guards / blocking / locked / ...
    arg: str            # "poll", "mu_", "" ...
    line: int           # 1-based line of the comment


@dataclass
class CppFunction:
    name: str
    file: str           # basename, e.g. "host.cc"
    line: int           # 1-based signature line
    sig_start: int      # offset of the name token
    body_start: int     # offset of '{'
    body_end: int       # offset one past the matching '}'
    annotations: dict = field(default_factory=dict)  # kind -> Annotation
    cls: str = ""       # innermost enclosing class/struct ("" = free)

    def annotation(self, kind: str) -> str | None:
        a = self.annotations.get(kind)
        return a.arg if a is not None else None


@dataclass
class CppField:
    name: str
    file: str
    line: int
    annotations: dict = field(default_factory=dict)


class CppSource:
    """One parsed C++ file: raw text, stripped text, functions, fields,
    annotations, lock sites."""

    def __init__(self, path: str, text: str | None = None):
        self.path = path
        self.name = os.path.basename(path)
        if text is None:
            with open(path) as f:
                text = f.read()
        self.text = text
        self.code = strip(text)
        self._line_starts = [0]
        for i, ch in enumerate(text):
            if ch == "\n":
                self._line_starts.append(i + 1)
        self.functions: list[CppFunction] = []
        self.fields: list[CppField] = []
        self._class_extents: list[tuple[str, int, int]] = []
        self._extract_classes()
        self._extract_functions()
        self._attach_annotations()
        # per-function memos: a CppSource is immutable after
        # construction and cached across runs, so the mutation /
        # load-bearing sweeps (which re-run the rules dozens of times
        # with ONE file overridden) reuse every other file's scans
        self._calls_memo: dict = {}
        self._locks_memo: dict = {}
        self._atomics_memo: dict = {}

    # -- positions -----------------------------------------------------------

    def line_of(self, offset: int) -> int:
        return bisect.bisect_right(self._line_starts, offset)

    def _line_text(self, line: int) -> str:
        start = self._line_starts[line - 1]
        end = (self._line_starts[line] - 1
               if line < len(self._line_starts) else len(self.text))
        return self.text[start:end]

    def _line_code(self, line: int) -> str:
        start = self._line_starts[line - 1]
        end = (self._line_starts[line] - 1
               if line < len(self._line_starts) else len(self.code))
        return self.code[start:end]

    # -- function extraction -------------------------------------------------

    def _match_paren(self, i: int) -> int:
        """Offset one past the ')' matching the '(' at ``i`` (stripped
        text), or -1."""
        depth = 0
        for j in range(i, len(self.code)):
            c = self.code[j]
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
                if depth == 0:
                    return j + 1
        return -1

    def match_brace(self, i: int) -> int:
        """Offset one past the '}' matching the '{' at ``i``."""
        depth = 0
        for j in range(i, len(self.code)):
            c = self.code[j]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    return j + 1
        return len(self.code)

    def _extract_classes(self) -> None:
        """(name, body_start, body_end) for every class/struct
        definition — the call graph resolves same-named methods by
        enclosing-class scope (round 17)."""
        for m in _CLASS_RE.finditer(self.code):
            body_start = self.code.index("{", m.start())
            self._class_extents.append(
                (m.group(1), body_start, self.match_brace(body_start)))

    def class_of(self, offset: int) -> str:
        """Innermost class/struct whose body contains ``offset``."""
        best, best_span = "", None
        for name, a, b in self._class_extents:
            if a <= offset < b and (best_span is None or b - a < best_span):
                best, best_span = name, b - a
        return best

    def _extract_functions(self) -> None:
        code = self.code
        covered_until = 0
        for m in _CALL_RE.finditer(code):
            name = m.group(1)
            if m.start() < covered_until:
                continue  # inside a previous function's body
            if name.lstrip("~") in _KEYWORDS:
                continue
            close = self._match_paren(m.end() - 1)
            if close < 0:
                continue
            # skip qualifiers, then require '{' (or ': init-list ... {')
            j = close
            while True:
                rest = code[j:j + 64]
                m2 = re.match(r"\s*(const|noexcept|override|final)\b", rest)
                if not m2:
                    break
                j += m2.end()
            m3 = re.match(r"\s*(\{|:)", code[j:])
            if not m3:
                continue
            if m3.group(1) == ":":
                # constructor initializer list: scan to the body '{'
                # outside parens; bail on ';' (declaration) or '::'
                k = j + m3.end()
                if code[k:k + 1] == ":":
                    continue  # '::' qualified name, not an init list
                depth = 0
                body = -1
                while k < len(code):
                    c = code[k]
                    if c == "(":
                        depth += 1
                    elif c == ")":
                        depth -= 1
                    elif c == "{" and depth == 0:
                        body = k
                        break
                    elif c == ";" and depth == 0:
                        break
                    k += 1
                if body < 0:
                    continue
                body_start = body
            else:
                body_start = j + m3.end() - 1
            body_end = self.match_brace(body_start)
            fn = CppFunction(
                name=name, file=self.name, line=self.line_of(m.start()),
                sig_start=m.start(), body_start=body_start,
                body_end=body_end, cls=self.class_of(m.start()))
            self.functions.append(fn)
            covered_until = body_end

    # -- annotations ---------------------------------------------------------

    def _attach_annotations(self) -> None:
        fn_by_line = {f.line: f for f in self.functions}
        n_lines = len(self._line_starts)
        field_by_line: dict = {}
        for line in range(1, n_lines + 1):
            raw = self._line_text(line)
            at = raw.find("//")
            if at < 0:
                continue
            anns = [Annotation(kind=k, arg=(a or "").strip(), line=line)
                    for k, a in _ANNOT_RE.findall(raw[at:])]
            if not anns:
                continue
            # attach to the declaration on this line if it has code,
            # else to the next line that has code
            target = line
            while target <= n_lines and not self._line_code(target).strip():
                target += 1
            if target > n_lines:
                continue
            fn = fn_by_line.get(target)
            if fn is None:
                # the annotated signature may span lines; a function
                # whose signature line is within 3 lines below counts
                for probe in range(target, min(target + 3, n_lines) + 1):
                    if probe in fn_by_line:
                        fn = fn_by_line[probe]
                        break
            if fn is not None and fn.line <= target + 3:
                for ann in anns:
                    fn.annotations[ann.kind] = ann
                continue
            fm = _FIELD_DECL_RE.search(self._line_code(target))
            if fm:
                fld = field_by_line.get(target)
                if fld is None:
                    fld = CppField(name=fm.group(1), file=self.name,
                                   line=target)
                    field_by_line[target] = fld
                    self.fields.append(fld)
                for ann in anns:
                    fld.annotations[ann.kind] = ann

    # -- per-function views --------------------------------------------------

    def body_code(self, fn: CppFunction) -> str:
        return self.code[fn.body_start:fn.body_end]

    def calls(self, fn: CppFunction) -> list[tuple[str, int]]:
        """(callee name, absolute offset) for every identifier( token
        in the body, keywords excluded. Callers filter against the
        model's function table."""
        memo = self._calls_memo.get(id(fn))
        if memo is not None:
            return memo
        out = []
        for m in _CALL_RE.finditer(self.code, fn.body_start, fn.body_end):
            name = m.group(1)
            if name in _KEYWORDS:
                continue
            out.append((name, m.start()))
        self._calls_memo[id(fn)] = out
        return out

    def lock_sites(self, fn: CppFunction) -> list[tuple[str, int, int]]:
        """(mutex name, lock offset, scope end offset) per acquisition
        in the body. Scope = the innermost brace block containing the
        lock site (lock_guard lifetime)."""
        memo = self._locks_memo.get(id(fn))
        if memo is not None:
            return memo
        out = []
        for m in _LOCK_RE.finditer(self.code, fn.body_start, fn.body_end):
            scope_end = self._enclosing_block_end(fn, m.start())
            out.append((m.group(1), m.start(), scope_end))
        self._locks_memo[id(fn)] = out
        return out

    def _enclosing_block_end(self, fn: CppFunction, pos: int) -> int:
        """End offset of the innermost { } block of ``fn`` containing
        ``pos``."""
        stack = []
        for j in range(fn.body_start, fn.body_end):
            c = self.code[j]
            if c == "{":
                stack.append(j)
            elif c == "}":
                if stack:
                    start = stack.pop()
                    if start <= pos < j + 1 and j >= pos:
                        return j + 1
        return fn.body_end

    def field_accesses(self, fn: CppFunction, name: str) -> list[int]:
        """Absolute offsets of every ``name`` token in the body."""
        pat = re.compile(rf"\b{re.escape(name)}\b")
        return [m.start()
                for m in pat.finditer(self.code, fn.body_start, fn.body_end)]

    # -- round-17 views (rules 7-9) ------------------------------------------

    def atomic_decls(self) -> list[tuple[str, int]]:
        """(field name, line) of every ``std::atomic<...>`` member
        declaration in this file — the rule-7 catalog is the DECLS,
        not the annotations, so an unannotated atomic is a finding."""
        return [(m.group(1), self.line_of(m.start()))
                for m in _ATOMIC_DECL_RE.finditer(self.code)]

    def atomic_accesses(self, names) -> list[tuple[str, str, int, list]]:
        """(field, op, offset, memory orders) for every load/store/RMW
        site of any field in ``names`` anywhere in this file. Orders
        come from the call's full paren extent (multi-line calls)."""
        if not names:
            return []
        key = tuple(sorted(names))
        memo = self._atomics_memo.get(key)
        if memo is not None:
            return memo
        pat = re.compile(
            r"\b(" + "|".join(re.escape(n) for n in sorted(names)) + r")"
            r"\s*(?:\[[^\]]*\])?\s*\.\s*(" + "|".join(_ATOMIC_OPS)
            + r")\s*\(")
        out = []
        for m in pat.finditer(self.code):
            close = self._match_paren(m.end() - 1)
            args = self.code[m.end():max(m.end(), close - 1)]
            out.append((m.group(1), m.group(2), m.start(),
                        _MEMORY_ORDER_RE.findall(args)))
        self._atomics_memo[key] = out
        return out

    def call_arg_uses(self, fn: CppFunction, name: str) -> list[tuple[str, int]]:
        """(innermost callee, token offset) for every use of ``name``
        inside a call's argument extent within the body — the
        @gen-handle flow check."""
        if name not in self.code[fn.body_start:fn.body_end]:
            return []
        memo = self._atomics_memo.get((id(fn), name))
        if memo is not None:
            return memo
        calls = []
        for cm in _CALL_RE.finditer(self.code, fn.body_start, fn.body_end):
            if cm.group(1) in _KEYWORDS:
                continue
            close = self._match_paren(cm.end() - 1)
            if close > 0:
                calls.append((cm.group(1), cm.end(), close))
        out = []
        for off in self.field_accesses(fn, name):
            inner = None
            for callee, a, b in calls:
                if a <= off < b and (inner is None or b - a < inner[2]):
                    inner = (callee, off, b - a)
            if inner is not None:
                out.append((inner[0], off))
        self._atomics_memo[(id(fn), name)] = out
        return out


# parse cache: the mutation/load-bearing tests re-analyze the tree
# dozens of times with one file overridden — unchanged files reparse
# from here (CppSource is immutable after construction)
_SOURCE_CACHE: dict = {}


def _cached_source(path: str, text: str | None) -> CppSource:
    if text is None:
        with open(path) as f:
            text = f.read()
    key = (path, hash(text))
    src = _SOURCE_CACHE.get(key)
    if src is None or src.text != text:
        src = CppSource(path, text=text)
        _SOURCE_CACHE[key] = src
    return src


class CppModel:
    """The joint model over a set of native sources (host.cc + the
    headers it includes): function table, call graph, annotations."""

    def __init__(self, paths: list[str],
                 overrides: dict[str, str] | None = None):
        overrides = overrides or {}
        self.sources: dict[str, CppSource] = {}
        for p in paths:
            name = os.path.basename(p)
            self.sources[name] = _cached_source(p, overrides.get(name))
        self.by_name: dict[str, list[CppFunction]] = {}
        for src in self.sources.values():
            for fn in src.functions:
                self.by_name.setdefault(fn.name, []).append(fn)

    def source_of(self, fn: CppFunction) -> CppSource:
        return self.sources[fn.file]

    def functions(self):
        for src in self.sources.values():
            yield from src.functions

    def annotated(self, kind: str, arg: str | None = None):
        for fn in self.functions():
            a = fn.annotations.get(kind)
            if a is not None and (arg is None or a.arg == arg):
                yield fn

    def fields_annotated(self, kind: str):
        for src in self.sources.values():
            for fld in src.fields:
                if kind in fld.annotations:
                    yield src, fld

    def call_edges(self, fn: CppFunction):
        """(callee CppFunction, call offset) resolved by name against
        the model's function table. Same-named functions are resolved
        by enclosing-class scope when the call is UNQUALIFIED (or
        ``this->``-qualified) and the caller's class defines the name
        (round 17); qualified calls (``obj->f(``, ``x.f(``, ``Ns::f(``)
        keep the over-approximation — waivers stay the pressure
        valve."""
        src = self.source_of(fn)
        for name, off in src.calls(fn):
            cands = self.by_name.get(name, ())
            if len(cands) > 1 and fn.cls and not self._qualified(src, off):
                same_cls = [c for c in cands
                            if c.file == fn.file and c.cls == fn.cls]
                if same_cls:
                    cands = same_cls
            for callee in cands:
                if callee is fn:
                    continue
                yield callee, off

    @staticmethod
    def _qualified(src: CppSource, off: int) -> bool:
        """True when the call token at ``off`` is reached through an
        object or namespace (``.``/``->``/``::``) other than ``this``."""
        j = off - 1
        while j >= 0 and src.code[j] in " \t\n":
            j -= 1
        if j >= 1 and src.code[j - 1:j + 1] in ("->", "::"):
            return not src.code[:j - 1].rstrip().endswith("this")
        return j >= 0 and src.code[j] == "."


# -- legacy-lint helpers (shared with tests/test_stats_lint.py and
# tests/test_native_wire_lint.py) ---------------------------------------------

def enum_body(src_text: str, name: str) -> str:
    """The body of ``enum <name> { ... };`` with // comments stripped
    (slot docs routinely NAME other slots, which must not count as
    enumerators)."""
    m = re.search(rf"enum {name}\b[^{{]*\{{(.*?)\}};", src_text, re.S)
    if not m:
        raise AssertionError(f"enum {name} not found")
    return re.sub(r"//[^\n]*", "", m.group(1))


def enumerators(src_text: str, enum_name: str, prefix: str) -> list[str]:
    """Enumerator names of ``enum_name`` carrying ``prefix``, with the
    prefix removed (``kSt`` -> ``FastIn`` ...). Sentinel entries whose
    first post-prefix char is lowercase (kStatCount-style) never match
    by construction."""
    return re.findall(rf"\b{prefix}([A-Z]\w*)\b",
                      enum_body(src_text, enum_name))


def snake(camel: str) -> str:
    """kStFooBar's post-prefix CamelCase -> foo_bar (the mechanical
    C++ <-> Python stat/stage name mapping)."""
    return "_".join(p.lower() for p in re.findall(r"[A-Z][a-z0-9]*", camel))


def header_comment_region(src_text: str, marker: str) -> str:
    """The contiguous header-comment region starting at ``marker``
    (stops at the first preprocessor line) — the wire-format contract
    the cross-plane lint parses."""
    start = src_text.index(marker)
    end = src_text.index("#include", start)
    return src_text[start:end]


_WIRE_TOKEN_RE = re.compile(
    r"\[(u8|u16|u32|u64)\s+([A-Za-z_]\w*)(?:\s+x\s+\w+)?\]")
_WIRE_KIND_RE = re.compile(r"kind\s+(\d+)\s*=")


def wire_kind_sections(src_text: str,
                       marker: str = "Event record wire format"
                       ) -> dict[int, str]:
    """kind number -> its slice of the wire-format header comment."""
    text = header_comment_region(src_text, marker)
    marks = [(int(m.group(1)), m.start())
             for m in _WIRE_KIND_RE.finditer(text)]
    out: dict[int, str] = {}
    for i, (kind, at) in enumerate(marks):
        nxt = marks[i + 1][1] if i + 1 < len(marks) else len(text)
        out[kind] = text[at:nxt]
    return out


def wire_tokens(section: str) -> frozenset:
    """The (width, name) field tokens of one wire-comment section
    (sub-kind markers like [u8 1] are excluded by the identifier-start
    requirement)."""
    return frozenset(_WIRE_TOKEN_RE.findall(section))
