"""In-flight window — parity with ``apps/emqx/src/emqx_inflight.erl``
(gb_tree keyed by packet id with a max window, :47-70): the QoS1/2
outbound messages awaiting PUBACK/PUBREC/PUBCOMP."""

from __future__ import annotations

from typing import Any, Iterator, Optional


class Inflight:
    """Ordered insert-time map with a max size (the receive window)."""

    def __init__(self, max_size: int = 32):
        self.max_size = max_size            # 0 = unlimited
        self._d: dict[int, Any] = {}        # insertion-ordered

    def is_full(self) -> bool:
        return self.max_size != 0 and len(self._d) >= self.max_size

    def is_empty(self) -> bool:
        return not self._d

    def contain(self, key: int) -> bool:
        return key in self._d

    def insert(self, key: int, value: Any) -> None:
        if key in self._d:
            raise KeyError(f"packet id {key} already in flight")
        self._d[key] = value

    def update(self, key: int, value: Any) -> None:
        if key not in self._d:
            raise KeyError(key)
        self._d[key] = value

    def delete(self, key: int) -> Optional[Any]:
        return self._d.pop(key, None)

    def lookup(self, key: int) -> Optional[Any]:
        return self._d.get(key)

    def peek_oldest(self) -> Optional[tuple[int, Any]]:
        for k, v in self._d.items():
            return k, v
        return None

    def items(self) -> Iterator[tuple[int, Any]]:
        return iter(list(self._d.items()))

    def values(self):
        return list(self._d.values())

    def __len__(self) -> int:
        return len(self._d)
