"""Persistent sessions — parity with ``apps/emqx/src/persistent_session/``.

The in-memory layer already survives *disconnects* (a disconnected
channel keeps its session until expiry — emqx_channel disconnected
state). This subsystem adds what the reference's opt-in persistence
adds: surviving a *node restart*. Three pieces, mirroring the reference:

- ``SessionRouter``: a dedicated route table + trie for persistent
  sessions (emqx_session_router.erl + the ``*_session`` trie variants,
  emqx_trie.erl:84-106) so ``persist_message`` can cheaply find which
  persistent sessions a publish matches.
- message persistence: every published message matching a persistent
  session's filters is stored once (by GUID) plus one unconsumed marker
  per matching session (emqx_persistent_session.erl:93-109); markers are
  consumed on delivery / resume-replay; GC drops fully-consumed
  messages and expired sessions (emqx_persistent_session_gc.erl).
- resume: a clean_start=false CONNECT with no live channel replays the
  saved subscriptions + pending messages from the store
  (emqx_persistent_session.erl:275-310).

Backends mirror the reference's trio: ``MemStore`` (ram copies),
``NativeDurableStore`` (the restart-surviving tier — session metadata,
messages AND markers all live in the ONE native durable store,
native/src/store.h, the same CRC-framed segments the C++ host appends
below the GIL; the disc/rocksdb slot, kept host-side: SURVEY §5 "the
HBM trie is a pure cache; persistence stays host-side"), and
``DummyStore`` (the null backend,
emqx_persistent_session_backend_dummy.erl).

Round 18 (one recovery path): the JSON ``DiskStore`` op log is GONE —
its ``sessions.log`` is boot-migrated once into the native store's
SESSION/REGISTER/MSG records, so a persistence-enabled broker recovers
everything (sessions, subscriptions, messages, markers, trunk rings)
from one segment walk. Marker consumption moved from delivery-write
time to the SETTLE seam (``Session.settle_fn`` → ``settle``): a conn
that drops after the socket write but before the PUBACK keeps its
marker, and restart resume retransmits the message.
"""

from __future__ import annotations

import base64
import json
import os
import threading
from typing import Any, Optional

from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message, SubOpts, now_ms
from emqx_tpu.router.trie import Trie

# Native store guids map into Python message-id space in their own
# window, so replayed-store copies and live copies of one message dedup
# by id without ever false-matching (the round-10 contract;
# broker/native_server.py re-exports this constant). Broker-minted ids
# (core/message.py guid(): microsecond clock << 16) live far ABOVE this
# window — bits 61+ are always set for them — so membership is the
# exact bit-60-only test below, not a >= compare.
DURABLE_GUID_BASE = 1 << 60


def is_native_msg_id(mid: int) -> bool:
    """True when ``mid`` is a native-store replay id (DURABLE_GUID_BASE
    + guid): bit 60 set, nothing above it."""
    return (mid >> 60) == 1


def msg_to_dict(m: Message) -> dict:
    return {
        "topic": m.topic,
        "payload": base64.b64encode(m.payload).decode(),
        "qos": m.qos,
        "from": m.from_,
        "id": m.id,
        "flags": m.flags,
        "headers": {k: v for k, v in m.headers.items()
                    if isinstance(v, (str, int, float, bool, dict, list))},
        "timestamp": m.timestamp,
    }


def msg_from_dict(d: dict) -> Message:
    return Message(
        topic=d["topic"],
        payload=base64.b64decode(d["payload"]),
        qos=d["qos"],
        from_=d["from"],
        id=d["id"],
        flags=dict(d.get("flags") or {}),
        headers=dict(d.get("headers") or {}),
        timestamp=d["timestamp"],
    )


class SessionRouter:
    """filter → persistent session ids, trie-indexed for publish match."""

    def __init__(self) -> None:
        self._trie = Trie()
        self._routes: dict[str, set[str]] = {}     # filter -> sids
        self._lock = threading.RLock()

    def add_route(self, filt: str, sid: str) -> None:
        with self._lock:
            sids = self._routes.setdefault(filt, set())
            if not sids and T.wildcard(filt):
                self._trie.insert(filt)
            sids.add(sid)

    def delete_route(self, filt: str, sid: str) -> None:
        with self._lock:
            sids = self._routes.get(filt)
            if sids is None:
                return
            sids.discard(sid)
            if not sids:
                del self._routes[filt]
                if T.wildcard(filt):
                    self._trie.delete(filt)

    def match(self, topic: str) -> set[str]:
        return set(self.match_filters(topic))

    def match_filters(self, topic: str) -> dict[str, str]:
        """sid → one matching filter (the sub_topic the replayed message
        is delivered under)."""
        with self._lock:
            out: dict[str, str] = {}
            for filt in [topic, *self._trie.match(topic)]:
                for sid in self._routes.get(filt, ()):
                    out.setdefault(sid, filt)
            return out

    def routes_of(self, sid: str) -> list[str]:
        with self._lock:
            return [f for f, sids in self._routes.items() if sid in sids]

    def is_empty(self) -> bool:
        return not self._routes


class MemStore:
    """RAM backend (mnesia ram_copies analogue) — fast, not restart-safe."""

    persistent = True

    def __init__(self) -> None:
        self.sessions: dict[str, dict] = {}     # sid -> {subs, expiry_ms, ts}
        self.messages: dict[int, dict] = {}     # guid -> msg dict
        self.markers: dict[str, dict[int, str]] = {}  # sid -> {guid: sub_topic}

    def put_session(self, sid: str, record: dict) -> None:
        self.sessions[sid] = record

    def get_session(self, sid: str) -> Optional[dict]:
        return self.sessions.get(sid)

    def delete_session(self, sid: str) -> None:
        self.sessions.pop(sid, None)
        self.markers.pop(sid, None)

    def put_message(self, guid: int, msg: dict) -> None:
        self.messages.setdefault(guid, msg)

    def put_marker(self, sid: str, guid: int, sub_topic: str) -> None:
        self.markers.setdefault(sid, {})[guid] = sub_topic

    def consume_marker(self, sid: str, guid: int) -> None:
        self.markers.get(sid, {}).pop(guid, None)

    def pending(self, sid: str) -> list[tuple[int, str]]:
        return list(self.markers.get(sid, {}).items())

    def gc_messages(self) -> int:
        live = {g for ms in self.markers.values() for g in ms}
        dead = [g for g in self.messages if g not in live]
        for g in dead:
            del self.messages[g]
        return len(dead)

    def all_sessions(self) -> list[tuple[str, dict]]:
        return list(self.sessions.items())

    def close(self) -> None:
        pass


class DummyStore(MemStore):
    """Null backend (emqx_persistent_session_backend_dummy.erl): accepts
    every write, remembers nothing."""

    persistent = False

    def put_session(self, sid: str, record: dict) -> None:
        pass

    def put_message(self, guid: int, msg: dict) -> None:
        pass

    def put_marker(self, sid: str, guid: int, sub_topic: str) -> None:
        pass


class NativeDurableStore(MemStore):
    """The restart-surviving backend over the ONE native durable store
    (native/src/store.h): session metadata rides SESSION records,
    messages + markers ride MSG/CONSUME records under the sid's
    REGISTER token — the exact records the C++ host's durable plane
    appends below the GIL, so boot recovery is one segment walk shared
    with the native server and the trunk replay ring.

    The old JSON ``DiskStore`` op log (``<dir>/sessions/sessions.log``)
    is boot-migrated once into these records, then renamed
    ``.migrated``.
    """

    persistent = True

    def __init__(self, base_dir: str, segment_bytes: int = 4 << 20,
                 fsync: str = "batch", native_store=None) -> None:
        super().__init__()
        self.dir = base_dir
        self._lock = threading.RLock()
        if native_store is None:
            from emqx_tpu import native as _native
            if not _native.available():
                raise RuntimeError(
                    f"native store unavailable: {_native.build_error()}")
            store_dir = os.path.join(base_dir, "store") if base_dir else ""
            if store_dir:
                os.makedirs(store_dir, exist_ok=True)
            native_store = _native.NativeStore(
                store_dir, segment_bytes, fsync)
        self.native = native_store
        # python msg id <-> native guid for THIS process's live copies
        # (after a restart no live copy carries a python id, so the
        # maps start empty by construction); refcounted by surviving
        # markers so they never grow past the pending set
        self._guid_of: dict[int, int] = {}
        self._pyid_of: dict[int, int] = {}
        self._refs: dict[int, int] = {}
        # the boot walk: session catalog out of SESSION records
        for sid, body in self.native.sessions():
            try:
                MemStore.put_session(self, sid, json.loads(body.decode()))
            except (ValueError, UnicodeDecodeError):
                continue
        if base_dir:
            self._migrate(os.path.join(base_dir, "sessions",
                                       "sessions.log"))

    # -- one-time JSON op-log migration -------------------------------------

    def _migrate(self, path: str) -> None:
        """Fold a pre-round-18 DiskStore op log into native records,
        then retire the file (renamed ``.migrated``) — the promised
        one-shot boot migration.

        Crash discipline (review finding): the log is CLAIMED first
        (renamed ``.migrating``) before any append — a kill -9
        mid-migration can therefore duplicate at most ONE crash
        window's worth of appends on the resumed run (at-least-once),
        never re-run the whole migration on every boot (the appends
        mint fresh guids, so re-runs would not dedup)."""
        claimed = path + ".migrating"
        if os.path.exists(path):
            os.replace(path, claimed)
        if not os.path.exists(claimed):
            return
        path = claimed
        sessions: dict[str, dict] = {}
        messages: dict[int, dict] = {}
        markers: dict[str, dict[int, str]] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    op = json.loads(line)
                except ValueError:
                    continue                      # torn tail write
                kind = op.get("op")
                if kind == "sess":
                    sessions[op["sid"]] = op["rec"]
                elif kind == "del_sess":
                    sessions.pop(op["sid"], None)
                    markers.pop(op["sid"], None)
                elif kind == "msg":
                    messages.setdefault(op["guid"], op["m"])
                elif kind == "mark":
                    markers.setdefault(op["sid"], {})[op["guid"]] = op["st"]
                elif kind == "consume":
                    markers.get(op["sid"], {}).pop(op["guid"], None)
        for sid, rec in sessions.items():
            self.put_session(sid, rec)
        by_msg: dict[int, list[str]] = {}
        for sid, marks in markers.items():
            if sid not in sessions:
                continue
            for old_guid in marks:
                by_msg.setdefault(old_guid, []).append(sid)
        for old_guid, sids in by_msg.items():
            d = messages.get(old_guid)
            if d is None:
                continue
            toks = [self.native.register(s) for s in sids]
            self.native.append(
                0, int(d.get("qos", 0) or 0), toks, d["topic"],
                base64.b64decode(d["payload"]),
                dup=bool((d.get("flags") or {}).get("dup")),
                cid=str(d.get("from") or ""))
        self.native.sync()
        os.replace(path, path.replace(".migrating", "") + ".migrated")

    # -- session catalog -----------------------------------------------------

    def put_session(self, sid: str, record: dict) -> None:
        with self._lock:
            MemStore.put_session(self, sid, record)
            self.native.put_session(sid, json.dumps(record).encode())

    def delete_session(self, sid: str) -> None:
        with self._lock:
            MemStore.delete_session(self, sid)
            self.native.delete_session(sid)
            # retire the REGISTER token too (session-expiry GC): the
            # sid→token mapping and any leftover markers must stop
            # pinning segments once the session is gone
            self.native.unregister(sid)

    # -- messages + markers (delegated to the native store) ------------------

    # the id-translation maps are an OPTIMIZATION (takeover dedup +
    # consume-by-python-id); guids consumed through paths this class
    # cannot see (the native server's drain/discard seams) can strand
    # entries, so a hard cap bounds the worst case — losing an entry
    # only means a marker lingers until the next resume drain spends it
    _MAP_CAP = 65536

    def persist(self, msg: Message, sids: list[str]) -> int:
        """One store append covers the message AND every matching
        session's marker (PersistentSessions.persist_message fast
        seam)."""
        with self._lock:
            toks = [self.native.register(s) for s in sids]
            guid = self.native.append(
                0, msg.qos, toks, msg.topic, bytes(msg.payload or b""),
                dup=bool((msg.flags or {}).get("dup")),
                cid=str(msg.from_ or ""))
            if guid:
                if len(self._pyid_of) >= self._MAP_CAP:
                    self._guid_of.clear()
                    self._pyid_of.clear()
                    self._refs.clear()
                self._guid_of[msg.id] = guid
                self._pyid_of[guid] = msg.id
                self._refs[guid] = len(toks)
            return len(toks)

    def pyid_of(self, guid: int):
        """This process's live python id for a native guid (None after
        a restart) — lets replay copies dedup against takeover copies."""
        return self._pyid_of.get(guid)

    def take_pyid(self, guid: int):
        """``pyid_of`` that also RETIRES the translation (the drain
        consumed the guid's marker, so the entry is dead after this
        lookup — review finding: entries pruned any other way leaked or
        broke the takeover dedup)."""
        with self._lock:
            pyid = self._pyid_of.pop(guid, None)
            self._refs.pop(guid, None)
            if pyid is not None:
                self._guid_of.pop(pyid, None)
            return pyid

    def consume_marker(self, sid: str, mid: int) -> None:
        with self._lock:
            tok = self.native.lookup(sid)
            if not tok:
                return
            guid = (mid - DURABLE_GUID_BASE if is_native_msg_id(mid)
                    else self._guid_of.get(mid))
            if not guid:
                return
            if self.native.consume(tok, [guid]):
                refs = self._refs.get(guid)
                if refs is not None:
                    if refs <= 1:
                        self._refs.pop(guid, None)
                        pyid = self._pyid_of.pop(guid, None)
                        if pyid is not None:
                            self._guid_of.pop(pyid, None)
                    else:
                        self._refs[guid] = refs - 1

    def pending(self, sid: str) -> list[tuple[int, str]]:
        # messages live natively; resume replays them through drain()
        # (or the native server's drain seam) instead of this view
        return []

    def drain(self, sid: str) -> list[tuple]:
        """Fetch + consume the sid's whole pending set (restart-resume
        replay). Returns native fetch rows: (guid, origin, ts, qos,
        dup, topic, payload, trace, cid)."""
        with self._lock:
            tok = self.native.lookup(sid)
            if not tok:
                return []
            rows = self.native.fetch(tok)
            if rows:
                self.native.consume(tok, [r[0] for r in rows])
                # NOTE: the id-translation entries for these guids are
                # retired by the caller's take_pyid (it still needs the
                # pyid for the takeover dedup) — never here
            return rows

    def gc_messages(self) -> int:
        return int(self.native.gc())

    def close(self) -> None:
        self.native.close()


class PersistentSessions:
    """The service: hook-wired message persistence + resume/discard/GC.

    ``is_persistent(sid)`` tells whether a live session opted in (MQTT5
    Session-Expiry-Interval > 0 / v3 clean_start=false); the app wires it
    to the CM. Persistence is a *superset* of the in-memory disconnected
    state — resume prefers the live channel (takeover) and only falls
    back to the store after a restart.
    """

    def __init__(self, store=None, is_persistent=None) -> None:
        self.store = store if store is not None else MemStore()
        self.router = SessionRouter()
        self.is_persistent = is_persistent or (lambda sid: True)
        # native durable plane seams (round 10, set by
        # broker/native_server.py when its below-the-GIL store is
        # attached): messages persisted by the C++ host live in ITS
        # store — with a NativeDurableStore backend it is the SAME
        # store, one recovery path. native_drain(sid) -> list[Message]
        # fetches + consumes the native pending set; native_discard(sid)
        # drops it; native_ack(sid, [guid]) spends markers at the
        # settle seam (consume-on-ack, round 18).
        self.native_drain = None
        self.native_discard = None
        self.native_ack = None
        # optional global cap on stored-session expiry (config
        # durable.session_expiry): gc() treats each session's expiry as
        # min(its own, this) when set — the operator's retention bound
        self.session_expiry_cap_ms = 0
        self._lock = threading.RLock()
        # restore session routes from a restart-surviving store
        for sid, rec in self.store.all_sessions():
            for filt in rec.get("subs", {}):
                group, real = T.parse_share(filt)
                if group is None:
                    self.router.add_route(real, sid)

    # -- hook wiring ---------------------------------------------------------

    def attach(self, hooks) -> None:
        # persist after the service layer has had its say (retainer at
        # -100 observes too; we only need to run after delayed's STOP)
        hooks.add("message.publish", self._on_publish, priority=-200)
        hooks.add("session.subscribed", self._on_subscribed)
        hooks.add("session.unsubscribed", self._on_unsubscribed)
        hooks.add("session.discarded", self.discard)
        hooks.add("session.terminated", lambda sid, reason: self.discard(sid))

    def _on_publish(self, msg: Message):
        if not msg.sys:
            self.persist_message(msg)
        return None

    def _on_subscribed(self, sid: str, topic: str, opts: SubOpts,
                       is_new: bool = True) -> None:
        if not self.is_persistent(sid):
            return
        group, real = T.parse_share(topic)
        if group is not None:
            return            # shared subs are not persisted (reference)
        with self._lock:
            self.router.add_route(real, sid)
            rec = self.store.get_session(sid) or {
                "subs": {}, "ts": now_ms()}
            rec["subs"][topic] = opts.__dict__
            self.store.put_session(sid, rec)

    def _on_unsubscribed(self, sid: str, topic: str) -> None:
        group, real = T.parse_share(topic)
        if group is not None:
            return
        with self._lock:
            self.router.delete_route(real, sid)
            rec = self.store.get_session(sid)
            if rec is not None and topic in rec.get("subs", {}):
                del rec["subs"][topic]
                self.store.put_session(sid, rec)

    # -- persistence ---------------------------------------------------------

    def persist_message(self, msg: Message) -> int:
        """Store msg + one marker per matching persistent session
        (emqx_persistent_session:persist_message). Returns marker count."""
        with self._lock:
            sids = self.router.match_filters(msg.topic)
            if not sids:
                return 0
            if hasattr(self.store, "persist"):
                # native-backed store: ONE append covers the message
                # and every marker (the kRecMsgBatch multi-token shape)
                return self.store.persist(msg, list(sids))
            d = msg_to_dict(msg)
            self.store.put_message(msg.id, d)
            n = 0
            for sid, filt in sids.items():
                self.store.put_marker(sid, msg.id, filt)
                n += 1
            return n

    def settle(self, sid: str, mid) -> None:
        """A delivery SETTLED (subscriber ack / effective-qos0 write /
        final drop): spend its replay marker now — never at
        delivery-write time, so a conn death between the socket write
        and the ack keeps the marker and restart resume retransmits
        (``Session.settle_fn`` wires here via the CM)."""
        if not isinstance(mid, int) or mid <= 0:
            return
        if is_native_msg_id(mid) and self.native_ack is not None:
            # a native-plane guid with the native server attached: its
            # consume seam owns the token bookkeeping
            self.native_ack(sid, [mid - DURABLE_GUID_BASE])
            return
        with self._lock:
            self.store.consume_marker(sid, mid)

    def mark_delivered(self, sid: str, msg_ids: list[int]) -> None:
        """Legacy delivery-time consumption (pre-settle-seam callers
        and tests): spends markers immediately."""
        with self._lock:
            for mid in msg_ids:
                self.store.consume_marker(sid, mid)

    # -- resume / discard ----------------------------------------------------

    def lookup(self, sid: str) -> Optional[dict]:
        return self.store.get_session(sid)

    def resume(self, sid: str) -> tuple[dict[str, SubOpts], list[Message]]:
        """Returns (saved subscriptions, pending messages) and consumes
        the replayed markers (emqx_persistent_session:resume)."""
        with self._lock:
            rec = self.store.get_session(sid)
            subs: dict[str, SubOpts] = {}
            if rec is not None:
                for topic, od in rec.get("subs", {}).items():
                    subs[topic] = SubOpts(**od)
                if rec.get("disconnected_at") is not None:
                    rec.pop("disconnected_at", None)
                    self.store.put_session(sid, rec)
            out: list[Message] = []
            for guid, sub_topic in sorted(self.store.pending(sid)):
                d = self.store.messages.get(guid)
                if d is not None:
                    m = msg_from_dict(d)
                    if not m.is_expired():
                        # deliver under the matched filter so the session
                        # can find its SubOpts (the takeover sub_topic hdr)
                        out.append(m.set_header("sub_topic", sub_topic))
                self.store.consume_marker(sid, guid)
            if self.native_drain is not None:
                # messages the C++ host persisted below the GIL: merge
                # them in (dedup by id — a takeover may already hold a
                # live-dispatched copy in the session mqueue)
                seen = {m.id for m in out}
                out.extend(m for m in self.native_drain(sid)
                           if m.id not in seen)
            elif hasattr(self.store, "drain"):
                # native-backed store WITHOUT a native server (asyncio
                # broker on the one recovery path): drain the store's
                # pending set directly
                seen = {m.id for m in out}
                for row in self.store.drain(sid):
                    m = self._native_row_msg(sid, row)
                    if m.id not in seen:
                        out.append(m)
            out.sort(key=lambda m: m.timestamp)
            return subs, out

    def _native_row_msg(self, sid: str, row: tuple) -> Message:
        """One native fetch row -> a deliverable Message: ids translate
        back to this process's python id when the copy is live (takeover
        dedup), else map into the disjoint DURABLE_GUID_BASE space."""
        guid, _origin, ts, qos, dup, topic, body, _trace, cid = row
        pyid = None
        if hasattr(self.store, "take_pyid"):
            # destructive: the drain already consumed this guid's
            # marker, so the translation retires with this lookup
            pyid = self.store.take_pyid(guid)
        filt = self.router.match_filters(topic).get(sid, topic)
        return Message(
            topic=topic, payload=body, qos=qos,
            from_=cid or "$durable",
            id=pyid if pyid is not None else DURABLE_GUID_BASE + guid,
            flags={"retain": False, "dup": dup},
            headers={"properties": {}, "protocol": "mqtt",
                     "sub_topic": filt},
            timestamp=ts,
        )

    def discard(self, sid: str, *args) -> None:
        with self._lock:
            for filt in self.router.routes_of(sid):
                self.router.delete_route(filt, sid)
            self.store.delete_session(sid)
            if self.native_discard is not None:
                self.native_discard(sid)

    # -- GC (emqx_persistent_session_gc.erl) ---------------------------------

    def gc(self, now: Optional[int] = None) -> int:
        """Drop expired sessions, then messages with no live markers."""
        with self._lock:
            now = now_ms() if now is None else now
            cap = self.session_expiry_cap_ms
            for sid, rec in list(self.store.all_sessions()):
                exp = rec.get("expiry_ms")
                if exp and cap:
                    exp = min(exp, cap)
                if exp and rec.get("disconnected_at") and \
                        now - rec["disconnected_at"] >= exp:
                    self.discard(sid)
            return self.store.gc_messages()

    def note_disconnected(self, sid: str, expiry_ms: int,
                          now: Optional[int] = None) -> None:
        with self._lock:
            rec = self.store.get_session(sid)
            if rec is not None:
                rec["disconnected_at"] = now_ms() if now is None else now
                rec["expiry_ms"] = expiry_ms
                self.store.put_session(sid, rec)

    def note_connected(self, sid: str) -> None:
        """Reconnect cancels the expiry clock — otherwise gc() would
        discard the stored session of a live client once the *old*
        disconnect timestamp ages past the expiry interval."""
        with self._lock:
            rec = self.store.get_session(sid)
            if rec is not None and rec.get("disconnected_at") is not None:
                rec.pop("disconnected_at", None)
                self.store.put_session(sid, rec)
