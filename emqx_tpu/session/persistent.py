"""Persistent sessions — parity with ``apps/emqx/src/persistent_session/``.

The in-memory layer already survives *disconnects* (a disconnected
channel keeps its session until expiry — emqx_channel disconnected
state). This subsystem adds what the reference's opt-in persistence
adds: surviving a *node restart*. Three pieces, mirroring the reference:

- ``SessionRouter``: a dedicated route table + trie for persistent
  sessions (emqx_session_router.erl + the ``*_session`` trie variants,
  emqx_trie.erl:84-106) so ``persist_message`` can cheaply find which
  persistent sessions a publish matches.
- message persistence: every published message matching a persistent
  session's filters is stored once (by GUID) plus one unconsumed marker
  per matching session (emqx_persistent_session.erl:93-109); markers are
  consumed on delivery / resume-replay; GC drops fully-consumed
  messages and expired sessions (emqx_persistent_session_gc.erl).
- resume: a clean_start=false CONNECT with no live channel replays the
  saved subscriptions + pending messages from the store
  (emqx_persistent_session.erl:275-310).

Backends mirror the reference's trio: ``MemStore`` (ram copies),
``DiskStore`` (append-only op log + compaction — the disc/rocksdb slot,
kept host-side: SURVEY §5 "the HBM trie is a pure cache; persistence
stays host-side"), and ``DummyStore`` (the null backend,
emqx_persistent_session_backend_dummy.erl).
"""

from __future__ import annotations

import base64
import json
import os
import threading
from typing import Any, Optional

from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message, SubOpts, now_ms
from emqx_tpu.router.trie import Trie


def msg_to_dict(m: Message) -> dict:
    return {
        "topic": m.topic,
        "payload": base64.b64encode(m.payload).decode(),
        "qos": m.qos,
        "from": m.from_,
        "id": m.id,
        "flags": m.flags,
        "headers": {k: v for k, v in m.headers.items()
                    if isinstance(v, (str, int, float, bool, dict, list))},
        "timestamp": m.timestamp,
    }


def msg_from_dict(d: dict) -> Message:
    return Message(
        topic=d["topic"],
        payload=base64.b64decode(d["payload"]),
        qos=d["qos"],
        from_=d["from"],
        id=d["id"],
        flags=dict(d.get("flags") or {}),
        headers=dict(d.get("headers") or {}),
        timestamp=d["timestamp"],
    )


class SessionRouter:
    """filter → persistent session ids, trie-indexed for publish match."""

    def __init__(self) -> None:
        self._trie = Trie()
        self._routes: dict[str, set[str]] = {}     # filter -> sids
        self._lock = threading.RLock()

    def add_route(self, filt: str, sid: str) -> None:
        with self._lock:
            sids = self._routes.setdefault(filt, set())
            if not sids and T.wildcard(filt):
                self._trie.insert(filt)
            sids.add(sid)

    def delete_route(self, filt: str, sid: str) -> None:
        with self._lock:
            sids = self._routes.get(filt)
            if sids is None:
                return
            sids.discard(sid)
            if not sids:
                del self._routes[filt]
                if T.wildcard(filt):
                    self._trie.delete(filt)

    def match(self, topic: str) -> set[str]:
        return set(self.match_filters(topic))

    def match_filters(self, topic: str) -> dict[str, str]:
        """sid → one matching filter (the sub_topic the replayed message
        is delivered under)."""
        with self._lock:
            out: dict[str, str] = {}
            for filt in [topic, *self._trie.match(topic)]:
                for sid in self._routes.get(filt, ()):
                    out.setdefault(sid, filt)
            return out

    def routes_of(self, sid: str) -> list[str]:
        with self._lock:
            return [f for f, sids in self._routes.items() if sid in sids]

    def is_empty(self) -> bool:
        return not self._routes


class MemStore:
    """RAM backend (mnesia ram_copies analogue) — fast, not restart-safe."""

    persistent = True

    def __init__(self) -> None:
        self.sessions: dict[str, dict] = {}     # sid -> {subs, expiry_ms, ts}
        self.messages: dict[int, dict] = {}     # guid -> msg dict
        self.markers: dict[str, dict[int, str]] = {}  # sid -> {guid: sub_topic}

    def put_session(self, sid: str, record: dict) -> None:
        self.sessions[sid] = record

    def get_session(self, sid: str) -> Optional[dict]:
        return self.sessions.get(sid)

    def delete_session(self, sid: str) -> None:
        self.sessions.pop(sid, None)
        self.markers.pop(sid, None)

    def put_message(self, guid: int, msg: dict) -> None:
        self.messages.setdefault(guid, msg)

    def put_marker(self, sid: str, guid: int, sub_topic: str) -> None:
        self.markers.setdefault(sid, {})[guid] = sub_topic

    def consume_marker(self, sid: str, guid: int) -> None:
        self.markers.get(sid, {}).pop(guid, None)

    def pending(self, sid: str) -> list[tuple[int, str]]:
        return list(self.markers.get(sid, {}).items())

    def gc_messages(self) -> int:
        live = {g for ms in self.markers.values() for g in ms}
        dead = [g for g in self.messages if g not in live]
        for g in dead:
            del self.messages[g]
        return len(dead)

    def all_sessions(self) -> list[tuple[str, dict]]:
        return list(self.sessions.items())

    def close(self) -> None:
        pass


class DummyStore(MemStore):
    """Null backend (emqx_persistent_session_backend_dummy.erl): accepts
    every write, remembers nothing."""

    persistent = False

    def put_session(self, sid: str, record: dict) -> None:
        pass

    def put_message(self, guid: int, msg: dict) -> None:
        pass

    def put_marker(self, sid: str, guid: int, sub_topic: str) -> None:
        pass


class DiskStore(MemStore):
    """Append-only JSON op log + in-memory index; compacts when the log
    grows past ``compact_every`` ops. Restart-safe."""

    def __init__(self, dir: str, compact_every: int = 10_000) -> None:
        super().__init__()
        self.dir = dir
        self.compact_every = compact_every
        self._ops = 0
        self._lock = threading.RLock()
        os.makedirs(dir, exist_ok=True)
        self._path = os.path.join(dir, "sessions.log")
        self._replay()
        self._f = open(self._path, "a")

    def _replay(self) -> None:
        try:
            with open(self._path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        op = json.loads(line)
                    except ValueError:
                        continue                  # torn tail write
                    self._apply(op)
                    self._ops += 1
        except FileNotFoundError:
            pass

    def _apply(self, op: dict) -> None:
        kind = op["op"]
        if kind == "sess":
            MemStore.put_session(self, op["sid"], op["rec"])
        elif kind == "del_sess":
            MemStore.delete_session(self, op["sid"])
        elif kind == "msg":
            MemStore.put_message(self, op["guid"], op["m"])
        elif kind == "mark":
            MemStore.put_marker(self, op["sid"], op["guid"], op["st"])
        elif kind == "consume":
            MemStore.consume_marker(self, op["sid"], op["guid"])

    def _log(self, op: dict) -> None:
        with self._lock:
            self._f.write(json.dumps(op) + "\n")
            self._f.flush()
            self._ops += 1
            if self._ops >= self.compact_every:
                self._compact()

    def _compact(self) -> None:
        """Rewrite the log as the current state (drops consumed churn)."""
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            for sid, rec in self.sessions.items():
                f.write(json.dumps({"op": "sess", "sid": sid, "rec": rec}) + "\n")
            live = {g for ms in self.markers.values() for g in ms}
            for guid, m in self.messages.items():
                if guid in live:
                    f.write(json.dumps({"op": "msg", "guid": guid, "m": m}) + "\n")
            for sid, ms in self.markers.items():
                for guid, st in ms.items():
                    f.write(json.dumps(
                        {"op": "mark", "sid": sid, "guid": guid, "st": st}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self._path)
        self._f = open(self._path, "a")
        self._ops = len(self.sessions) + len(self.messages) + sum(
            len(m) for m in self.markers.values())

    def put_session(self, sid: str, record: dict) -> None:
        MemStore.put_session(self, sid, record)
        self._log({"op": "sess", "sid": sid, "rec": record})

    def delete_session(self, sid: str) -> None:
        MemStore.delete_session(self, sid)
        self._log({"op": "del_sess", "sid": sid})

    def put_message(self, guid: int, msg: dict) -> None:
        if guid not in self.messages:
            MemStore.put_message(self, guid, msg)
            self._log({"op": "msg", "guid": guid, "m": msg})

    def put_marker(self, sid: str, guid: int, sub_topic: str) -> None:
        MemStore.put_marker(self, sid, guid, sub_topic)
        self._log({"op": "mark", "sid": sid, "guid": guid, "st": sub_topic})

    def consume_marker(self, sid: str, guid: int) -> None:
        if guid in self.markers.get(sid, {}):
            MemStore.consume_marker(self, sid, guid)
            self._log({"op": "consume", "sid": sid, "guid": guid})

    def gc_messages(self) -> int:
        with self._lock:
            n = MemStore.gc_messages(self)
            if n:
                self._compact()
            return n

    def close(self) -> None:
        self._f.close()


class PersistentSessions:
    """The service: hook-wired message persistence + resume/discard/GC.

    ``is_persistent(sid)`` tells whether a live session opted in (MQTT5
    Session-Expiry-Interval > 0 / v3 clean_start=false); the app wires it
    to the CM. Persistence is a *superset* of the in-memory disconnected
    state — resume prefers the live channel (takeover) and only falls
    back to the store after a restart.
    """

    def __init__(self, store=None, is_persistent=None) -> None:
        self.store = store if store is not None else MemStore()
        self.router = SessionRouter()
        self.is_persistent = is_persistent or (lambda sid: True)
        # native durable plane seams (round 10, set by
        # broker/native_server.py when its below-the-GIL store is
        # attached): messages persisted by the C++ host live in ITS
        # store, not this one — resume merges both, discard drops both.
        # native_drain(sid) -> list[Message] fetches + consumes the
        # native pending set; native_discard(sid) drops it.
        self.native_drain = None
        self.native_discard = None
        # optional global cap on stored-session expiry (config
        # durable.session_expiry): gc() treats each session's expiry as
        # min(its own, this) when set — the operator's retention bound
        self.session_expiry_cap_ms = 0
        self._lock = threading.RLock()
        # restore session routes from a restart-surviving store
        for sid, rec in self.store.all_sessions():
            for filt in rec.get("subs", {}):
                group, real = T.parse_share(filt)
                if group is None:
                    self.router.add_route(real, sid)

    # -- hook wiring ---------------------------------------------------------

    def attach(self, hooks) -> None:
        # persist after the service layer has had its say (retainer at
        # -100 observes too; we only need to run after delayed's STOP)
        hooks.add("message.publish", self._on_publish, priority=-200)
        hooks.add("session.subscribed", self._on_subscribed)
        hooks.add("session.unsubscribed", self._on_unsubscribed)
        hooks.add("session.discarded", self.discard)
        hooks.add("session.terminated", lambda sid, reason: self.discard(sid))

    def _on_publish(self, msg: Message):
        if not msg.sys:
            self.persist_message(msg)
        return None

    def _on_subscribed(self, sid: str, topic: str, opts: SubOpts,
                       is_new: bool = True) -> None:
        if not self.is_persistent(sid):
            return
        group, real = T.parse_share(topic)
        if group is not None:
            return            # shared subs are not persisted (reference)
        with self._lock:
            self.router.add_route(real, sid)
            rec = self.store.get_session(sid) or {
                "subs": {}, "ts": now_ms()}
            rec["subs"][topic] = opts.__dict__
            self.store.put_session(sid, rec)

    def _on_unsubscribed(self, sid: str, topic: str) -> None:
        group, real = T.parse_share(topic)
        if group is not None:
            return
        with self._lock:
            self.router.delete_route(real, sid)
            rec = self.store.get_session(sid)
            if rec is not None and topic in rec.get("subs", {}):
                del rec["subs"][topic]
                self.store.put_session(sid, rec)

    # -- persistence ---------------------------------------------------------

    def persist_message(self, msg: Message) -> int:
        """Store msg + one marker per matching persistent session
        (emqx_persistent_session:persist_message). Returns marker count."""
        with self._lock:
            sids = self.router.match_filters(msg.topic)
            if not sids:
                return 0
            d = msg_to_dict(msg)
            self.store.put_message(msg.id, d)
            n = 0
            for sid, filt in sids.items():
                self.store.put_marker(sid, msg.id, filt)
                n += 1
            return n

    def mark_delivered(self, sid: str, msg_ids: list[int]) -> None:
        """Connected-path consumption: the message reached the session's
        window, so its replay marker is spent."""
        with self._lock:
            for mid in msg_ids:
                self.store.consume_marker(sid, mid)

    # -- resume / discard ----------------------------------------------------

    def lookup(self, sid: str) -> Optional[dict]:
        return self.store.get_session(sid)

    def resume(self, sid: str) -> tuple[dict[str, SubOpts], list[Message]]:
        """Returns (saved subscriptions, pending messages) and consumes
        the replayed markers (emqx_persistent_session:resume)."""
        with self._lock:
            rec = self.store.get_session(sid)
            subs: dict[str, SubOpts] = {}
            if rec is not None:
                for topic, od in rec.get("subs", {}).items():
                    subs[topic] = SubOpts(**od)
                if rec.get("disconnected_at") is not None:
                    rec.pop("disconnected_at", None)
                    self.store.put_session(sid, rec)
            out: list[Message] = []
            for guid, sub_topic in sorted(self.store.pending(sid)):
                d = self.store.messages.get(guid)
                if d is not None:
                    m = msg_from_dict(d)
                    if not m.is_expired():
                        # deliver under the matched filter so the session
                        # can find its SubOpts (the takeover sub_topic hdr)
                        out.append(m.set_header("sub_topic", sub_topic))
                self.store.consume_marker(sid, guid)
            if self.native_drain is not None:
                # messages the C++ host persisted below the GIL: merge
                # them in (dedup by id — a takeover may already hold a
                # live-dispatched copy in the session mqueue)
                seen = {m.id for m in out}
                out.extend(m for m in self.native_drain(sid)
                           if m.id not in seen)
            out.sort(key=lambda m: m.timestamp)
            return subs, out

    def discard(self, sid: str, *args) -> None:
        with self._lock:
            for filt in self.router.routes_of(sid):
                self.router.delete_route(filt, sid)
            self.store.delete_session(sid)
            if self.native_discard is not None:
                self.native_discard(sid)

    # -- GC (emqx_persistent_session_gc.erl) ---------------------------------

    def gc(self, now: Optional[int] = None) -> int:
        """Drop expired sessions, then messages with no live markers."""
        with self._lock:
            now = now_ms() if now is None else now
            cap = self.session_expiry_cap_ms
            for sid, rec in list(self.store.all_sessions()):
                exp = rec.get("expiry_ms")
                if exp and cap:
                    exp = min(exp, cap)
                if exp and rec.get("disconnected_at") and \
                        now - rec["disconnected_at"] >= exp:
                    self.discard(sid)
            return self.store.gc_messages()

    def note_disconnected(self, sid: str, expiry_ms: int,
                          now: Optional[int] = None) -> None:
        with self._lock:
            rec = self.store.get_session(sid)
            if rec is not None:
                rec["disconnected_at"] = now_ms() if now is None else now
                rec["expiry_ms"] = expiry_ms
                self.store.put_session(sid, rec)

    def note_connected(self, sid: str) -> None:
        """Reconnect cancels the expiry clock — otherwise gc() would
        discard the stored session of a live client once the *old*
        disconnect timestamp ages past the expiry interval."""
        with self._lock:
            rec = self.store.get_session(sid)
            if rec is not None and rec.get("disconnected_at") is not None:
                rec.pop("disconnected_at", None)
                self.store.put_session(sid, rec)
