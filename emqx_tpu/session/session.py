"""Session state machine — parity with ``apps/emqx/src/emqx_session.erl``.

Holds the per-client messaging state: subscriptions, the QoS1/2 outbound
inflight window, the backlog mqueue, and the incoming-QoS2 awaiting_rel
set (emqx_session.erl:108-146). Pure state + explicit clock: methods
return the packets to emit, the connection layer does IO — the same
separation as channel/session in the reference.

Reference behaviors implemented:
- deliver with inflight backpressure → mqueue (:542-589)
- enqueue with drop policy (:594-607)
- incoming QoS2 dedup via awaiting_rel + receive-maximum quota (:379-399)
- puback/pubrec/pubrel/pubcomp lifecycle (:432-530)
- retry (redeliver with dup) and await_rel expiry timers
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional

from emqx_tpu.core.message import Message, SubOpts, now_ms
from emqx_tpu.mqtt import packet as P
from emqx_tpu.session.inflight import Inflight
from emqx_tpu.session.mqueue import MQueue, MQueueOpts


@dataclass
class InflightEntry:
    packet_id: int
    msg: Message
    phase: str            # "publish" (await PUBACK/PUBREC) | "pubrel" (await PUBCOMP)
    sent_at: int
    qos: int
    subopts: "SubOpts" = None  # as-delivered opts (subid/rap survive retry)
    # the delivered message's broker id, kept past pubrec's msg=None
    # drop so the PUBCOMP settle (store marker consume-on-ack) can
    # still name it (round 18)
    msg_id: int = 0


class SessionError(Exception):
    def __init__(self, rc: int):
        super().__init__(f"rc=0x{rc:02x}")
        self.rc = rc


@dataclass
class Session:
    clientid: str
    clean_start: bool = True
    max_inflight: int = 32
    max_awaiting_rel: int = 100
    retry_interval_ms: int = 30_000
    await_rel_timeout_ms: int = 300_000
    session_expiry_ms: int = 0          # 0 = ends with connection
    max_subscriptions: int = 0          # 0 = unlimited
    upgrade_qos: bool = False
    mqueue_opts: MQueueOpts = field(default_factory=MQueueOpts)
    created_at: int = field(default_factory=now_ms)

    def __post_init__(self) -> None:
        self.subscriptions: dict[str, SubOpts] = {}
        self.inflight = Inflight(self.max_inflight)
        self.mqueue = MQueue(self.mqueue_opts)
        self.awaiting_rel: dict[int, int] = {}     # packet_id -> ts
        self._next_pkt_id = 0
        # delivery-settlement observer (round 18, the one-recovery-path
        # contract): called with a message id when that delivery will
        # never need a store replay again — the subscriber ACKED it
        # (PUBACK/PUBCOMP), it went out at effective qos0 (no ack
        # exists), or it was dropped for good (no-local, expiry, late
        # unsubscribe, mqueue overflow). The persistence layer consumes
        # its replay marker HERE, not at delivery-write time: a conn
        # that drops after the socket write but before the ack keeps
        # its marker, so restart resume retransmits the message.
        self.settle_fn = None
        # native ack-plane mirror (broker/native_server.py): the C++
        # host owns the window state for pids >= 32768 and reports ONE
        # batched ack record per poll cycle; these gauges are that
        # record's session-side reflection (surfaced by info())
        self.native_inflight = 0      # native window occupancy, last cycle
        self.native_pending = 0       # native mqueue-analogue depth
        self.native_acked = 0         # cumulative natively-freed slots

    # -- packet ids --------------------------------------------------------

    # Outbound packet ids stay in [1, 32767]: the native host's fast
    # path allocates [32768, 65535] on the same wire connection
    # (native/src/host.cc kNativePidBase), so a subscriber's PUBACK
    # routes unambiguously — high pids consumed in C++, low pids here.
    # 32767 concurrent unacked deliveries is far beyond any receive-max.
    PKT_ID_SPACE = 32767

    def next_packet_id(self) -> int:
        for _ in range(self.PKT_ID_SPACE):
            self._next_pkt_id = self._next_pkt_id % self.PKT_ID_SPACE + 1
            if not self.inflight.contain(self._next_pkt_id):
                return self._next_pkt_id
        raise SessionError(P.RC_RECEIVE_MAXIMUM_EXCEEDED)

    # -- subscriptions (the broker layer mirrors these into the router) ----

    def subscribe(self, topic: str, opts: SubOpts) -> None:
        if (
            self.max_subscriptions
            and topic not in self.subscriptions
            and len(self.subscriptions) >= self.max_subscriptions
        ):
            raise SessionError(P.RC_QUOTA_EXCEEDED)
        self.subscriptions[topic] = opts

    def unsubscribe(self, topic: str) -> SubOpts:
        if topic not in self.subscriptions:
            raise SessionError(P.RC_NO_SUBSCRIPTION_EXISTED)
        return self.subscriptions.pop(topic)

    # -- incoming publish (client → broker), QoS2 dedup --------------------

    def publish_in(self, packet_id: Optional[int], msg: Message,
                   now: Optional[int] = None) -> None:
        """Track incoming QoS2 for exactly-once (emqx_session.erl:379-399).
        Raises SessionError on dup packet id or quota exceeded."""
        if msg.qos != 2:
            return
        now = now_ms() if now is None else now
        if packet_id in self.awaiting_rel:
            raise SessionError(P.RC_PACKET_IDENTIFIER_IN_USE)
        if (
            self.max_awaiting_rel
            and len(self.awaiting_rel) >= self.max_awaiting_rel
        ):
            raise SessionError(P.RC_RECEIVE_MAXIMUM_EXCEEDED)
        self.awaiting_rel[packet_id] = now

    def pubrel_in(self, packet_id: int) -> None:
        """Incoming PUBREL completes the QoS2 receive (:478-492)."""
        if packet_id not in self.awaiting_rel:
            raise SessionError(P.RC_PACKET_IDENTIFIER_NOT_FOUND)
        del self.awaiting_rel[packet_id]

    # -- outbound delivery (broker → client) -------------------------------

    def _settle(self, msg_id) -> None:
        """This delivery will never need a store replay again: tell the
        persistence layer to spend its marker (no-op when unwired or
        the message was never persisted)."""
        if self.settle_fn is not None and msg_id:
            self.settle_fn(msg_id)

    def deliver(self, deliveries: list[tuple[str, Message]],
                now: Optional[int] = None) -> list[P.Packet]:
        """Route matched messages into the window/queue; return PUBLISH
        packets ready to send (emqx_session.erl:542-589)."""
        now = now_ms() if now is None else now
        out: list[P.Packet] = []
        for sub_topic, msg in deliveries:
            opts = self.subscriptions.get(sub_topic)
            if opts is None:
                # late delivery after unsubscribe — drop (settled: the
                # subscription is gone, a replay would drop it again)
                self._settle(msg.id)
                continue
            if opts.nl and msg.from_ == self.clientid:
                self._settle(msg.id)
                continue  # MQTT5 no-local
            qos = max(opts.qos, msg.qos) if self.upgrade_qos else min(opts.qos, msg.qos)
            if msg.is_expired(now):
                self._settle(msg.id)
                continue
            if qos == 0:
                out.append(self._pub_packet(None, msg, qos, opts))
                # effective qos0 has no ack: the socket write is final
                self._settle(msg.id)
            elif self.inflight.is_full():
                # mqueue drops do NOT settle: the store is a superset —
                # resume replays what the bounded queue had to shed
                self.mqueue.insert(self._with_sub(msg, sub_topic))
            else:
                pid = self.next_packet_id()
                self.inflight.insert(
                    pid, InflightEntry(pid, msg, "publish", now, qos,
                                       opts, msg.id)
                )
                out.append(self._pub_packet(pid, msg, qos, opts))
        return out

    def _with_sub(self, msg: Message, sub_topic: str) -> Message:
        return msg.set_header("sub_topic", sub_topic)

    def _pub_packet(self, pid: Optional[int], msg: Message, qos: int,
                    opts: SubOpts) -> P.Publish:
        props = dict(msg.headers.get("properties") or {})
        if opts.subid is not None:
            props["Subscription-Identifier"] = [opts.subid]
        mei = props.get("Message-Expiry-Interval")
        if mei is not None:
            # forward the REMAINING interval (MQTT5 3.3.2-6): queue/store
            # time already consumed from the expiry budget
            elapsed_s = (now_ms() - msg.timestamp) // 1000
            props["Message-Expiry-Interval"] = max(1, int(mei) - elapsed_s)
        retain = msg.retain if opts.rap else False
        if msg.headers.get("retained"):
            retain = True  # messages replayed from the retainer keep retain=1
        return P.Publish(
            topic=msg.topic, payload=msg.payload, qos=qos,
            retain=retain, dup=False, packet_id=pid, properties=props,
        )

    def enqueue(self, sub_topic: str, msg: Message) -> None:
        """Buffer while disconnected (persistent sessions, :594-607)."""
        opts = self.subscriptions.get(sub_topic)
        if opts is None:
            self._settle(msg.id)
            return
        if opts.nl and msg.from_ == self.clientid:
            self._settle(msg.id)
            return
        # mqueue drops do NOT settle: resume replays from the store
        self.mqueue.insert(self._with_sub(msg, sub_topic))

    # -- acks --------------------------------------------------------------

    def discard_delivery(self, packet_id: int,
                         now: Optional[int] = None) -> list[P.Packet]:
        """Server-side 'as if it had completed sending' (MQTT5 3.1.2-25:
        an outgoing publish the client's Maximum-Packet-Size forbids is
        dropped): release the window slot regardless of QoS/phase and
        pull the next queued messages into it."""
        entry = self.inflight.lookup(packet_id)
        if entry is not None:
            self._settle(entry.msg_id)
        self.inflight.delete(packet_id)
        return self.dequeue(now)

    def puback(self, packet_id: int,
               now: Optional[int] = None) -> list[P.Packet]:
        entry = self.inflight.lookup(packet_id)
        if entry is None or entry.phase != "publish" or entry.qos != 1:
            raise SessionError(P.RC_PACKET_IDENTIFIER_NOT_FOUND)
        # the ack is the settlement point (round 18): only now is the
        # store's replay marker spent — a conn death between the write
        # and this PUBACK keeps it, so restart resume retransmits
        self._settle(entry.msg_id)
        self.inflight.delete(packet_id)
        return self.dequeue(now)

    def pubrec(self, packet_id: int,
               now: Optional[int] = None) -> P.PubRel:
        """QoS2 leg 1 acked → move to await-PUBCOMP, emit PUBREL (:466-476)."""
        entry = self.inflight.lookup(packet_id)
        if entry is None or entry.qos != 2 or entry.phase != "publish":
            raise SessionError(P.RC_PACKET_IDENTIFIER_NOT_FOUND)
        entry.phase = "pubrel"
        entry.sent_at = now_ms() if now is None else now
        # payload no longer needed once PUBREC is in (reference stores
        # 'pubrel' marker only)
        entry.msg = None
        return P.PubRel(packet_id=packet_id)

    def pubcomp(self, packet_id: int,
                now: Optional[int] = None) -> list[P.Packet]:
        entry = self.inflight.lookup(packet_id)
        if entry is None or entry.phase != "pubrel":
            raise SessionError(P.RC_PACKET_IDENTIFIER_NOT_FOUND)
        # qos2 settlement: PUBCOMP ends the exchange (msg_id survives
        # pubrec's msg=None drop exactly for this)
        self._settle(entry.msg_id)
        self.inflight.delete(packet_id)
        return self.dequeue(now)

    def native_ack_sync(self, inflight_now: int, pending_now: int,
                        acked: int,
                        now: Optional[int] = None) -> list[P.Packet]:
        """Reconcile one batched native ack record into the session
        (broker/native_server.py drains kind-7 events here once per
        poll cycle — the per-message PUBACK bookkeeping that capped the
        windowed QoS1 plane now arrives as one cycle-level delta).

        Returns PUBLISH packets to send when natively-freed window
        slots let Python-queued messages (punt-served deliveries that
        overflowed into the mqueue) hand off into the wire window."""
        self.native_inflight = inflight_now
        self.native_pending = pending_now
        self.native_acked += acked
        if acked and len(self.mqueue) and not self.inflight.is_full():
            return self.dequeue(now)
        return []

    def adopt_native_window(self, awaiting: list[int],
                            inflight: list[tuple[int, int, str]],
                            pending: list[tuple[str, Message]],
                            now: Optional[int] = None) -> list[P.Packet]:
        """Adopt the C++ host's AckState at live plane demotion
        (broker/native_server.py _on_handoff drains kind-11 records
        here). Three pieces, mirroring the handoff wire format:

        - ``awaiting``: publisher-side qos2 packet ids the native plane
          owned — adopted into ``awaiting_rel`` so a DUP retransmit
          straddling the demotion dedups (PACKET_IDENTIFIER_IN_USE →
          PUBREC, no re-delivery) and the client's PUBREL completes
          here;
        - ``inflight``: (pid, qos, phase) for native deliveries still
          unacked. The pids are >= 32768 (the native space — disjoint
          from ``next_packet_id``'s [1, 32767]), inserted with
          ``msg=None``: the subscriber's PUBACK/PUBREC/PUBCOMP frees
          the slot normally; the retry timer skips message-less entries
          (the written bytes were never retained in C++ — ROADMAP notes
          the edge);
        - ``pending``: (sub_topic, Message) parsed from the window-full
          queue frames — re-enqueued into the mqueue, so they survive a
          later disconnect for the retransmit-on-reconnect replay.

        Returns PUBLISH packets when freed window room lets the adopted
        pending messages start flowing immediately."""
        now = now_ms() if now is None else now
        for pid in awaiting:
            self.awaiting_rel.setdefault(pid, now)
        for pid, qos, phase in inflight:
            if not self.inflight.contain(pid):
                self.inflight.insert(
                    pid, InflightEntry(pid, None, phase, now, qos))
        for sub_topic, msg in pending:
            self.mqueue.insert(self._with_sub(msg, sub_topic))
        return self.dequeue(now) if pending else []

    def dequeue(self, now: Optional[int] = None) -> list[P.Packet]:
        """Fill freed inflight slots from the mqueue (:520-530)."""
        now = now_ms() if now is None else now
        out: list[P.Packet] = []
        while not self.inflight.is_full():
            msg = self.mqueue.pop()
            if msg is None:
                break
            sub_topic = msg.headers.get("sub_topic", msg.topic)
            opts = self.subscriptions.get(sub_topic)
            if opts is None:
                self._settle(msg.id)   # late unsubscribe: final drop
                continue
            qos = max(opts.qos, msg.qos) if self.upgrade_qos else min(opts.qos, msg.qos)
            if msg.is_expired(now):
                self._settle(msg.id)
                continue
            if qos == 0:
                out.append(self._pub_packet(None, msg, qos, opts))
                self._settle(msg.id)
            else:
                pid = self.next_packet_id()
                self.inflight.insert(
                    pid, InflightEntry(pid, msg, "publish", now, qos,
                                       opts, msg.id)
                )
                out.append(self._pub_packet(pid, msg, qos, opts))
        return out

    # -- timers ------------------------------------------------------------

    def retry(self, now: Optional[int] = None) -> list[P.Packet]:
        """Redeliver inflight entries older than retry_interval with DUP
        (the retry_delivery timer, emqx_session.erl retry logic)."""
        now = now_ms() if now is None else now
        out: list[P.Packet] = []
        for pid, entry in self.inflight.items():
            if now - entry.sent_at < self.retry_interval_ms:
                continue
            entry.sent_at = now
            if entry.phase == "pubrel":
                out.append(P.PubRel(packet_id=pid))
            elif entry.msg is not None:
                if entry.msg.is_expired(now):
                    self._settle(entry.msg_id)  # expired: final drop
                    self.inflight.delete(pid)
                    continue
                # reuse the as-delivered subopts so Subscription-Identifier
                # and retain-as-published survive the retransmission
                opts = entry.subopts or SubOpts(qos=entry.qos)
                pkt = self._pub_packet(pid, entry.msg, entry.qos, opts)
                pkt.dup = True
                out.append(pkt)
        return out

    def expire_awaiting_rel(self, now: Optional[int] = None) -> int:
        """Drop incoming-QoS2 trackers past await_rel_timeout."""
        now = now_ms() if now is None else now
        victims = [
            pid for pid, ts in self.awaiting_rel.items()
            if now - ts >= self.await_rel_timeout_ms
        ]
        for pid in victims:
            del self.awaiting_rel[pid]
        return len(victims)

    # -- takeover / resume -------------------------------------------------

    def pending_for_resume(self) -> list[Message]:
        """Messages that would replay on session resume (read-only view)."""
        out = [e.msg for e in self.inflight.values()
               if e.msg is not None]
        out.extend(self.mqueue.peek_all())
        return out

    def take_pending(self) -> list[Message]:
        """Drain publish-phase inflight + mqueue for takeover redelivery.

        The resuming channel re-delivers these through a fresh window (new
        packet ids). 'pubrel'-phase QoS2 entries stay in the inflight — the
        retry timer re-emits their PUBREL on the new connection."""
        out: list[Message] = []
        for pid, entry in self.inflight.items():
            if entry.phase == "publish" and entry.msg is not None:
                out.append(entry.msg)
                self.inflight.delete(pid)
        out.extend(self.mqueue.peek_all())
        while self.mqueue.pop() is not None:
            pass
        return out

    def info(self) -> dict[str, Any]:
        return {
            "clientid": self.clientid,
            "subscriptions_cnt": len(self.subscriptions),
            "inflight_cnt": len(self.inflight),
            "mqueue_len": len(self.mqueue),
            "mqueue_dropped": self.mqueue.dropped,
            "awaiting_rel_cnt": len(self.awaiting_rel),
            "native_inflight_cnt": self.native_inflight,
            "native_pending_len": self.native_pending,
            "native_acked_cnt": self.native_acked,
            "created_at": self.created_at,
        }
