from emqx_tpu.session.inflight import Inflight
from emqx_tpu.session.mqueue import MQueue
from emqx_tpu.session.session import Session

__all__ = ["Inflight", "MQueue", "Session"]
