"""Message queue with priorities and drop policies — parity with
``apps/emqx/src/emqx_mqueue.erl`` (:44-45, :83-108) and
``emqx_pqueue.erl``: bounded queue of messages awaiting an inflight slot,
with per-topic priorities, optional QoS0 bypass, and drop-oldest or
drop-current behavior when full."""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from emqx_tpu.core.message import Message


@dataclass
class MQueueOpts:
    max_len: int = 1000                      # 0 = unlimited
    store_qos0: bool = True                  # keep QoS0 when no conn?
    priorities: dict[str, int] = field(default_factory=dict)  # topic -> prio
    default_priority: str = "lowest"         # "lowest" | "highest"
    shift_multiplier: int = 10               # fairness: msgs per prio round


class MQueue:
    """Priority buckets of FIFO deques; drop-oldest when full."""

    def __init__(self, opts: Optional[MQueueOpts] = None):
        self.opts = opts or MQueueOpts()
        self._qs: dict[int, deque] = {}      # prio -> deque
        self._len = 0
        self.dropped = 0
        self._shift_budget: dict[int, int] = {}

    def _prio(self, msg: Message) -> int:
        p = self.opts.priorities.get(msg.topic)
        if p is not None:
            return p
        if self.opts.default_priority == "highest":
            return max(self.opts.priorities.values(), default=0) + 1
        return 0

    def __len__(self) -> int:
        return self._len

    def is_empty(self) -> bool:
        return self._len == 0

    def insert(self, msg: Message) -> Optional[Message]:
        """Enqueue; returns a dropped message if the queue was full
        (drop-oldest within the same priority, emqx_mqueue.erl:83-108),
        or the message itself if QoS0 and store_qos0=false."""
        if msg.qos == 0 and not self.opts.store_qos0:
            self.dropped += 1
            return msg
        prio = self._prio(msg)
        q = self._qs.setdefault(prio, deque())
        dropped = None
        if self.opts.max_len and self._len >= self.opts.max_len:
            # evict from the lowest-priority non-empty bucket; if the
            # newcomer itself is below every queued message, drop it
            low = min(p for p, b in self._qs.items() if b)
            if prio < low:
                self.dropped += 1
                return msg
            dropped = self._qs[low].popleft()
            self._len -= 1
            self.dropped += 1
        q.append(msg)
        self._len += 1
        return dropped

    def pop(self) -> Optional[Message]:
        """Dequeue highest priority, with shift-budget fairness so lower
        priorities are not starved (emqx_pqueue round-robin shift)."""
        if self._len == 0:
            return None
        prios = sorted((p for p, q in self._qs.items() if q), reverse=True)
        if not prios:
            return None
        if len(prios) > 1:
            top = prios[0]
            budget = self._shift_budget.get(top, self.opts.shift_multiplier)
            if budget <= 0:
                self._shift_budget[top] = self.opts.shift_multiplier
                prios = prios[1:] + [top]
            else:
                self._shift_budget[top] = budget - 1
        q = self._qs[prios[0]]
        msg = q.popleft()
        self._len -= 1
        return msg

    def peek_all(self) -> list[Message]:
        out = []
        for p in sorted(self._qs, reverse=True):
            out.extend(self._qs[p])
        return out
