"""TLS context construction for listeners and clients — the ssl-option
surface of ``emqx_listeners.erl:196-238`` (esockd ssl/wss listeners) and
``apps/emqx_psk/`` (TLS-PSK), built on the stdlib ``ssl`` module.

Design notes (vs the reference):

- The reference passes esockd ``ssl_options`` (certfile/keyfile/cacertfile,
  ``verify``/``fail_if_no_peer_cert``, ``versions``, ``ciphers``, depth).
  The same option names are accepted here and mapped onto
  ``ssl.SSLContext`` so listener configs translate one-to-one.
- ``peer_cert_as_username`` / ``peer_cert_as_clientid`` (cn|dn|crt|pem|md5,
  ``emqx_schema.erl`` listener opts) are implemented by the connection
  host: :func:`peer_cert_identity` extracts the fields from the
  handshake's peer certificate and the listener rewrites the CONNECT.
- TLS-PSK (``apps/emqx_psk/src/emqx_psk.erl`` lookup surface): the
  ``PskStore`` table plugs in via ``SSLContext.set_psk_server_callback``,
  which CPython exposes from 3.13. On older runtimes the wiring is
  detected and reported at listener-build time rather than failing the
  handshake mysteriously (``psk_supported()``).
- DTLS (CoAP/MQTT-SN gateways in the reference) has no stdlib transport;
  the gateways keep their UDP listeners and DTLS stays an explicitly
  gated slot (same status as QUIC/msquic — SURVEY §2.4).
"""

from __future__ import annotations

import ssl
from typing import Optional

_VERSIONS = {
    "tlsv1": ssl.TLSVersion.TLSv1,
    "tlsv1.1": ssl.TLSVersion.TLSv1_1,
    "tlsv1.2": ssl.TLSVersion.TLSv1_2,
    "tlsv1.3": ssl.TLSVersion.TLSv1_3,
}


def psk_supported() -> bool:
    """True when the runtime ssl module can serve TLS-PSK (CPython 3.13+)."""
    return hasattr(ssl.SSLContext, "set_psk_server_callback")


def _apply_versions(ctx: ssl.SSLContext, versions) -> None:
    if not versions:
        # reference default: tlsv1.2 + tlsv1.3 (emqx_schema.erl ssl defaults)
        versions = ["tlsv1.2", "tlsv1.3"]
    unknown = [v for v in versions if v.lower() not in _VERSIONS]
    if unknown:
        raise ValueError(
            f"unknown TLS version(s) {unknown!r} in ssl_options.versions "
            f"(expected one of {sorted(_VERSIONS)})")
    order = list(_VERSIONS)
    idx = sorted({order.index(v.lower()) for v in versions})
    if idx != list(range(idx[0], idx[-1] + 1)):
        # SSLContext can only express a min/max range; a non-contiguous
        # list ("tlsv1" + "tlsv1.3") would silently enable the versions
        # in between — refuse rather than weaken the configured posture
        raise ValueError(
            f"non-contiguous TLS version list {sorted(versions)!r}: the "
            "runtime enforces a continuous min..max range")
    vs = sorted(_VERSIONS[v.lower()] for v in versions)
    ctx.minimum_version = vs[0]
    ctx.maximum_version = vs[-1]


def make_server_context(
    opts: dict,
    psk_store=None,
) -> ssl.SSLContext:
    """Build the listener-side context from an ``ssl_options`` dict:
    certfile, keyfile, password, cacertfile, verify
    ("verify_peer"|"verify_none"), fail_if_no_peer_cert, versions,
    ciphers, depth."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    _apply_versions(ctx, opts.get("versions"))
    certfile = opts.get("certfile")
    if certfile:
        ctx.load_cert_chain(
            certfile, opts.get("keyfile") or None,
            opts.get("password") or None)
    cacertfile = opts.get("cacertfile")
    if cacertfile:
        ctx.load_verify_locations(cacertfile)
    if opts.get("verify", "verify_none") == "verify_peer":
        # esockd: verify_peer + fail_if_no_peer_cert=false still completes
        # the handshake without a client cert (CERT_OPTIONAL)
        ctx.verify_mode = (
            ssl.CERT_REQUIRED if opts.get("fail_if_no_peer_cert")
            else ssl.CERT_OPTIONAL)
    else:
        ctx.verify_mode = ssl.CERT_NONE
    ciphers = opts.get("ciphers")
    if ciphers:
        ctx.set_ciphers(":".join(ciphers)
                        if isinstance(ciphers, (list, tuple)) else ciphers)
    if psk_store is not None:
        if not psk_supported():
            raise RuntimeError(
                "TLS-PSK requires CPython >= 3.13 "
                "(ssl.SSLContext.set_psk_server_callback); "
                "gate the listener's enable_psk on tls.psk_supported()")

        def _psk_cb(identity: Optional[str]):
            key = psk_store.lookup(identity or "")
            return key if key is not None else b""

        ctx.set_psk_server_callback(_psk_cb)
    return ctx


def make_client_context(opts: Optional[dict] = None) -> ssl.SSLContext:
    """Client-side context (MQTT bridge egress, test clients): cacertfile
    to pin the server CA, certfile/keyfile for mutual TLS, verify
    "verify_none" to skip server-cert checks."""
    opts = opts or {}
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    _apply_versions(ctx, opts.get("versions"))
    cacertfile = opts.get("cacertfile")
    if cacertfile:
        ctx.load_verify_locations(cacertfile)
    else:
        ctx.load_default_certs()
    if opts.get("verify", "verify_peer") == "verify_none":
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    certfile = opts.get("certfile")
    if certfile:
        ctx.load_cert_chain(
            certfile, opts.get("keyfile") or None,
            opts.get("password") or None)
    return ctx


def peer_cert_identity(peercert: Optional[dict]) -> dict:
    """Extract the identity fields a listener's ``peer_cert_as_username``
    / ``peer_cert_as_clientid`` option selects from (cn | dn); ``crt``/
    ``pem``/``md5`` need the DER bytes, which the connection host passes
    separately when configured."""
    if not peercert:
        return {}
    out: dict = {"peercert": peercert}
    rdns = peercert.get("subject", ())
    parts = []
    for rdn in rdns:
        for name, value in rdn:
            if name == "commonName":
                out.setdefault("cn", value)
            parts.append(f"{_DN_ABBREV.get(name, name)}={value}")
    if parts:
        out["dn"] = ",".join(reversed(parts))
    return out


_DN_ABBREV = {
    "commonName": "CN", "countryName": "C", "stateOrProvinceName": "ST",
    "localityName": "L", "organizationName": "O",
    "organizationalUnitName": "OU", "emailAddress": "emailAddress",
}
