"""Overload protection, forced GC, and congestion alarms — the
``emqx_olp.erl`` (+ `lc` dep), ``emqx_gc.erl`` and ``emqx_congestion.erl``
analogues.

The reference watches BEAM run-queue pressure and then sheds load by
skipping hibernation/GC and refusing new connections
(emqx_olp:backoff_new_conn/1). Our load signal is event-loop lag: the
housekeeping timer knows when it *should* have fired; the drift is the
Python-side run-queue. The native (C++) host reports its poll-loop lag
through the same interface.
"""

from __future__ import annotations

import gc as _pygc
import time
from typing import Optional


class Olp:
    """Load flags from loop lag; consumers ask before doing optional work."""

    def __init__(self, enable: bool = True,
                 backoff_delay_ms: float = 100.0,
                 backoff_new_conn: bool = True,
                 backoff_hibernation: bool = True,
                 backoff_gc: bool = True) -> None:
        self.enable = enable
        self.backoff_delay_ms = backoff_delay_ms
        self._flag_new_conn = backoff_new_conn
        self._flag_hib = backoff_hibernation
        self._flag_gc = backoff_gc
        self.lag_ms = 0.0
        self._overloaded = False

    def note_lag(self, lag_ms: float) -> None:
        """Feed the measured scheduling drift (EWMA-smoothed)."""
        self.lag_ms = 0.7 * self.lag_ms + 0.3 * max(0.0, lag_ms)
        self._overloaded = self.enable and self.lag_ms > self.backoff_delay_ms

    def is_overloaded(self) -> bool:
        return self._overloaded

    def backoff_new_conn(self) -> bool:
        """True → refuse the incoming connection at accept."""
        return self._overloaded and self._flag_new_conn

    def backoff_hibernation(self) -> bool:
        return self._overloaded and self._flag_hib

    def backoff_gc(self) -> bool:
        return self._overloaded and self._flag_gc


class GcPolicy:
    """Force a collection every N messages / bytes per connection
    (emqx_gc:run/3 — zone config ``force_gc``)."""

    def __init__(self, count: int = 16000, bytes_: int = 16 * 1024 * 1024,
                 enable: bool = True) -> None:
        self.enable = enable
        self.count_budget = count
        self.bytes_budget = bytes_
        self._count = count
        self._bytes = bytes_

    def note(self, msgs: int, nbytes: int,
             olp: Optional[Olp] = None) -> bool:
        """Returns True if a collection ran."""
        if not self.enable:
            return False
        self._count -= msgs
        self._bytes -= nbytes
        if self._count > 0 and self._bytes > 0:
            return False
        self._count = self.count_budget
        self._bytes = self.bytes_budget
        if olp is not None and olp.backoff_gc():
            return False        # overloaded: skip optional GC
        _pygc.collect(0)        # young generation only, like the per-proc GC
        return True


class Congestion:
    """TCP congestion alarms: socket send buffer persistently above the
    high watermark → alarm; clears below the low watermark
    (emqx_congestion.erl)."""

    def __init__(self, alarms=None, high_watermark: int = 1 << 20,
                 low_watermark: int = 1 << 16,
                 min_alarm_sustain_s: float = 1.0) -> None:
        self.alarms = alarms
        self.high = high_watermark
        self.low = low_watermark
        self.sustain_s = min_alarm_sustain_s
        self._over_since: dict[str, float] = {}
        self.congested: set[str] = set()

    def check(self, conn_id: str, buffered: int,
              now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if buffered >= self.high:
            since = self._over_since.setdefault(conn_id, now)
            if (now - since >= self.sustain_s
                    and conn_id not in self.congested):
                self.congested.add(conn_id)
                if self.alarms is not None:
                    self.alarms.activate(
                        f"conn_congestion/{conn_id}",
                        message=f"send buffer {buffered}B > {self.high}B")
        else:
            # below high: the sustain clock resets (must be continuously
            # over the watermark); the ALARM clears only under the low
            # watermark (hysteresis band keeps it active in between)
            self._over_since.pop(conn_id, None)
            if buffered <= self.low and conn_id in self.congested:
                self.congested.discard(conn_id)
                if self.alarms is not None:
                    self.alarms.deactivate(f"conn_congestion/{conn_id}")

    def forget(self, conn_id: str) -> None:
        self._over_since.pop(conn_id, None)
        if conn_id in self.congested:
            self.congested.discard(conn_id)
            if self.alarms is not None:
                self.alarms.deactivate(f"conn_congestion/{conn_id}")
