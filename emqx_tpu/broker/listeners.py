"""Config-driven listener lifecycle — the ``emqx_listeners.erl`` start
surface: the ``listeners`` config map (name → conf) becomes running
tcp / ssl / ws / wss servers bound to one BrokerApp.

Mirrors ``emqx_listeners:start/0`` → ``start_listener/3``
(emqx_listeners.erl:189-238): tcp+ssl ride the stream listener
(BrokerServer), ws+wss the websocket listener (WsBrokerServer); ssl/wss
build an ``ssl.SSLContext`` from the listener's ``ssl_options`` (and the
app's PskStore when ``enable_psk``). ``quic`` is an explicitly gated
slot: the reference's quicer/msquic NIF has no stdlib counterpart, so a
quic listener config is accepted by the schema but start raises with the
descope reason rather than pretending to serve
(emqx_quic_connection.erl — SURVEY §2.4 native-deps table).
"""

from __future__ import annotations

import logging
from typing import Optional

from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.broker.ws import WsBrokerServer

log = logging.getLogger("emqx_tpu.listeners")


def parse_bind(bind: "str | int", default_port: int = 1883
               ) -> tuple[str, int]:
    """'0.0.0.0:1883' | ':1883' | '1883' | 1883 | '[::1]:1883'
    → (host, port)."""
    if isinstance(bind, int):
        return "0.0.0.0", bind
    s = str(bind).strip()
    host, sep, port = s.rpartition(":")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]                 # bracketed IPv6 literal
    elif ":" in host:
        # '::1' with no port — rpartition split inside the address
        host, port = s.strip("[]"), ""
    elif not sep and not port.isdigit():
        host, port = port, ""             # bare hostname, default port
    try:
        return host or "0.0.0.0", int(port) if port else default_port
    except ValueError:
        raise ValueError(f"invalid listener bind {bind!r} "
                         "(expected host:port, :port, or port)") from None


def build_listener(app, name: str, conf: dict):
    """One listener conf → an (unstarted) server object."""
    ltype = conf.get("type", "tcp")
    host, port = parse_bind(conf.get("bind", "0.0.0.0:1883"))
    ssl_context = None
    extra_ssl: dict = {}
    if ltype in ("ssl", "wss"):
        from emqx_tpu.broker import tls

        psk_store = None
        if conf.get("ssl_options", {}).get("enable_psk"):
            psk_store = getattr(app, "psk", None)
        ssl_context = tls.make_server_context(
            conf.get("ssl_options", {}), psk_store=psk_store)
        hs = conf.get("ssl_options", {}).get("handshake_timeout")
        if hs:
            extra_ssl = {"ssl_handshake_timeout": float(hs)}
        if (conf.get("ssl_options", {}).get("verify", "verify_none")
                != "verify_peer"
                and any(conf.get(k) not in ("disabled", None, "")
                        for k in ("peer_cert_as_username",
                                  "peer_cert_as_clientid"))):
            raise ValueError(
                f"listener {name!r}: peer_cert_as_username/clientid "
                "needs ssl_options.verify = verify_peer — without it "
                "the server never requests a client certificate and "
                "the cert identity would silently not apply")
    elif ltype == "quic":
        raise NotImplementedError(
            "quic listener: the reference rides the quicer/msquic C NIF; "
            "no msquic binding ships in this build — use tcp/ssl/ws/wss "
            "(config slot reserved, emqx_quic_connection.erl)")

    def _ident(key: str) -> Optional[str]:
        v = conf.get(key, "disabled")
        return None if v in ("disabled", None, "") else v

    kw = dict(
        app=app,
        host=host,
        port=port,
        max_connections=int(conf.get("max_connections", 1_000_000)),
        mountpoint=conf.get("mountpoint", ""),
        listener_id=f"{ltype}:{name}",
        ssl_context=ssl_context,
        **extra_ssl,
        peer_cert_as_username=_ident("peer_cert_as_username"),
        peer_cert_as_clientid=_ident("peer_cert_as_clientid"),
        limiter=getattr(app, "limiter", None),
    )
    if ltype in ("ws", "wss"):
        return WsBrokerServer(path=conf.get("websocket_path", "/mqtt"), **kw)
    if ltype == "native":
        # ws_bind opens the C++ RFC6455 listener next to the TCP one —
        # both feed the same epoll loop/fast path; the asyncio ws
        # listener (type = ws) remains the slow-plane oracle
        ws_bind = conf.get("ws_bind")
        ws_host = ws_port = None
        # NOT a truthiness test: the integer bind 0 (ephemeral port)
        # is a valid, enabled configuration
        if ws_bind is not None and ws_bind is not False and ws_bind != "":
            ws_host, ws_port = parse_bind(ws_bind, default_port=8083)
        return NativeListener(
            app=app, host=host, port=port,
            max_connections=kw["max_connections"],
            mountpoint=kw["mountpoint"],
            listener_id=kw["listener_id"],
            fast_path=bool(conf.get("fast_path", True)),
            device_lane=str(conf.get("device_lane", "auto")),
            ws_host=ws_host, ws_port=ws_port,
            ws_path=conf.get("websocket_path", "/mqtt"))
    return BrokerServer(**kw)


class NativeListener:
    """Async-supervisor adapter over the C++ epoll host
    (``broker/native_server.py``) so ``listeners { n1 { type = native } }``
    boots it like any other listener. Construction (which may compile
    the C++ library on first use) and teardown (thread join +
    host.destroy) run in a worker thread — blocking the event loop here
    would stall every other listener and the management API."""

    def __init__(self, app, host: str, port: int, max_connections: int,
                 mountpoint: str, listener_id: str,
                 fast_path: bool = True,
                 device_lane: str = "auto",
                 ws_host: "str | None" = None,
                 ws_port: "int | None" = None,
                 ws_path: str = "/mqtt") -> None:
        self._app = app
        self._bind = (host, port)
        self._kw = dict(max_connections=max_connections,
                        mountpoint=mountpoint, fast_path=fast_path,
                        device_lane=device_lane, ws_host=ws_host,
                        ws_port=ws_port, ws_path=ws_path)
        self.listener_id = listener_id
        self.host = host
        self.port = port
        self.ws_port = ws_port       # bound port known after start()
        self.max_connections = max_connections
        self.ssl_context = None
        self._srv = None
        self._server = None          # "running" flag for info()

    @property
    def connections(self):
        return self._srv.conns if self._srv is not None else {}

    def fast_stats(self) -> dict:
        return self._srv.fast_stats() if self._srv is not None else {}

    async def start(self) -> None:
        import asyncio

        def _boot():
            from emqx_tpu.broker.native_server import NativeBrokerServer
            srv = NativeBrokerServer(
                app=self._app, host=self._bind[0], port=self._bind[1],
                **self._kw)
            srv.start()
            return srv

        self._srv = await asyncio.to_thread(_boot)
        self.port = self._srv.port
        self.ws_port = self._srv.ws_port
        self._server = self._srv

    async def stop(self) -> None:
        import asyncio

        srv, self._srv, self._server = self._srv, None, None
        if srv is not None:
            await asyncio.to_thread(srv.stop)


class Listeners:
    """Supervisor for the app's listener set (start/stop/restart by id)."""

    def __init__(self, app) -> None:
        self.app = app
        self.servers: dict[str, BrokerServer] = {}   # "type:name" → server

    async def start_all(self, listeners_conf: dict) -> list[str]:
        started = []
        try:
            for name, conf in (listeners_conf or {}).items():
                if not conf.get("enabled", True):
                    continue
                server = build_listener(self.app, name, conf)
                await server.start()
                self.servers[server.listener_id] = server
                started.append(server.listener_id)
                log.info("listener %s on %s:%d%s", server.listener_id,
                         server.host, server.port,
                         " (tls)" if server.ssl_context else "")
        except Exception:
            # all-or-nothing boot: a half-started listener set would keep
            # ports bound and make the retry fail with EADDRINUSE
            for lid in started:
                await self.stop(lid)
            raise
        return started

    async def stop(self, listener_id: str) -> bool:
        server = self.servers.pop(listener_id, None)
        if server is None:
            return False
        await server.stop()
        return True

    async def stop_all(self) -> None:
        for lid in list(self.servers):
            await self.stop(lid)

    def find(self, listener_id: str) -> Optional[BrokerServer]:
        return self.servers.get(listener_id)

    def info(self) -> list[dict]:
        return [
            {
                "id": lid,
                "type": lid.split(":", 1)[0],
                "bind": f"{s.host}:{s.port}",
                "running": s._server is not None,
                "current_connections": len(s.connections),
                "max_connections": s.max_connections,
            }
            for lid, s in self.servers.items()
        ]
