"""Shared subscriptions (``$share/group/topic``) — parity with
``apps/emqx/src/emqx_shared_sub.erl``.

Group membership per (group, topic) with the reference's 7 dispatch
strategies (emqx_shared_sub.erl:78-85, :309-379):

- ``random``               uniform pick
- ``round_robin``          per-(group,topic) rotating cursor
- ``round_robin_per_group`` one cursor per group (all topics share it)
- ``sticky``               pin to one member until it leaves
- ``local``                prefer members on this node, else random
- ``hash_clientid``        publisher clientid hash
- ``hash_topic``           topic hash

QoS1/2 ack/redispatch (:190-217, :244-266): if the picked member nacks
(session window full / down), redispatch to another member not yet tried.
"""

from __future__ import annotations

import random
import threading
import zlib
from typing import Any, Optional

from emqx_tpu.core.message import Message


class SharedSub:
    def __init__(self, node: str = "node1", strategy: str = "round_robin",
                 seed: Optional[int] = None):
        self.node = node
        self.strategy = strategy
        # (group, topic) -> [(sid, node)]
        self._members: dict[tuple[str, str], list[tuple[str, str]]] = {}
        # dispatch table: (group, topic) -> [members, sub_topic, rr_cursor]
        # — ONE dict lookup on the per-message hot path instead of three
        # (members, pre-formatted "$share/g/t", and the round-robin
        # cursor live in the same entry; members aliases _members[key])
        self._tab: dict[tuple[str, str], list] = {}
        self._rr_group: dict[str, int] = {}
        self._sticky: dict[tuple[str, str], tuple[str, str]] = {}
        self._rng = random.Random(seed)
        self._lock = threading.RLock()

    # -- membership --------------------------------------------------------

    def join(self, group: str, topic: str, sid: str,
             node: Optional[str] = None) -> None:
        with self._lock:
            key = (group, topic)
            members = self._members.setdefault(key, [])
            if key not in self._tab:
                self._tab[key] = [members, f"$share/{group}/{topic}", -1]
            entry = (sid, node or self.node)
            if entry not in members:
                members.append(entry)

    def leave(self, group: str, topic: str, sid: str,
              node: Optional[str] = None) -> None:
        with self._lock:
            key = (group, topic)
            members = self._members.get(key)
            if not members:
                return
            entry = (sid, node or self.node)
            if entry in members:
                members.remove(entry)
            if not members:
                self._members.pop(key, None)
                self._tab.pop(key, None)
                self._sticky.pop(key, None)
            elif self._sticky.get(key) == entry:
                self._sticky.pop(key, None)

    def _purge(self, dead) -> None:
        """Drop members matching ``dead((sid, node))`` from every group."""
        with self._lock:
            for key in list(self._members):
                members = self._members[key]
                members[:] = [m for m in members if not dead(m)]
                if not members:
                    self._members.pop(key, None)
                    self._tab.pop(key, None)
                    self._sticky.pop(key, None)
                elif (sticky := self._sticky.get(key)) and dead(sticky):
                    self._sticky.pop(key, None)

    def member_down(self, sid: str) -> None:
        """Clean a dead subscriber out of every group, any node
        (emqx_shared_sub.erl:456-519)."""
        self._purge(lambda m: m[0] == sid)

    def groups_for(self, topic: str) -> list[str]:
        with self._lock:
            return [g for (g, t) in self._members if t == topic]

    def members(self) -> dict[tuple[str, str], list[tuple[str, str]]]:
        """{(group, topic): [(sid, node)]} snapshot (cluster bootstrap)."""
        with self._lock:
            return {k: list(v) for k, v in self._members.items()}

    def node_down(self, node: str) -> None:
        """Purge every member hosted on a dead node
        (emqx_shared_sub node-down sweep)."""
        self._purge(lambda m: m[1] == node)

    # -- dispatch ----------------------------------------------------------

    def pick(self, group: str, topic: str, msg: Message,
             exclude: Optional[set] = None) -> Optional[tuple[str, str]]:
        """Pick one member (sid, node) per the strategy; ``exclude`` is the
        already-nacked set during redispatch."""
        with self._lock:
            key = (group, topic)
            members = self._members.get(key)
            if exclude and members:
                # redispatch path only: the common no-exclusion pick
                # must not copy the member list per message
                members = [m for m in members if m not in exclude]
            if not members:
                return None
            s = self.strategy
            if s == "sticky":
                cur = self._sticky.get(key)
                if cur in members:
                    return cur
                choice = self._rng.choice(members)
                self._sticky[key] = choice
                return choice
            if s == "round_robin":
                ent = self._tab[key]
                i = ent[2] + 1
                ent[2] = i
                return members[i % len(members)]
            if s == "round_robin_per_group":
                i = self._rr_group.get(group, -1) + 1
                self._rr_group[group] = i
                return members[i % len(members)]
            if s == "local":
                local = [m for m in members if m[1] == self.node]
                return self._rng.choice(local or members)
            # deterministic hash (erlang:phash2 analogue): Python's hash()
            # is salted per process and would repick after restarts/nodes
            if s == "hash_clientid":
                return members[zlib.crc32(msg.from_.encode()) % len(members)]
            if s == "hash_topic":
                return members[zlib.crc32(msg.topic.encode()) % len(members)]
            return self._rng.choice(members)   # random

    def dispatch(self, group: str, topic: str, msg: Message,
                 deliver_fn=None) -> list[tuple[str, str, str]]:
        """Broker-facing dispatch: pick a member; with ``deliver_fn``
        ((sid, node) → bool ack) retry un-acked members (QoS>0 redispatch
        semantics). Returns [(sid, node, sub_topic)] that accepted."""
        tried: Optional[set] = None      # allocated only on redispatch
        while True:
            member = self.pick(group, topic, msg, exclude=tried)
            if member is None:
                return []
            sid, node = member
            ent = self._tab.get((group, topic))
            sub_topic = ent[1] if ent else f"$share/{group}/{topic}"
            if deliver_fn is None or msg.qos == 0:
                return [(sid, node, sub_topic)]
            if deliver_fn(sid, node):
                return [(sid, node, sub_topic)]
            if tried is None:
                tried = set()
            tried.add(member)
            if self.strategy == "sticky":
                # nacked: unpin so the next pick rotates
                self._sticky.pop((group, topic), None)

    def dispatch_batch(self, legs, deliver_fn=None) -> list:
        """Batched strategy picks (VERDICT r3 #7): one lock hold and an
        inlined cursor walk for a whole publish batch's shared legs,
        instead of a pick() call (lock + strategy branch + dict walks)
        per message. ``legs`` is ``[(group, topic, msg)]``; returns one
        ``(sid, node, sub_topic) | None`` per leg, order-preserving.
        Strategies other than the rotating/hash families — and every
        ack/redispatch (deliver_fn) path — fall back to ``dispatch``
        per leg, so the semantics match the single-message API
        (emqx_shared_sub.erl:138-157 strategy table)."""
        s = self.strategy
        if s not in ("round_robin", "round_robin_per_group",
                     "hash_clientid", "hash_topic") or (
                deliver_fn is not None and s != "round_robin"):
            return [
                (d[0] if (d := self.dispatch(g, t, m,
                                             deliver_fn=deliver_fn))
                 else None)
                for g, t, m in legs
            ]
        out = []
        append = out.append
        # QoS>0 legs whose pick must survive a deliver_fn verdict; the
        # callback runs AFTER the lock is released. dispatch() already
        # keeps deliver_fn outside its hold, and a batch must match: an
        # arbitrary callback (it may re-enter SharedSub, block on a
        # session, or just be slow) must not extend the table hold
        # across the whole batch and starve concurrent join/leave
        pending: list = []
        with self._lock:
            tab_get = self._tab.get
            if s == "round_robin":
                for group, topic, msg in legs:
                    ent = tab_get((group, topic))
                    if ent is None or not ent[0]:
                        append(None)
                        continue
                    members = ent[0]
                    i = ent[2] + 1
                    ent[2] = i
                    m = members[i % len(members)]
                    if deliver_fn is not None and msg.qos:
                        pending.append((len(out), group, topic, msg, m))
                    append((m[0], m[1], ent[1]))
            elif s == "round_robin_per_group":
                rrg = self._rr_group
                for group, topic, msg in legs:
                    ent = tab_get((group, topic))
                    if ent is None or not ent[0]:
                        append(None)
                        continue
                    members = ent[0]
                    i = rrg.get(group, -1) + 1
                    rrg[group] = i
                    m = members[i % len(members)]
                    append((m[0], m[1], ent[1]))
            else:                        # hash_clientid / hash_topic
                by_client = s == "hash_clientid"
                for group, topic, msg in legs:
                    ent = tab_get((group, topic))
                    if ent is None or not ent[0]:
                        append(None)
                        continue
                    members = ent[0]
                    word = msg.from_ if by_client else msg.topic
                    m = members[zlib.crc32(word.encode()) % len(members)]
                    append((m[0], m[1], ent[1]))
        # outside the lock: confirm QoS>0 picks; a nack falls back to the
        # single-leg dispatch(), whose rotate-past-nacked retry loop
        # already interleaves pick() and deliver_fn without holding the
        # table lock (the cursor advance above keeps rotation fair)
        for idx, group, topic, msg, m in pending:
            if deliver_fn(m[0], m[1]):
                continue
            d = self.dispatch(group, topic, msg, deliver_fn=deliver_fn)
            out[idx] = d[0] if d else None
        return out
