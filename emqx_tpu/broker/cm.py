"""Connection/session manager — parity with ``apps/emqx/src/emqx_cm.erl``.

Registry of clientid → live channel, session open with clean-start /
resume semantics, takeover/discard/kick (emqx_cm.erl:268-341, :377-429,
:433-560). The reference's per-clientid distributed lock (emqx_cm_locker)
maps to a per-clientid threading lock here; the cross-node legs ride the
cluster plane's versioned protos once connected.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from emqx_tpu.session.session import Session


class CM:
    def __init__(self, persistence: Any = None) -> None:
        self._channels: dict[str, Any] = {}     # clientid -> Channel
        self._locks: dict[str, threading.Lock] = {}
        self._glock = threading.Lock()
        # optional PersistentSessions service: the restart-surviving tier
        # behind the in-memory disconnected-channel state (emqx_cm checks
        # emqx_persistent_session on resume with no live channel)
        self.persistence = persistence

    def _lock_for(self, clientid: str) -> threading.Lock:
        with self._glock:
            return self._locks.setdefault(clientid, threading.Lock())

    def _wire_settle(self, clientid: str, session: Session) -> None:
        """Wire the session's delivery-settlement observer to the
        persistence layer (round 18, consume-on-ack): a store replay
        marker is spent when the delivery SETTLES — subscriber ack,
        effective-qos0 write, or a final drop — never at delivery-write
        time, so a conn that dies between the socket write and the
        PUBACK keeps its marker and restart resume retransmits."""
        if self.persistence is not None and session is not None:
            session.settle_fn = (
                lambda mid, _sid=clientid:
                self.persistence.settle(_sid, mid))

    def lookup_channel(self, clientid: str) -> Optional[Any]:
        return self._channels.get(clientid)

    def register_channel(self, clientid: str, channel: Any) -> None:
        self._channels[clientid] = channel

    def unregister_channel(self, clientid: str, channel: Any = None) -> None:
        cur = self._channels.get(clientid)
        if channel is None or cur is channel:
            self._channels.pop(clientid, None)

    def all_channels(self) -> list[tuple[str, Any]]:
        return list(self._channels.items())

    def open_session(
        self, clean_start: bool, clientid: str, new_channel: Any,
        session_opts: Optional[dict] = None,
    ) -> tuple[Session, bool, list]:
        """Returns (session, session_present, pending_messages).

        clean_start=True  → discard any live channel + fresh session
        clean_start=False → takeover: old channel yields its session and
                            pending messages, then dies (2-phase:
                            emqx_cm.erl takeover_session)
        """
        with self._lock_for(clientid):
            old = self._channels.get(clientid)
            if clean_start:
                if old is not None and old is not new_channel:
                    old.discard()                     # kicked (RC 0x8E)
                elif self.persistence is not None:
                    # no live channel, but a clean start still wipes any
                    # stored session state (MQTT5 3.1.2.4)
                    self.persistence.discard(clientid)
                session = Session(
                    clientid=clientid, clean_start=True,
                    **(session_opts or {}),
                )
                self._wire_settle(clientid, session)
                self._channels[clientid] = new_channel
                return session, False, []
            # resume path
            if old is not None and old is not new_channel:
                session, pending = old.takeover()
                self._channels[clientid] = new_channel
                if session is not None:
                    session.clean_start = False
                    self._wire_settle(clientid, session)
                    if (self.persistence is not None
                            and self.persistence.lookup(clientid)
                            is not None):
                        # consume the stored markers too, or a later node
                        # restart replays messages this takeover already
                        # delivered; merge any the in-memory queue dropped
                        _subs, stored = self.persistence.resume(clientid)
                        seen = {m.id for m in pending}
                        pending = pending + [
                            m for m in stored if m.id not in seen
                        ]
                    return session, True, pending
            self._channels[clientid] = new_channel
            session = Session(
                clientid=clientid, clean_start=False,
                **(session_opts or {}),
            )
            self._wire_settle(clientid, session)
            # restart-resume: no live channel — replay from the store
            # (emqx_persistent_session:resume, :275-310)
            if (self.persistence is not None
                    and self.persistence.lookup(clientid) is not None):
                subs, pending = self.persistence.resume(clientid)
                session.subscriptions.update(subs)
                return session, True, pending
            return session, False, []

    def dispatch(self, deliveries: dict[str, list]) -> None:
        """Fan broker deliveries out to each target channel's socket."""
        from emqx_tpu.core.message import now_ms

        begin = now_ms()
        for sid, items in deliveries.items():
            # deliver-begin stamp (emqx_session.erl:908 mark_begin_deliver):
            # slow-subs latency measures dispatch→flush, not storage age —
            # retained/delayed messages would otherwise report their shelf
            # time as delivery latency. Unconditional: a replay of a stored
            # message (retainer keeps a copy sharing this extra dict) is a
            # NEW delivery and must restamp.
            for _st, m in items:
                m.extra["deliver_begin_at"] = begin
            ch = self._channels.get(sid)
            if ch is not None:
                # marker consumption moved to the SETTLE seam (round
                # 18): the session spends each marker when the delivery
                # settles — subscriber ack / effective-qos0 write /
                # final drop — never here at delivery-write time, so a
                # conn death before the ack keeps the replay marker
                ch.send(ch.handle_deliver(items))

    def kick(self, clientid: str) -> bool:
        """Administrative kick (emqx_cm:kick_session)."""
        with self._lock_for(clientid):
            ch = self._channels.pop(clientid, None)
            if ch is None:
                return False
            ch.discard()
            return True
