"""Hierarchical token-bucket rate limiting — parity with
``apps/emqx/src/emqx_limiter/`` (13 modules).

The reference layers three levels — node-wide bucket → listener/zone
bucket (``emqx_limiter_server.erl`` allocator) → per-connection client
bucket (``emqx_htb_limiter.erl``), composed per connection in
``emqx_limiter_container.erl`` and hooked into the socket loop via
``emqx_esockd_htb_limiter.erl``. Here the same shape is a parent-linked
token-bucket tree: consuming at a leaf must also draw from every
ancestor, so a node cap throttles all listeners and a listener cap
throttles all its connections.

Limit types (emqx_limiter_schema.erl): ``bytes_in``, ``message_in``,
``connection``, ``message_routing``. Unconfigured type = infinity
(always allow).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

TYPES = ("bytes_in", "message_in", "connection", "message_routing")


class Bucket:
    """One token bucket; ``rate`` tokens/second, ``burst`` capacity.
    rate=None → infinity."""

    def __init__(self, rate: Optional[float], burst: Optional[float] = None,
                 parent: Optional["Bucket"] = None, name: str = "") -> None:
        self.rate = rate
        self.burst = burst if burst is not None else (
            rate if rate is not None else 0.0)
        self.tokens = self.burst
        self.parent = parent
        self.name = name
        self._last = time.monotonic()

    def _refill(self, now: float) -> None:
        if self.rate is None:
            return
        elapsed = now - self._last
        if elapsed > 0:
            self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
            self._last = now

    def _available(self, now: float) -> float:
        if self.rate is None:
            mine = float("inf")
        else:
            self._refill(now)
            mine = self.tokens
        if self.parent is not None:
            return min(mine, self.parent._available(now))
        return mine

    def _take(self, n: float, now: float) -> None:
        if self.rate is not None:
            self._refill(now)
            self.tokens -= n
        if self.parent is not None:
            self.parent._take(n, now)

    def try_consume(self, n: float = 1.0,
                    now: Optional[float] = None) -> tuple[bool, float]:
        """→ (granted, retry_after_s). All-or-nothing across the chain
        (the htb client either gets its demand or registers a wait)."""
        now = time.monotonic() if now is None else now
        # epsilon absorbs float error at exact refill boundaries
        # (0.1s * 10/s must count as 1 token)
        if self._available(now) + 1e-9 >= n:
            self._take(n, now)
            return True, 0.0
        return False, self.retry_after(n, now)

    def retry_after(self, n: float, now: Optional[float] = None) -> float:
        """Seconds until ``n`` tokens could be available on the chain."""
        now = time.monotonic() if now is None else now
        worst = 0.0
        node: Optional[Bucket] = self
        while node is not None:
            if node.rate is not None:
                node._refill(now)
                deficit = n - node.tokens
                if deficit > 0:
                    worst = max(worst, deficit / node.rate
                                if node.rate > 0 else float("inf"))
            node = node.parent
        return worst

    def child(self, rate: Optional[float] = None,
              burst: Optional[float] = None, name: str = "") -> "Bucket":
        return Bucket(rate, burst, parent=self, name=name)


class LimiterContainer:
    """Per-connection composite (emqx_limiter_container.erl): one leaf
    bucket per limit type; missing type = infinity."""

    def __init__(self, buckets: Optional[dict[str, Bucket]] = None) -> None:
        self.buckets: dict[str, Bucket] = dict(buckets or {})

    def check(self, type_: str, n: float = 1.0) -> tuple[bool, float]:
        b = self.buckets.get(type_)
        if b is None:
            return True, 0.0
        return b.try_consume(n)


@dataclass
class LimiterConfig:
    """Rates for one scope (node / listener / per-client). None=infinity.
    ``*_burst`` defaults to one second's worth of tokens."""
    bytes_in: Optional[float] = None
    message_in: Optional[float] = None
    connection: Optional[float] = None
    message_routing: Optional[float] = None
    bytes_in_burst: Optional[float] = None
    message_in_burst: Optional[float] = None
    connection_burst: Optional[float] = None
    message_routing_burst: Optional[float] = None

    def rate(self, t: str) -> Optional[float]:
        return getattr(self, t)

    def burst(self, t: str) -> Optional[float]:
        return getattr(self, f"{t}_burst")


class LimiterServer:
    """Root/listener bucket registry (emqx_limiter_server.erl). Builds
    per-connection containers whose leaves chain to the listener buckets,
    which chain to the node buckets."""

    def __init__(self, node_config: Optional[LimiterConfig] = None) -> None:
        self.node_config = node_config or LimiterConfig()
        self._node: dict[str, Bucket] = {}
        for t in TYPES:
            r = self.node_config.rate(t)
            if r is not None:
                self._node[t] = Bucket(r, self.node_config.burst(t),
                                       name=f"node.{t}")
        self._listeners: dict[str, dict[str, Bucket]] = {}
        self._listener_cfg: dict[str, LimiterConfig] = {}

    def add_listener(self, listener_id: str, config: LimiterConfig,
                     client_config: Optional[LimiterConfig] = None) -> None:
        buckets: dict[str, Bucket] = {}
        for t in TYPES:
            r = config.rate(t)
            parent = self._node.get(t)
            if r is not None or parent is not None:
                buckets[t] = Bucket(r, config.burst(t), parent=parent,
                                    name=f"{listener_id}.{t}")
        self._listeners[listener_id] = buckets
        self._listener_cfg[listener_id] = client_config or LimiterConfig()

    def connect(self, listener_id: str) -> tuple[bool, float]:
        """New-connection admission (the esockd conn-rate limit)."""
        buckets = self._listeners.get(listener_id, {})
        b = buckets.get("connection")
        if b is None:
            return True, 0.0
        return b.try_consume(1.0)

    def make_container(self, listener_id: str) -> LimiterContainer:
        buckets = self._listeners.get(listener_id, {})
        cfg = self._listener_cfg.get(listener_id, LimiterConfig())
        leaves: dict[str, Bucket] = {}
        for t in ("bytes_in", "message_in", "message_routing"):
            parent = buckets.get(t)
            r = cfg.rate(t)
            if r is not None or parent is not None:
                leaves[t] = Bucket(r, cfg.burst(t), parent=parent,
                                   name=f"client.{t}")
        return LimiterContainer(leaves)
