"""Per-client MQTT protocol FSM — parity with ``apps/emqx/src/emqx_channel.erl``.

conn_state: idle → connecting → connected → (reauthenticating) →
disconnected (emqx_channel.erl:113). The channel consumes *parsed*
packets and returns (outgoing packets, actions); the connection host owns
the socket. Pipelines implemented (reference line refs):

- CONNECT: proto checks → banned check → authenticate hook →
  open_session clean/resume → CONNACK (+session-present, assigned
  clientid) (:338-420, :608-633)
- PUBLISH: quota → topic validate → authorize hook → QoS0/1/2 branches
  (:639-704, :730-757)
- SUBSCRIBE/UNSUBSCRIBE with per-filter authorize + shared-sub parse
  (:795-870)
- deliver → session window (:931-1015); keepalive; will message on
  abnormal terminate.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional

from emqx_tpu.broker.broker import Broker, ExclusiveLocked
from emqx_tpu.broker.cm import CM
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message, SubOpts, now_ms
from emqx_tpu.mqtt import packet as P
from emqx_tpu.session.session import Session, SessionError

MAX_CLIENTID_LEN = 65535


@dataclass
class ConnInfo:
    peername: str = "127.0.0.1:0"
    proto_ver: int = P.MQTT_V4
    keepalive: int = 60
    clientid: str = ""
    username: Optional[str] = None
    clean_start: bool = True
    expiry_interval_ms: int = 0
    connected_at: int = 0
    # client's announced Maximum-Packet-Size: the server MUST NOT send a
    # larger packet (MQTT5 3.1.2-25); 0 = no limit announced
    max_packet_out: int = 0


@dataclass
class Will:
    msg: Message
    delay_ms: int = 0


class Channel:
    def __init__(
        self,
        broker: Broker,
        cm: CM,
        conninfo: Optional[ConnInfo] = None,
        max_packet_size: int = 1 << 20,
        session_opts: Optional[dict] = None,
        mountpoint: str = "",
        send=None,
        publish_sink=None,
    ) -> None:
        self.broker = broker
        self.cm = cm
        self.hooks: Hooks = broker.hooks
        self.conninfo = conninfo or ConnInfo()
        self.conn_state = "idle"
        self.session: Optional[Session] = None
        self.will: Optional[Will] = None
        self.alias_in: dict[int, str] = {}        # MQTT5 topic aliases (in)
        # outbound aliasing (server→client): bounded by the client's
        # announced Topic-Alias-Maximum; assignment is first-come-keep
        self.alias_out: dict[str, int] = {}
        self.alias_out_max = 0
        self.session_opts = session_opts or {}
        self.mountpoint = mountpoint
        self.last_packet_at = now_ms()
        self.takeover_to: Optional[str] = None
        # the connection host's "write to my socket"; without one, packets
        # accumulate in outbox for the host to drain
        self.outbox: list[P.Packet] = []
        self._send = send if send is not None else self.outbox.extend
        # device-path seam: when the host wires a PublishPipeline sink,
        # publishes coalesce into batched kernel launches instead of the
        # per-message host walk (broker/pipeline.py)
        self.publish_sink = publish_sink
        self.pending_will_at: Optional[int] = None   # MQTT5 will-delay
        self.session_expire_at: Optional[int] = None  # disconnected TTL

    def send(self, pkts: list[P.Packet]) -> None:
        if pkts:
            self._send(pkts)

    def _publish_and_dispatch(self, msg: Message) -> None:
        """Publish + fan deliveries out to the target channels' sockets
        (the process-boundary send in the reference, emqx_broker.erl:546).
        With a publish_sink, the message joins the next device batch; acks
        don't depend on fan-out, so the FSM's replies are unchanged."""
        if self.publish_sink is not None:
            self.publish_sink(msg)
            return
        deliveries = self.broker.publish(msg)
        self.cm.dispatch(deliveries)

    # -- helpers -----------------------------------------------------------

    @property
    def clientid(self) -> str:
        return self.conninfo.clientid

    def _v5(self) -> bool:
        return self.conninfo.proto_ver == P.MQTT_V5

    def _mount(self, topic: str) -> str:
        if not self.mountpoint:
            return topic
        return T.feed_var(self.mountpoint, {
            "%c": self.clientid, "%u": self.conninfo.username or "",
        }) + topic

    def _unmount(self, topic: str) -> str:
        if not self.mountpoint:
            return topic
        mp = T.feed_var(self.mountpoint, {
            "%c": self.clientid, "%u": self.conninfo.username or "",
        })
        return topic[len(mp):] if topic.startswith(mp) else topic

    # -- main entry --------------------------------------------------------

    def handle_in(self, pkt: P.Packet) -> list[P.Packet]:
        self.last_packet_at = now_ms()
        if self.conn_state == "idle" and pkt.type != P.CONNECT:
            raise P.FrameError("first packet must be CONNECT",
                               P.RC_PROTOCOL_ERROR)
        if self.conn_state == "connected" and pkt.type == P.CONNECT:
            raise P.FrameError("duplicate CONNECT", P.RC_PROTOCOL_ERROR)
        handler = {
            P.CONNECT: self._in_connect,
            P.PUBLISH: self._in_publish,
            P.PUBACK: self._in_puback,
            P.PUBREC: self._in_pubrec,
            P.PUBREL: self._in_pubrel,
            P.PUBCOMP: self._in_pubcomp,
            P.SUBSCRIBE: self._in_subscribe,
            P.UNSUBSCRIBE: self._in_unsubscribe,
            P.PINGREQ: lambda _: [P.PingResp()],
            P.DISCONNECT: self._in_disconnect,
            P.AUTH: self._in_auth,
        }.get(pkt.type)
        if handler is None:
            raise P.FrameError(f"unexpected packet {pkt.type}",
                               P.RC_PROTOCOL_ERROR)
        return handler(pkt)

    # -- CONNECT -----------------------------------------------------------

    def _in_connect(self, pkt: P.Connect) -> list[P.Packet]:
        self.conn_state = "connecting"
        ci = self.conninfo
        ci.proto_ver = pkt.proto_ver
        ci.keepalive = pkt.keepalive
        ci.username = pkt.username
        ci.clean_start = pkt.clean_start
        if pkt.proto_ver not in (P.MQTT_V3, P.MQTT_V4, P.MQTT_V5):
            return self._connack_error(P.RC_UNSUPPORTED_PROTOCOL_VERSION)
        clientid = pkt.clientid
        assigned = None
        if not clientid:
            if not pkt.clean_start and pkt.proto_ver != P.MQTT_V5:
                return self._connack_error(P.RC_CLIENT_IDENTIFIER_NOT_VALID)
            assigned = clientid = f"emqx_tpu_{now_ms():x}_{id(self) & 0xFFFF:x}"
        if len(clientid) > MAX_CLIENTID_LEN:
            return self._connack_error(P.RC_CLIENT_IDENTIFIER_NOT_VALID)
        ci.clientid = clientid

        # banned check ('client.connect' hook may also deny)
        deny = self.hooks.run_fold(
            "client.connect", (dict(clientid=clientid,
                                    username=pkt.username,
                                    peername=ci.peername),), None)
        if deny is not None and deny != P.RC_SUCCESS:
            return self._connack_error(deny)

        # authenticate chain (emqx_channel.erl:374-419 → authn hook)
        auth_result = self.hooks.run_fold(
            "client.authenticate",
            (dict(clientid=clientid, username=pkt.username,
                  password=pkt.password, peername=ci.peername,
                  proto_ver=pkt.proto_ver),),
            {"result": "ok"},
        )
        if auth_result.get("result") != "ok":
            self.hooks.run("client.connack",
                           (ci, P.RC_NOT_AUTHORIZED))
            return self._connack_error(
                auth_result.get("rc", P.RC_NOT_AUTHORIZED))

        if pkt.proto_ver == P.MQTT_V5:
            self.alias_out_max = int(
                (pkt.properties or {}).get("Topic-Alias-Maximum", 0) or 0)
        max_qos = getattr(self.broker, "max_qos_allowed", 2)
        if pkt.will_flag and pkt.will_qos > max_qos:
            # [MQTT-3.2.2-12]: a will above the advertised cap is a
            # connect-time refusal, not a later disconnect
            return self._connack_error(P.RC_QOS_NOT_SUPPORTED)

        # will message
        if pkt.will_flag:
            self.will = Will(
                msg=Message(
                    topic=self._mount(pkt.will_topic),
                    payload=pkt.will_payload or b"",
                    qos=pkt.will_qos,
                    from_=clientid,
                    flags={"retain": pkt.will_retain},
                    headers={"properties": pkt.will_props or {}},
                ),
                delay_ms=1000 * (pkt.will_props or {}).get(
                    "Will-Delay-Interval", 0),
            )

        # session open / takeover (emqx_cm analogue)
        expiry = (pkt.properties or {}).get("Session-Expiry-Interval")
        if expiry is None:
            # v3: clean_start=false means "keep forever"; v5 default is 0
            expiry = (
                0xFFFFFFFF
                if pkt.proto_ver != P.MQTT_V5 and not pkt.clean_start
                else 0
            )
        ci.expiry_interval_ms = int(expiry) * 1000
        session, present, pending = self.cm.open_session(
            pkt.clean_start, clientid, self, self.session_opts
        )
        self.session = session
        # the client's Maximum-Packet-Size caps every packet we send
        # (enforced at serialization by the connection host)
        mps = (pkt.properties or {}).get("Maximum-Packet-Size")
        if mps:
            ci.max_packet_out = int(mps)
        # client flow control: its Receive-Maximum caps our send window
        # (MQTT5 3.1.2-11; reference folds it into the inflight limit)
        rm = (pkt.properties or {}).get("Receive-Maximum")
        if rm:
            session.max_inflight = max(1, min(session.max_inflight, int(rm)))
            session.inflight.max_size = session.max_inflight
        # restart-resume: the store prefilled session.subscriptions —
        # rebuild the broker's routes/tables for any not already live
        for sub_topic, sub_opts in list(session.subscriptions.items()):
            if (clientid, sub_topic) not in self.broker.suboption:
                try:
                    self.broker.subscribe(clientid, sub_topic, sub_opts,
                                          restore=True)
                except ExclusiveLocked:
                    # the $exclusive topic was claimed while we were away:
                    # degrade that one subscription, never the whole resume
                    session.subscriptions.pop(sub_topic, None)
        ci.connected_at = now_ms()
        self.conn_state = "connected"
        self.hooks.run("client.connected", (ci,))

        out: list[P.Packet] = []
        props: dict[str, Any] = {}
        if assigned is not None and self._v5():
            props["Assigned-Client-Identifier"] = assigned
        if self._v5():
            # server capability advertisement (emqx_channel connack props)
            props["Receive-Maximum"] = session.max_inflight
            props["Topic-Alias-Maximum"] = 65535   # inbound aliases accepted
            if max_qos < 2:
                props["Maximum-QoS"] = max_qos     # [MQTT-3.2.2-9]
            if not self.broker.shared_dispatch:
                props["Shared-Subscription-Available"] = 0
        connack = P.Connack(
            session_present=present, reason_code=P.RC_SUCCESS,
            properties=props,
        )
        self.hooks.run("client.connack", (ci, P.RC_SUCCESS))
        out.append(connack)
        # resume: replay pending messages through the fresh window
        if pending:
            deliveries = [
                (m.headers.get("sub_topic", m.topic), m) for m in pending
            ]
            out.extend(self._postprocess_out(session.deliver(deliveries)))
            self.hooks.run("session.resumed", (clientid,))
        return out

    def _connack_error(self, rc: int) -> list[P.Packet]:
        self.conn_state = "disconnected"
        if not self._v5() and rc > 0x80:
            # map v5 codes onto v3 connack codes (emqx_reason_codes:compat)
            rc3 = {
                P.RC_UNSUPPORTED_PROTOCOL_VERSION: 1,
                P.RC_CLIENT_IDENTIFIER_NOT_VALID: 2,
                P.RC_SERVER_UNAVAILABLE: 3,
                P.RC_BAD_USER_NAME_OR_PASSWORD: 4,
                P.RC_NOT_AUTHORIZED: 5,
                P.RC_BANNED: 5,
            }.get(rc, 5)
            return [P.Connack(reason_code=rc3)]
        return [P.Connack(reason_code=rc)]

    # -- PUBLISH (emqx_channel.erl:639-757) ---------------------------------

    def _in_publish(self, pkt: P.Publish) -> list[P.Packet]:
        topic = pkt.topic
        # MQTT5 topic alias resolution
        alias = (pkt.properties or {}).get("Topic-Alias")
        if alias is not None:
            if alias == 0:
                raise P.FrameError("topic alias 0", P.RC_TOPIC_ALIAS_INVALID)
            if topic:
                self.alias_in[alias] = topic
            else:
                topic = self.alias_in.get(alias)
                if topic is None:
                    raise P.FrameError("unknown topic alias",
                                       P.RC_PROTOCOL_ERROR)
        if not T.validate_name(topic):
            # wildcard/invalid topic NAME is a protocol violation, not a
            # deliverable error: the reference disconnects with 0x90
            # (emqx_mqtt_protocol_v5_SUITE t_publish_wildtopic)
            raise P.FrameError("invalid topic name",
                               P.RC_TOPIC_NAME_INVALID)
        if pkt.qos > getattr(self.broker, "max_qos_allowed", 2):
            # [MQTT-3.2.2-11]: DISCONNECT 0x9B, not a puback error
            raise P.FrameError("qos not supported",
                               P.RC_QOS_NOT_SUPPORTED)

        mounted = self._mount(topic)
        # authorize (client.authorize hook fold: allow | deny)
        verdict = self.hooks.run_fold(
            "client.authorize",
            (dict(clientid=self.clientid, username=self.conninfo.username,
                  peername=self.conninfo.peername),
             "publish", mounted),
            "allow",
        )
        if verdict != "allow":
            self.hooks.run("message.dropped.authz", (mounted,))
            return self._puberr(pkt, P.RC_NOT_AUTHORIZED)

        # Topic-Alias is CONNECTION-scoped [MQTT-3.3.2-7]: forwarding the
        # publisher's inbound alias would hand subscribers an alias THEY
        # never negotiated (their own aliasing happens in
        # _postprocess_out against their announced maximum)
        fwd_props = dict(pkt.properties or {})
        fwd_props.pop("Topic-Alias", None)
        msg = Message(
            topic=mounted, payload=pkt.payload, qos=pkt.qos,
            from_=self.clientid,
            flags={"retain": pkt.retain, "dup": pkt.dup},
            headers={
                "properties": fwd_props,
                "username": self.conninfo.username,
                "peername": self.conninfo.peername,
                "protocol": "mqtt",
            },
        )
        if pkt.qos == 0:
            self._publish_and_dispatch(msg)
            return []
        if pkt.qos == 1:
            self._publish_and_dispatch(msg)
            return [P.PubAck(packet_id=pkt.packet_id)]
        # QoS2: exactly-once receive
        try:
            self.session.publish_in(pkt.packet_id, msg)
        except SessionError as e:
            return [P.PubRec(packet_id=pkt.packet_id, reason_code=e.rc)]
        self._publish_and_dispatch(msg)
        return [P.PubRec(packet_id=pkt.packet_id)]

    def _puberr(self, pkt: P.Publish, rc: int) -> list[P.Packet]:
        if pkt.qos == 1:
            return [P.PubAck(packet_id=pkt.packet_id, reason_code=rc)]
        if pkt.qos == 2:
            return [P.PubRec(packet_id=pkt.packet_id, reason_code=rc)]
        return []  # QoS0 errors are silent (no ack slot to carry the rc)

    # -- acks ---------------------------------------------------------------

    def _postprocess_out(self, pkts: list[P.Packet]) -> list[P.Packet]:
        """Unmount topics + fire message.delivered for outgoing PUBLISHes —
        every path that emits them (deliver, dequeue, retry) goes through
        here so the internal mounted namespace never leaks to the client."""
        for pkt in pkts:
            if isinstance(pkt, P.Publish):
                pkt.topic = self._unmount(pkt.topic)
                self.hooks.run(
                    "message.delivered", (self.clientid, pkt.topic)
                )
                if self.alias_out_max and pkt.topic and self._v5():
                    # outbound alias ([MQTT-3.3.2] server side): known
                    # topic → alias with empty name; room left → assign
                    # and send alias WITH the full name this once
                    a = self.alias_out.get(pkt.topic)
                    if a is not None:
                        pkt.properties = {**(pkt.properties or {}),
                                          "Topic-Alias": a}
                        pkt.topic = ""
                    elif len(self.alias_out) < self.alias_out_max:
                        a = len(self.alias_out) + 1
                        self.alias_out[pkt.topic] = a
                        pkt.properties = {**(pkt.properties or {}),
                                          "Topic-Alias": a}
        return pkts

    def _in_puback(self, pkt: P.PubAck) -> list[P.Packet]:
        try:
            out = self.session.puback(pkt.packet_id)
            self.hooks.run("message.acked", (self.clientid, pkt.packet_id))
            return self._postprocess_out(out)
        except SessionError:
            return []

    def _in_pubrec(self, pkt: P.PubRec) -> list[P.Packet]:
        try:
            if pkt.reason_code >= 0x80:
                # receiver refused: drop the inflight entry
                self.session.inflight.delete(pkt.packet_id)
                return []
            return [self.session.pubrec(pkt.packet_id)]
        except SessionError as e:
            return [P.PubRel(packet_id=pkt.packet_id, reason_code=e.rc)]

    def _in_pubrel(self, pkt: P.PubRel) -> list[P.Packet]:
        try:
            self.session.pubrel_in(pkt.packet_id)
            return [P.PubComp(packet_id=pkt.packet_id)]
        except SessionError as e:
            return [P.PubComp(packet_id=pkt.packet_id, reason_code=e.rc)]

    def _in_pubcomp(self, pkt: P.PubComp) -> list[P.Packet]:
        try:
            return self._postprocess_out(self.session.pubcomp(pkt.packet_id))
        except SessionError:
            return []

    # -- SUBSCRIBE / UNSUBSCRIBE -------------------------------------------

    def _in_subscribe(self, pkt: P.Subscribe) -> list[P.Packet]:
        rcs: list[int] = []
        subid = (pkt.properties or {}).get("Subscription-Identifier")
        if isinstance(subid, list):
            subid = subid[0] if subid else None
        # client.subscribe fold: rewrite/veto filters before processing
        # (emqx_rewrite registers here, emqx_rewrite.erl:101-102)
        topic_filters = self.hooks.run_fold(
            "client.subscribe",
            (dict(clientid=self.clientid,
                  username=self.conninfo.username),
             pkt.properties or {}),
            pkt.topic_filters,
        )
        for filt, opts in topic_filters:
            group, real = T.parse_share(filt)
            exclusive = False
            if not group:
                # $exclusive/t → exclusive flag + real topic t
                # (emqx_topic.erl:225-230 parse)
                exclusive, real = T.parse_exclusive(real)
            if exclusive and not self.broker.exclusive_enabled:
                # cap disabled → invalid filter (emqx_mqtt_caps:do_check_sub)
                rcs.append(P.RC_TOPIC_FILTER_INVALID)
                continue
            if not T.validate_filter(real):
                rcs.append(P.RC_TOPIC_FILTER_INVALID)
                continue
            if group and opts.get("nl"):
                # shared subs must not set no-local (MQTT5 spec)
                rcs.append(P.RC_PROTOCOL_ERROR)
                continue
            # mount only the real topic: '$share/g/t' in namespace 'ns/'
            # becomes '$share/g/ns/t' (the reference mounts after share
            # parsing for the same reason)
            mounted_real = self._mount(real)
            mounted_key = (
                f"{T.SHARE_PREFIX}/{group}/{mounted_real}" if group
                else mounted_real
            )
            verdict = self.hooks.run_fold(
                "client.authorize",
                (dict(clientid=self.clientid,
                      username=self.conninfo.username,
                      peername=self.conninfo.peername),
                 "subscribe", mounted_real),
                "allow",
            )
            if verdict != "allow":
                rcs.append(P.RC_NOT_AUTHORIZED)
                continue
            subopts = SubOpts(
                qos=opts.get("qos", 0), nl=opts.get("nl", 0),
                rap=opts.get("rap", 0), rh=opts.get("rh", 0),
                share=group, subid=subid, exclusive=exclusive,
            )
            # remember any prior subscription to this key so a rejected
            # exclusive upgrade can roll back without destroying it
            prior_opts = self.session.subscriptions.get(mounted_key)
            try:
                self.session.subscribe(mounted_key, subopts)
            except SessionError as e:
                rcs.append(e.rc)
                continue
            try:
                self.broker.subscribe(self.clientid, mounted_key, subopts)
            except ExclusiveLocked:
                # $exclusive/... already held → 0x97, same rc the
                # reference returns (emqx_exclusive_subscription.erl)
                if prior_opts is not None:
                    self.session.subscriptions[mounted_key] = prior_opts
                else:
                    self.session.unsubscribe(mounted_key)
                rcs.append(P.RC_QUOTA_EXCEEDED)
                continue
            rcs.append(subopts.qos)  # granted qos
        return [P.SubAck(packet_id=pkt.packet_id, reason_codes=rcs)]

    def _in_unsubscribe(self, pkt: P.Unsubscribe) -> list[P.Packet]:
        rcs: list[int] = []
        topic_filters = self.hooks.run_fold(
            "client.unsubscribe",
            (dict(clientid=self.clientid,
                  username=self.conninfo.username),
             pkt.properties or {}),
            pkt.topic_filters,
        )
        for filt in topic_filters:
            group, real = T.parse_share(filt)
            if not group:
                _excl, real = T.parse_exclusive(real)
            mounted_real = self._mount(real)
            mounted_key = (
                f"{T.SHARE_PREFIX}/{group}/{mounted_real}" if group
                else mounted_real
            )
            try:
                self.session.unsubscribe(mounted_key)
                self.broker.unsubscribe(self.clientid, mounted_key)
                rcs.append(P.RC_SUCCESS)
            except SessionError as e:
                rcs.append(e.rc)
        return [P.UnsubAck(packet_id=pkt.packet_id, reason_codes=rcs)]

    # -- DISCONNECT / AUTH --------------------------------------------------

    def _in_disconnect(self, pkt: P.Disconnect) -> list[P.Packet]:
        if pkt.reason_code == P.RC_SUCCESS:
            self.will = None        # normal disconnect discards the will
        expiry = (pkt.properties or {}).get("Session-Expiry-Interval")
        if expiry is not None:
            self.conninfo.expiry_interval_ms = int(expiry) * 1000
        self.terminate("normal" if pkt.reason_code == P.RC_SUCCESS
                       else "client_disconnect")
        return []

    def _in_auth(self, pkt: P.Auth) -> list[P.Packet]:
        # enhanced auth continuation — delegated to the authn chain
        self.conn_state = "reauthenticating"
        result = self.hooks.run_fold(
            "client.reauthenticate",
            (dict(clientid=self.clientid), pkt.properties),
            {"result": "ok"},
        )
        self.conn_state = "connected"
        if result.get("result") != "ok":
            return [P.Disconnect(reason_code=P.RC_NOT_AUTHORIZED)]
        return [P.Auth(reason_code=P.RC_SUCCESS)]

    # -- broker → client ----------------------------------------------------

    def handle_deliver(
        self, deliveries: list[tuple[str, Message]]
    ) -> list[P.Packet]:
        if self.conn_state != "connected" or self.session is None:
            for sub_topic, msg in deliveries:
                if self.session is not None:
                    self.session.enqueue(sub_topic, msg)
            return []
        out = self._postprocess_out(self.session.deliver(list(deliveries)))
        # per-delivery latency from the deliver-begin stamp (the
        # reference's mark_begin_deliver, emqx_session.erl:908) → slow_subs
        now = now_ms()
        for sub_topic, msg in deliveries:
            begin = msg.extra.get("deliver_begin_at", msg.timestamp)
            self.hooks.run(
                "delivery.completed",
                (self.clientid, msg.topic, now - begin))
        return out

    # -- timers -------------------------------------------------------------

    def keepalive_expired(self, now: Optional[int] = None) -> bool:
        """1.5 × keepalive with no inbound packet (emqx_keepalive)."""
        if self.conninfo.keepalive == 0 or self.conn_state != "connected":
            return False
        now = now_ms() if now is None else now
        return now - self.last_packet_at > self.conninfo.keepalive * 1500

    def handle_timeout(self, kind: str,
                       now: Optional[int] = None) -> list[P.Packet]:
        if self.session is None:
            return []
        if kind == "retry":
            return self._postprocess_out(self.session.retry(now))
        if kind == "expire_awaiting_rel":
            self.session.expire_awaiting_rel(now)
        return []

    # -- lifecycle ----------------------------------------------------------

    def takeover(self) -> tuple[Optional[Session], list[Message]]:
        """Yield the session to a resuming channel; this channel dies
        (emqx_channel:handle_call takeover / emqx_cm.erl 2-phase)."""
        session = self.session
        pending = session.take_pending() if session else []
        self.conn_state = "disconnected"
        self.session = None
        # resuming in time cancels a delayed will (MQTT5 3.1.3.2.2)
        self.pending_will_at = None
        self.will = None
        self.hooks.run("session.takenover", (self.clientid,))
        return session, pending

    def will_tick(self, now: Optional[int] = None) -> None:
        """Fire a due delayed will (driven by the app housekeeping timer)."""
        if self.pending_will_at is None or self.will is None:
            return
        now = now_ms() if now is None else now
        if now >= self.pending_will_at:
            self._publish_and_dispatch(self.will.msg)
            self.will = None
            self.pending_will_at = None

    def discard(self) -> None:
        """Kicked by a clean-start connect or admin (RC 0x8E). Unlike
        takeover, the session state dies — clean its broker footprint
        (routes/subscriber sets/model slots) or they leak forever."""
        self.conn_state = "disconnected"
        if self.session is not None:
            self.broker.subscriber_down(self.clientid)
            self.session = None
        self.cm.unregister_channel(self.clientid, self)
        self.hooks.run("session.discarded", (self.clientid,))

    def terminate(self, reason: str) -> None:
        if self.conn_state == "disconnected":
            return
        self.conn_state = "disconnected"
        if self.will is not None and reason != "normal":
            if (
                self.will.delay_ms > 0
                and self.conninfo.expiry_interval_ms > 0
            ):
                # MQTT5 Will Delay: withhold; cancelled if the session is
                # resumed before it fires (will_tick / takeover). The will
                # MUST be published no later than session end (MQTT5
                # 3.1.2.5: earlier of Will Delay and Session Expiry), so
                # the delay caps at the expiry interval.
                self.pending_will_at = now_ms() + min(
                    self.will.delay_ms, self.conninfo.expiry_interval_ms)
            else:
                self._publish_and_dispatch(self.will.msg)
                self.will = None
        if self.conninfo.expiry_interval_ms == 0:
            # session dies with the connection
            if self.session is not None:
                self.broker.subscriber_down(self.clientid)
                self.hooks.run("session.terminated", (self.clientid, reason))
                self.session = None
            self.cm.unregister_channel(self.clientid, self)
        else:
            # stay registered as a disconnected channel holding the
            # session until expiry/resume (the reference keeps the channel
            # process alive in this state, emqx_channel.erl disconnected);
            # the deadline is enforced by expire_tick
            self.session_expire_at = (
                now_ms() + self.conninfo.expiry_interval_ms)
        self.hooks.run("client.disconnected", (self.conninfo, reason))

    def expire_tick(self, now: Optional[int] = None) -> bool:
        """Enforce a disconnected channel's session-expiry deadline
        (MQTT5 3.1.2-23: session state MUST be discarded when the
        interval elapses). Returns True when the session expired."""
        if (self.conn_state != "disconnected"
                or self.session is None
                or self.session_expire_at is None):
            return False
        now = now_ms() if now is None else now
        if now < self.session_expire_at:
            return False
        # a still-pending delayed will fires at session end at the latest
        if self.will is not None and self.pending_will_at is not None:
            self._publish_and_dispatch(self.will.msg)
            self.will = None
            self.pending_will_at = None
        self.broker.subscriber_down(self.clientid)
        self.hooks.run("session.terminated", (self.clientid, "expired"))
        self.session = None
        self.session_expire_at = None
        self.cm.unregister_channel(self.clientid, self)
        return True
