from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.broker import Broker

__all__ = ["Hooks", "Broker"]
