"""Hook registry — parity with ``apps/emqx/src/emqx_hooks.erl``.

Named hookpoints hold priority-ordered callback chains; ``run`` executes
for side effects with stop semantics, ``run_fold`` threads an accumulator
(emqx_hooks.erl:156-193). Priorities sort descending, ties in insertion
order. A callback returns:

- ``None``               → continue (acc unchanged in run_fold)
- ``Hooks.STOP``         → stop the chain
- ``(Hooks.STOP, acc)``  → stop with new acc (run_fold)
- ``(Hooks.OK, acc)``    → continue with new acc (run_fold)

Standard hookpoints (emqx_hooks.hrl): client.connect/connack/connected/
disconnected/authenticate/authorize/subscribe/unsubscribe,
session.created/subscribed/unsubscribed/resumed/discarded/takenover/
terminated, message.publish/delivered/acked/dropped.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any, Callable, Optional

log = logging.getLogger(__name__)


@dataclass
class _Callback:
    fn: Callable
    priority: int
    seq: int


class Hooks:
    STOP = object()
    OK = object()

    def __init__(self) -> None:
        self._hooks: dict[str, list[_Callback]] = {}
        self._seq = 0
        self._lock = threading.RLock()

    def add(self, name: str, fn: Callable, priority: int = 0) -> None:
        with self._lock:
            self._seq += 1
            chain = self._hooks.setdefault(name, [])
            if any(cb.fn is fn for cb in chain):
                return  # emqx_hooks:add is idempotent per callback
            chain.append(_Callback(fn, priority, self._seq))
            chain.sort(key=lambda cb: (-cb.priority, cb.seq))

    def put(self, name: str, fn: Callable, priority: int = 0) -> None:
        """add-or-replace (emqx_hooks:put)."""
        self.delete(name, fn)
        self.add(name, fn, priority)

    def delete(self, name: str, fn: Callable) -> None:
        with self._lock:
            chain = self._hooks.get(name)
            if chain:
                chain[:] = [cb for cb in chain if cb.fn is not fn]

    def run(self, name: str, args: tuple = ()) -> None:
        for cb in self._chain(name):
            try:
                ret = cb.fn(*args)
            except Exception:
                # a crashing callback must not break the chain or kill
                # the caller (emqx_hooks wraps every callback the same
                # way: log and continue)
                log.exception("hook %s callback %r crashed", name, cb.fn)
                continue
            if ret is Hooks.STOP:
                return

    # folds whose accumulator is a security verdict: a crashing callback
    # must abort the operation (fail closed), not fall through to the
    # permissive default accumulator
    FAIL_CLOSED = frozenset({"client.authenticate", "client.authorize"})

    def run_fold(self, name: str, args: tuple, acc: Any) -> Any:
        for cb in self._chain(name):
            try:
                ret = cb.fn(*args, acc)
            except Exception:
                log.exception("hook %s callback %r crashed", name, cb.fn)
                if name in self.FAIL_CLOSED:
                    raise
                continue
            if ret is None:
                continue
            if ret is Hooks.STOP:
                return acc
            if isinstance(ret, tuple) and len(ret) == 2:
                tag, acc2 = ret
                if tag is Hooks.STOP:
                    return acc2
                if tag is Hooks.OK:
                    acc = acc2
                    continue
            acc = ret  # plain value → new acc
        return acc

    def _chain(self, name: str) -> list[_Callback]:
        with self._lock:
            return list(self._hooks.get(name, ()))
