"""The pub/sub fabric — parity with ``apps/emqx/src/emqx_broker.erl``.

Node-local subscription tables + the publish pipeline:

- ``suboption``   {(sid, topic) → SubOpts}   (emqx_broker.erl:105-118)
- ``subscription`` {sid → set(topic)}
- ``subscriber``   {topic → set(sid)}
- publish pipeline: 'message.publish' hook fold → route match → dispatch
  (:218-232, :284-300), remote routes handed to the cluster plane
- subscriber slots: every local subscriber id (session) gets a bitmap
  slot so the device fan-out can address it; slots are recycled on
  subscriber_down (the emqx_broker_helper shard-assignment analogue)

Two read paths share one source of truth (the Router's trie):

- ``publish``        host path, one message (the oracle walk)
- ``publish_batch``  device path, a topic batch through RouterModel —
  the {active,N}-style coalescing surface the connection host feeds

Delivery is returned, not performed: ``{sid: [(sub_topic, Message)]}`` —
the channel/connection layer owns sockets (process boundary in the
reference, function boundary here).
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Sequence

from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message, SubOpts
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.router.router import Router

Sid = str  # subscriber id (session/clientid)


class SlotRegistry:
    """sid ↔ bitmap-slot allocation over a FIXED shard space.

    The emqx_broker_helper.erl:55,82-92 discipline, TPU-shaped: while
    unique slots remain, each sid owns one (exact decode, no false
    positives); past capacity, new sids hash into the same [0, capacity)
    space and a slot becomes a subscriber *shard* — decode filters
    candidates through the suboption table.  Capacity never grows, so
    the device-side structures are fixed-size at 10M subscribers and no
    capacity-doubling rebuild stall exists (round-1 weak #4)."""

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._slot_of: dict[Sid, int] = {}
        self._sids_of: dict[int, set[Sid]] = {}
        self._free: list[int] = []
        self._next = 0

    @staticmethod
    def _hash(sid: Sid) -> int:
        # stable across processes (phash2 analogue); Python's hash() is
        # salted per-process and would break cluster-symmetric decode
        import zlib
        return zlib.crc32(sid.encode() if isinstance(sid, str) else sid)

    def get_or_assign(self, sid: Sid) -> int:
        slot = self._slot_of.get(sid)
        if slot is not None:
            return slot
        if self._free:
            slot = self._free.pop()
        elif self._next < self.capacity:
            slot = self._next
            self._next += 1
        else:
            slot = self._hash(sid) % self.capacity
        self._slot_of[sid] = slot
        self._sids_of.setdefault(slot, set()).add(sid)
        return slot

    def lookup_sids(self, slot: int):
        """All sids sharing the slot (1 in the unique regime)."""
        return self._sids_of.get(slot, ())

    def lookup_slot(self, sid: Sid) -> Optional[int]:
        return self._slot_of.get(sid)

    def release(self, sid: Sid) -> Optional[int]:
        slot = self._slot_of.pop(sid, None)
        if slot is not None:
            sids = self._sids_of.get(slot)
            if sids is not None:
                sids.discard(sid)
                if not sids:
                    del self._sids_of[slot]
                    self._free.append(slot)
        return slot

    def slot_count(self) -> int:
        return self._next


class ExclusiveLocked(Exception):
    """$exclusive/... topic already held by another subscriber."""

    def __init__(self, topic: str, holder: Sid) -> None:
        super().__init__(f"{topic} exclusively held by {holder}")
        self.topic, self.holder = topic, holder


class Broker:
    """Single-node pub/sub core; the cluster plane plugs in via
    ``forward_fn`` (gen_rpc analogue) for remote-node routes."""

    def __init__(
        self,
        node: str = "node1",
        hooks: Optional[Hooks] = None,
        router: Optional[Router] = None,
        router_model=None,       # emqx_tpu.models.RouterModel (device path)
        forward_fn=None,         # fn(node, delivery) for remote routes
        shared_dispatch=None,    # fn(group, topic, msg) -> [(sid, sub_topic)]
        metrics=None,            # observe.metrics.Metrics (shared node-wide)
    ) -> None:
        self.node = node
        self.hooks = hooks or Hooks()
        self.router = router or Router()
        self.model = router_model
        self.forward_fn = forward_fn
        self.shared_dispatch = shared_dispatch
        # batched variant (app._shared_dispatch_batch → SharedSub.
        # dispatch_batch): one lock hold for ALL of a publish batch's
        # shared legs instead of a dispatch per message (VERDICT r3 #7)
        self.shared_dispatch_batch = None
        # device co-batching sink for the rule engine (config 5): called
        # with (msg, matched_filters) after the kernel, or (msg, None)
        # for fallback topics the kernel couldn't cover; rules_gate_fn
        # brackets the batch's hook fold (RuleEngine.publish_gate)
        self.rules_matched_fn = None
        self.rules_gate_fn = None
        # degradation ledger (round 13, set by the app): device-loss
        # failovers record a structured reason event next to the
        # messages.device_failover counter
        self.ledger = None
        self.slots = SlotRegistry(
            capacity=router_model.n_sub_slots
            if router_model is not None else 8192)
        self._lock = threading.RLock()
        self.suboption: dict[tuple[Sid, str], SubOpts] = {}
        self.subscription: dict[Sid, set[str]] = {}
        self.subscriber: dict[str, set[Sid]] = {}
        # $exclusive/... topics: one subscriber at a time
        # (emqx_exclusive_subscription.erl — a mnesia transaction there).
        # This map covers LOCAL holders; cluster-wide exclusivity is the
        # exclusive_try_fn/exclusive_release_fn seam that ClusterNode
        # wires to a peer-confirmed acquire (cluster/node.py), mirroring
        # the reference's cluster-wide try_subscribe txn.  Standalone
        # (fn unset) the lock is node-local.
        # Gated by the mqtt.exclusive_subscription cap (emqx_mqtt_caps).
        self.exclusive: dict[str, Sid] = {}
        self.exclusive_enabled = True
        # mqtt.max_qos_allowed zone cap (emqx_mqtt_caps): <2 is
        # advertised in CONNACK Maximum-QoS and enforced on PUBLISH
        # ([MQTT-3.2.2-11]) and will qos ([MQTT-3.2.2-12])
        self.max_qos_allowed = 2
        self.exclusive_try_fn = None      # fn(topic, sid) -> Optional[holder]
        self.exclusive_release_fn = None  # fn(topic, sid)
        if metrics is None:
            from emqx_tpu.observe.metrics import Metrics
            metrics = Metrics()
        self.metrics = metrics
        # subscription observers: fn(op, sid, topic, opts) with op in
        # {"add", "del"}, fired on EVERY table change including
        # restore=True resumes (unlike the 'session.subscribed' hook) —
        # the native host mirrors the table through this seam
        # (broker/native_server.py), so a missed event would make its
        # fast path silently skip a subscriber
        self.sub_observers: list = []

    def _inc(self, key: str, n: int = 1) -> None:
        self.metrics.inc(key, n)

    # -- subscribe / unsubscribe (emqx_broker.erl:134-173) ------------------

    def subscribe(self, sid: Sid, topic: str, opts: Optional[SubOpts] = None,
                  restore: bool = False) -> None:
        """``restore=True`` rebuilds tables/routes for a resumed session
        without firing 'session.subscribed' — a resume is not a SUBSCRIBE,
        so retained messages must not re-dispatch (MQTT5 3.8.3.1)."""
        opts = opts or SubOpts()
        group, real_topic = T.parse_share(topic)
        if group:
            opts = SubOpts(**{**opts.__dict__, "share": group})
        cluster_claimed = False
        if (not group and getattr(opts, "exclusive", False)
                and self.exclusive_try_fn is not None):
            # Cluster-wide acquire BEFORE the broker lock: the try fn does
            # peer RPC and must not run under self._lock (a peer acquiring
            # concurrently would deadlock on the crossed handler calls).
            remote_holder = self.exclusive_try_fn(topic, sid)
            if remote_holder is not None:
                raise ExclusiveLocked(topic, remote_holder)
            cluster_claimed = True
        try:
            is_new = self._subscribe_locked(sid, topic, opts, group,
                                            real_topic)
        except BaseException:
            # ANY failure after the cluster claim (a local holder beat us,
            # an invalid filter, a model slot error) must roll the claim
            # back or it leaks cluster-wide forever (excl.sync would keep
            # re-asserting it); release runs OUTSIDE the broker lock (the
            # broadcast does peer IO)
            if cluster_claimed and self.exclusive_release_fn is not None:
                self.exclusive_release_fn(topic, sid)
            raise
        for obs in self.sub_observers:
            obs("add", sid, topic, opts)
        # is_new lets rh=1 (send-retained-if-new) distinguish resubscribes
        if not restore:
            self.hooks.run("session.subscribed", (sid, topic, opts, is_new))

    def _subscribe_locked(self, sid: Sid, topic: str, opts: SubOpts,
                          group, real_topic: str) -> bool:
        with self._lock:
            if not group and getattr(opts, "exclusive", False):
                # subscription already carries the real (stripped) topic;
                # exclusivity is a lock keyed by it (try_subscribe txn,
                # emqx_exclusive_subscription.erl)
                holder = self.exclusive.get(topic)
                if holder is not None and holder != sid:
                    raise ExclusiveLocked(topic, holder)
                self.exclusive[topic] = sid
            key = (sid, topic)
            is_new = key not in self.suboption
            self.suboption[key] = opts
            self.subscription.setdefault(sid, set()).add(topic)
            if is_new:
                subs_key = real_topic if not group else topic
                subs = self.subscriber.setdefault(subs_key, set())
                first_local = not subs
                subs.add(sid)
                if group:
                    # shared subs route as {group, node}
                    # (emqx_shared_sub.erl:420); one route per group+topic
                    if first_local:
                        self.router.add_route(real_topic, (group, self.node))
                else:
                    # one (topic, node) route per topic regardless of local
                    # subscriber count (emqx_broker.erl route aggregation)
                    if first_local:
                        self.router.add_route(real_topic, self.node)
                    if self.model is not None:
                        slot = self.slots.get_or_assign(sid)
                        self.model.subscribe(real_topic, slot)
            return is_new

    def unsubscribe(self, sid: Sid, topic: str) -> bool:
        group, real_topic = T.parse_share(topic)
        release_exclusive = False
        with self._lock:
            opts = self.suboption.pop((sid, topic), None)
            if opts is None:
                return False
            if (getattr(opts, "exclusive", False)
                    and self.exclusive.get(topic) == sid):
                del self.exclusive[topic]
                # the cluster release broadcast does peer IO — deferred
                # to after the lock (a slow peer must not stall every
                # subscribe/unsubscribe on the node)
                release_exclusive = self.exclusive_release_fn is not None
            self.subscription.get(sid, set()).discard(topic)
            subs_key = real_topic if not group else topic
            subs = self.subscriber.get(subs_key)
            last_local = False
            if subs is not None:
                subs.discard(sid)
                if not subs:
                    del self.subscriber[subs_key]
                    last_local = True
            if group:
                if last_local:
                    self.router.delete_route(real_topic, (group, self.node))
            else:
                if last_local:
                    self.router.delete_route(real_topic, self.node)
                if self.model is not None:
                    # read-only lookup: a teardown path must never mint a
                    # fresh slot for an already-released sid
                    slot = self.slots.lookup_slot(sid)
                    if slot is not None:
                        self.model.unsubscribe(real_topic, slot)
        if release_exclusive:
            self.exclusive_release_fn(topic, sid)
        for obs in self.sub_observers:
            obs("del", sid, topic, opts)
        self.hooks.run("session.unsubscribed", (sid, topic))
        return True

    def subscriber_down(self, sid: Sid) -> int:
        """Batch-clean a dead subscriber (emqx_broker.erl:361-383).
        Snapshot-then-unsubscribe: each unsubscribe takes the lock itself
        so the exclusive release / hook legs run outside it.  The final
        teardown is conditional — a concurrent re-subscribe for the same
        sid (reconnect racing the old session's expiry) must keep its
        fresh subscription set and slot."""
        with self._lock:
            topics = list(self.subscription.get(sid, ()))
        for topic in topics:
            self.unsubscribe(sid, topic)
        with self._lock:
            remaining = self.subscription.get(sid)
            if remaining is not None and not remaining:
                self.subscription.pop(sid, None)
            if not self.subscription.get(sid):
                self.slots.release(sid)
        return len(topics)

    def subscriptions(self, sid: Sid) -> list[tuple[str, SubOpts]]:
        with self._lock:
            return [
                (t, self.suboption[(sid, t)])
                for t in self.subscription.get(sid, ())
            ]

    # -- publish (emqx_broker.erl:218-232) ----------------------------------

    def publish(self, msg: Message) -> dict[Sid, list[tuple[str, Message]]]:
        """Host-path publish of one message. Returns local deliveries
        {sid: [(sub_topic, msg)]}; remote routes are forwarded."""
        msg = self.hooks.run_fold("message.publish", (), msg)
        if msg is None or msg.headers.get("allow_publish") is False:
            self._inc("messages.dropped")
            return {}
        self._inc("messages.publish")
        return self._route(msg.topic, msg)

    def publish_batch(
        self, msgs: Sequence[Message]
    ) -> list[dict[Sid, list[tuple[str, Message]]]]:
        """Device-path publish: one kernel launch for the whole batch
        (falls back to the host oracle per overflow/too-long topic)."""
        return self.publish_batch_collect(self.publish_batch_submit(msgs))

    def publish_batch_submit(self, msgs: Sequence[Message],
                             force_host: bool = False):
        """Stage 1: run the publish hooks and dispatch the routing
        kernel; returns an opaque token for ``publish_batch_collect``.
        The pipeline overlaps the in-flight device step with the next
        batch's hooks (double-buffering, SURVEY §2.5-6).

        ``force_host=True`` answers from the host oracle without a
        device launch — the pipeline's small-batch latency bypass
        (below the RTT knee the oracle walk is faster than the
        dispatch; SURVEY §7 hard part (b)). Rules then match in the
        message.publish hook as on the plain host path."""
        cobatch = (not force_host
                   and self.rules_matched_fn is not None
                   and self.rules_gate_fn is not None
                   and self.model is not None)
        if cobatch:
            # the rule engine defers to the kernel's matches (delivered
            # via rules_matched_fn below) instead of matching in the
            # message.publish hook — one trie walk for fan-out AND rules.
            # Gated via thread-local state, NOT a message header: hooks
            # may store copies (delayed queue, retainer) that a header
            # would poison past this batch.
            self.rules_gate_fn(True)
        try:
            msgs = [
                self.hooks.run_fold("message.publish", (), m) for m in msgs
            ]
        finally:
            if cobatch:
                self.rules_gate_fn(False)
        live = []
        for i, m in enumerate(msgs):
            if m is None or m.headers.get("allow_publish") is False:
                self._inc("messages.dropped")     # same as publish()
                if cobatch and m is not None:
                    # host-path hook order runs rules BEFORE the deny
                    # (rules prio -50, retainer -100): a denied-but-real
                    # message still rule-matches (host trie)
                    self.rules_matched_fn(m, None)
            else:
                live.append((i, m))
        out: list[dict[Sid, list[tuple[str, Message]]]] = [{} for _ in msgs]
        if not live:
            return (msgs, live, cobatch, out, None)
        if self.model is None or force_host:
            return (msgs, live, cobatch, out, None)
        try:
            pending = self.model.publish_batch_submit(
                [m.topic for _, m in live])
        except Exception:  # noqa: BLE001 — device loss / reset / OOM
            # device-loss failover: the host oracle serves the batch
            # (pending=None token) instead of dropping it; matching is
            # replicated on the host, so only latency degrades
            self._device_failover("submit")
            return (msgs, live, cobatch, out, None)
        return (msgs, live, cobatch, out, pending)

    def _device_failover(self, stage: str) -> None:
        import logging

        self._inc("messages.device_failover")
        if self.ledger is not None:
            self.ledger.record("device_failover", 1, detail=stage)
        logging.getLogger("emqx_tpu.broker").exception(
            "device router %s failed; batch served by the host oracle",
            stage)

    def publish_batch_collect(
        self, token
    ) -> list[dict[Sid, list[tuple[str, Message]]]]:
        """Stage 2: collect a submitted batch's routing results and
        build the per-session delivery map."""
        msgs, live, cobatch, out, pending = token
        if not live:
            return out
        if pending is None:                    # host-oracle path
            for i, m in live:
                self._inc("messages.publish")
                if cobatch:
                    # cobatch with no device result = submit-side device
                    # failover: the rules deferred to the kernel, so they
                    # must re-match on the host trie here
                    self.rules_matched_fn(m, None)
                out[i] = self._route(m.topic, m)
            return out
        if isinstance(pending, tuple) and len(pending) == 2 \
                and pending[0] == "host":
            # cpu host-matcher served this batch instead of the kernel:
            # count it in its fixed slot and on the degradation ledger,
            # next to device_failover — same seam, softer reason
            self._inc("messages.kernel.hostmatch")
            if self.ledger is not None:
                self.ledger.record("kernel_hostmatch", 1,
                                   detail="cpu host dispatch")
        try:
            matched, aux, slots, fallback = self.model.publish_batch_collect(
                pending)
        except Exception:  # noqa: BLE001 — device lost mid-flight
            # collect-side failover: the submitted launch died with the
            # device; re-route the whole batch on the host oracle (rules
            # re-match on the host trie when cobatched)
            self._device_failover("collect")
            for i, m in live:
                self._inc("messages.publish")
                if cobatch:
                    self.rules_matched_fn(m, None)
                out[i] = self._route(m.topic, m)
            return out
        fb = set(fallback)
        if fb:
            # rows the kernel punted (frontier/candidate overflow or
            # too-long topic) re-route on the host oracle below; record
            # the degradation with its row count so an operator sees
            # capacity pressure before it becomes a failover
            if self.ledger is not None:
                self.ledger.record(
                    "kernel_overflow", len(fb),
                    detail="device overflow; host-oracle fallback")
            else:
                self._inc("messages.ledger.kernel_overflow", len(fb))
        batch_legs: list = []    # (out index, msg, group, route topic)
        for j, (i, m) in enumerate(live):
            self._inc("messages.publish")
            if j in fb:
                if cobatch:
                    self.rules_matched_fn(m, None)  # host-match rules
                out[i] = self._route(m.topic, m)   # oracle fallback
                continue
            if cobatch:
                # aux alone suffices: every rule FROM filter is
                # aux-registered (subscriber-shared ones included)
                self.rules_matched_fn(m, aux[j])
            deliveries: dict[Sid, list[tuple[str, Message]]] = {}
            for slot in slots[j]:
                for sid in self.slots.lookup_sids(slot):
                    for filt in matched[j]:
                        if (sid, filt) in self.suboption:
                            deliveries.setdefault(sid, []).append((filt, m))
                            self._inc("messages.delivered")
            # shared groups + remote nodes still come from the route
            # table; shared legs are COLLECTED here and dispatched once
            # for the whole batch below (one SharedSub lock hold)
            shared_legs, nonlocal_legs = self._collect_nonlocal(m.topic, m)
            for group, rtopic in shared_legs:
                batch_legs.append((i, m, group, rtopic))
            if not matched[j] and not nonlocal_legs:
                # hook/metric parity with the host path (_route): rules on
                # $events/message_dropped and dashboards keep working with
                # the device router enabled
                self._inc("messages.dropped.no_subscribers")
                self.hooks.run("message.dropped", (m, "no_subscribers"))
            out[i] = deliveries
        self._dispatch_shared_batch(batch_legs, out)
        return out

    def _collect_nonlocal(self, topic: str, msg: Message):
        """-> ([(group, route_topic)], total nonlocal legs); remote
        forwards are executed inline (they are per-destination IO, not
        strategy picks)."""
        seen_groups = set()
        shared_legs = []
        legs = 0
        for route in self.router.match_routes(topic):
            dest = route.dest
            if isinstance(dest, tuple):
                group = dest[0]
                if (group, route.topic) not in seen_groups:
                    seen_groups.add((group, route.topic))
                    legs += 1
                    shared_legs.append((group, route.topic))
            elif dest != self.node and self.forward_fn is not None:
                self.forward_fn(dest, route.topic, msg)
                self._inc("messages.forward")
                self._inc("messages.forward.slow")
                legs += 1
        return shared_legs, legs

    def _dispatch_shared_batch(self, batch_legs, out) -> None:
        if not batch_legs:
            return
        if self.shared_dispatch_batch is not None:
            results = self.shared_dispatch_batch(
                [(g, t, m) for (_i, m, g, t) in batch_legs])
        elif self.shared_dispatch is not None:
            results = [self.shared_dispatch(g, t, m)
                       for (_i, m, g, t) in batch_legs]
        else:
            return
        for (i, m, _g, _t), picks in zip(batch_legs, results):
            for sid, sub_topic in picks:
                out[i].setdefault(sid, []).append((sub_topic, m))
                self._inc("messages.delivered")

    # -- dispatch (emqx_broker.erl:264-337, :546-579) ------------------------

    def _route(self, topic: str, msg: Message) -> dict[Sid, list[tuple[str, Message]]]:
        deliveries: dict[Sid, list[tuple[str, Message]]] = {}
        routes = self.router.match_routes(topic)
        if not routes:
            self._inc("messages.dropped.no_subscribers")
            self.hooks.run("message.dropped", (msg, "no_subscribers"))
        seen_groups = set()
        for route in routes:
            dest = route.dest
            if isinstance(dest, tuple):        # ({group, node}) shared
                # one dispatch per {group, topic-filter} route: the same
                # group may subscribe via several matching filters with
                # disjoint membership lists
                group = dest[0]
                if (group, route.topic) in seen_groups:
                    continue
                seen_groups.add((group, route.topic))
                if self.shared_dispatch is not None:
                    for sid, sub_topic in self.shared_dispatch(
                        group, route.topic, msg
                    ):
                        deliveries.setdefault(sid, []).append((sub_topic, msg))
            elif dest == self.node:
                self._dispatch_local(route.topic, msg, deliveries)
            elif self.forward_fn is not None:
                self.forward_fn(dest, route.topic, msg)
                self._inc("messages.forward")
                # the slow half of the forward split: the Python
                # forward_fn lane, next to messages.forward.native
                # (trunked legs counted by the native server's merge)
                self._inc("messages.forward.slow")
        return deliveries

    def _dispatch_local(
        self, filt: str, msg: Message,
        deliveries: dict[Sid, list[tuple[str, Message]]],
    ) -> None:
        for sid in self.subscriber.get(filt, ()):
            deliveries.setdefault(sid, []).append((filt, msg))
            self._inc("messages.delivered")

