"""PublishPipeline — the {active,N}-style coalescing stage that puts the
device router on the LIVE serving path.

The reference's hot loop is one trie walk per message inside the
publishing client's process (emqx_broker.erl:218-232 via
emqx_connection.erl:132's ``{active,N}`` socket batching).  The TPU-era
shape inverts it: connections *submit* publishes into a queue; a single
flusher drains whatever accumulated — while the previous device step was
in flight — into one ``Broker.publish_batch`` kernel launch, then fans
the merged deliveries out through the CM.  Batch assembly overlaps
device execution exactly like ``{active,N}`` overlaps socket reads with
dispatch (SURVEY.md §2.5-6 pipeline parallelism).

Correctness notes:

- per-publisher ordering: FIFO queue + in-order batch results ⇒ a
  client's publishes deliver in submission order (the reference's
  per-connection ordering guarantee);
- acks don't wait: QoS1/2 acks depend only on local session state, not
  on delivery fan-out (same as the reference, where PUBACK is sent as
  soon as ``emqx_broker:publish/1`` returns and the actual subscriber
  sends are async process messages);
- hooks (`message.publish` fold: rules, retainer, delayed...) run at
  flush time inside ``publish_batch`` — same hook surface, same order.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque
from typing import Optional

from emqx_tpu.core.message import Message

log = logging.getLogger("emqx_tpu.pipeline")


class PublishPipeline:
    """Thread-safe publish coalescer over ``Broker.publish_batch``.

    Servers wire ``submit`` as the channels' ``publish_sink``; the
    asyncio host runs ``flusher()`` as a background task, the native
    host calls ``flush()`` after each poll step.
    """

    def __init__(self, broker, cm, max_batch: int = 512) -> None:
        self.broker = broker
        self.cm = cm
        self.max_batch = max_batch
        # latency policy (SURVEY §7 hard part (b), VERDICT r3 #3): a
        # batch below the knee answers from the host oracle in
        # microseconds instead of paying the device round trip.
        #   min_device_batch >= 0: fixed threshold (config
        #   router.device.min_batch); -1 (default): adaptive — the knee
        #   is device_RTT / host_cost from running EMAs of both, so a 70 ms
        #   tunneled chip floors small batches onto the host while a
        #   sub-ms local chip keeps the device path for batch >= ~100.
        self.min_device_batch = -1
        self._rtt_ema = 5e-3       # device round trip per batch (s)
        self._host_cost_ema = 6e-6 # host-oracle walk per message (s)
        self.host_batches = 0      # batches that took the bypass
        self._since_device = 0     # bypasses since the last device batch
        # in-flight launch depth (VERDICT r4 #4): on a fixed-RTT tunnel
        # the service rate is depth x max_batch / RTT — depth, not batch
        # size, is the loaded-latency lever. Config:
        # router.device.pipeline_depth.
        self.depth = 4
        # sojourn spill: a batch whose OLDEST message already waited
        # past the deadline answers from the host oracle (µs) instead
        # of joining the device queue — bounding loaded p99 near the
        # deadline. <0 = adaptive (3 x RTT EMA, floored at 30 ms).
        self.spill_ms = -1.0
        self.spilled_batches = 0
        self._q: deque[Message] = deque()
        self._lock = threading.Lock()
        # serializes concurrent consumers (the flusher task's to_thread
        # flush vs. stop()'s final drain): batches must never interleave
        # or race the model's donated device buffers
        self._consumer_lock = threading.Lock()
        self._flusher_task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._wake: Optional[asyncio.Event] = None
        self.batches = 0          # flush count (≈ kernel launches)
        self.published = 0

    # -- producer side ------------------------------------------------------

    def submit(self, msg: Message) -> None:
        with self._lock:
            self._q.append(msg)
        wake, loop = self._wake, self._loop
        if wake is not None and loop is not None:
            try:
                if asyncio.get_running_loop() is loop:
                    wake.set()
                    return
            except RuntimeError:
                pass
            try:
                loop.call_soon_threadsafe(wake.set)
            except RuntimeError:
                pass          # loop closed; stop()'s final flush drains

    def pending(self) -> int:
        with self._lock:
            return len(self._q)

    # -- consumer side ------------------------------------------------------

    def spill_deadline_ms(self) -> Optional[float]:
        """Queue-sojourn bound before a batch spills to the host
        oracle; adaptive default tracks the measured device RTT.
        ``None`` disables the implicit spill: a config that PINS the
        knee to 0 (force-kernel mode — benches and kernel-path tests
        that need every batch on the device) must not be silently
        diverted under load; an explicit spill_ms still applies."""
        if self.spill_ms >= 0:
            return self.spill_ms
        if self.min_device_batch == 0:
            return None
        return max(3e3 * self._rtt_ema, 30.0)

    def flush(self) -> int:
        """Drain the queue in ≤max_batch launches; returns messages
        flushed.  Safe from multiple consumer threads (serialized).

        Pipelined to ``depth`` in-flight launches: batches k+1..k+depth
        have their hooks+tokenize+launch run BEFORE batch k's results
        are collected, so the device round trip (~70 ms fixed on a
        tunneled TPU) overlaps both host work and the OTHER in-flight
        round trips — service rate ≈ depth × max_batch / RTT (SURVEY
        §2.5-6; VERDICT r4 #4). Collection stays in submission order,
        preserving per-publisher delivery order, and batches whose head
        message out-waited the spill deadline answer from the host
        oracle so loaded p99 stays bounded."""
        total = 0
        with self._consumer_lock:
            inflight: deque = deque()             # (batch, broker token)
            try:
                while True:
                    batch = []
                    if len(inflight) < max(1, self.depth):
                        with self._lock:
                            batch = [
                                self._q.popleft()
                                for _ in range(min(len(self._q),
                                                   self.max_batch))]
                    if batch:
                        # small batch: the host oracle answers in µs;
                        # the device RTT would dominate (latency knee)
                        bypass = len(batch) < self.device_knee()
                        if (bypass and self.min_device_batch < 0
                                and len(batch) >= 8
                                and self._since_device >= 64):
                            # adaptive mode must not ratchet one-way: a
                            # stale RTT prior that saturates the knee
                            # would otherwise never be re-measured. A
                            # periodic probe batch rides the device to
                            # refresh the EMA.
                            bypass = False
                        if not bypass:
                            deadline = self.spill_deadline_ms()
                            sojourn = time.time() * 1e3 - batch[0].timestamp
                            if deadline is not None and sojourn > deadline:
                                # the device queue is saturated: this
                                # batch's wait already ate the latency
                                # budget — the oracle answers now
                                bypass = True
                                self.spilled_batches += 1
                        if bypass:
                            self.host_batches += 1
                            self._since_device += 1
                        else:
                            self._since_device = 0
                        token = self.broker.publish_batch_submit(
                            batch, force_host=bypass)
                        if token is not None:
                            inflight.append((batch, token))
                    if inflight and (not batch
                                     or len(inflight) >= max(1, self.depth)):
                        pbatch, ptoken = inflight.popleft()
                        # counters first: an observer that saw a
                        # delivery must also see it counted (dispatch
                        # wakes sockets before this thread would
                        # otherwise increment)
                        self.batches += 1
                        total += len(pbatch)
                        self.published += len(pbatch)
                        self._collect_dispatch(ptoken)
                    if not batch and not inflight:
                        return total
            finally:
                # a raising submit/collect must not strand the OTHER,
                # already-submitted (and already-acked) batches — their
                # hooks ran and their device steps succeeded; deliver
                # them in order
                while inflight:
                    pbatch, ptoken = inflight.popleft()
                    self.batches += 1
                    self.published += len(pbatch)
                    try:
                        self._collect_dispatch(ptoken)
                    except Exception:       # noqa: BLE001
                        log.exception(
                            "pending batch collect failed; batch dropped")

    def device_knee(self) -> int:
        """Batch size below which the host oracle beats the device.
        Fixed by config (router.device.min_batch >= 0) or adaptive:
        knee = device-RTT / host-cost-per-message, both running EMAs
        measured at collect time. On a ~70 ms tunneled chip the knee
        saturates at max_batch (host path serves latency, device path
        serves saturated full batches); on a local sub-ms chip it sits
        around 10²."""
        if self.broker.model is None:
            return 0                    # no device path configured
        if self.min_device_batch >= 0:
            return self.min_device_batch
        return min(self.max_batch,
                   max(1, int(self._rtt_ema
                              / max(self._host_cost_ema, 1e-9))))

    def _collect_dispatch(self, token) -> None:
        t0 = time.perf_counter()
        results = self.broker.publish_batch_collect(token)
        dt = time.perf_counter() - t0
        live = token[1]
        if not live:
            pass          # hook-dropped batch: nothing was routed, so
        elif token[4] is None:          # no cost signal — don't poison
            # host-oracle batch: normalize by messages actually routed
            per_msg = dt / len(live)
            self._host_cost_ema += 0.2 * (per_msg - self._host_cost_ema)
        else:                           # device batch: effective blocked
            self._rtt_ema += 0.2 * (dt - self._rtt_ema)  # time at collect
        merged: dict[str, list] = {}
        for d in results:
            for sid, items in d.items():
                merged.setdefault(sid, []).extend(items)
        if merged:
            self.cm.dispatch(merged)

    def ensure_flusher(self) -> asyncio.Task:
        """Start (or adopt) the ONE flusher task for the running loop.
        The pipeline owns the task — several listeners sharing one app
        (tcp + ws) must not each spawn/cancel their own flusher, or one
        listener's shutdown would orphan the others' deliveries."""
        loop = asyncio.get_running_loop()
        if (self._flusher_task is None or self._flusher_task.done()
                or self._loop is not loop):
            self._loop = loop
            self._wake = asyncio.Event()
            self._flusher_task = loop.create_task(self.flusher())
        return self._flusher_task

    async def flusher(self) -> None:
        """Asyncio consumer: wake on submit, drain off-loop (the device
        step blocks a thread, not the accept loop; submissions landing
        during a flush coalesce into the next batch — the overlap).
        A failing batch is logged and dropped — one poisoned message (a
        raising hook, a device error) must not kill delivery forever."""
        if self._loop is None:
            self._loop = asyncio.get_running_loop()
        if self._wake is None:
            self._wake = asyncio.Event()
        wake = self._wake
        while True:
            await wake.wait()
            wake.clear()
            try:
                await asyncio.to_thread(self.flush)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("publish flush failed; batch dropped")
