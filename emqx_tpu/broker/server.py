"""TCP connection host + listener — the ``emqx_connection.erl`` /
``emqx_listeners.erl`` analogue.

One asyncio task per connection (the BEAM's process-per-connection on an
event loop): socket reads feed the incremental parser in ``{active,N}``
style batches, parsed packets drive the channel FSM, outgoing packets
serialize back to the socket. Periodic housekeeping covers keepalive
(1.5×), retry, and awaiting-rel expiry (the channel's timer set,
emqx_channel.erl:125-132).

The production ingest path is the C++ host in ``emqx_tpu/native`` feeding
publish batches to the device router; this asyncio host is the reference
implementation and the control-plane/test surface. Both speak to the same
Broker/Channel objects.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.cm import CM
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import FrameError, Parser, serialize

log = logging.getLogger("emqx_tpu.server")

READ_CHUNK = 65536          # {active,N}-ish coalescing
HOUSEKEEP_INTERVAL = 5.0


class Connection:
    """One client socket: parser + channel + writer."""

    def __init__(self, server: "BrokerServer",
                 reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        peer = writer.get_extra_info("peername") or ("?", 0)
        self.parser = Parser(max_size=server.max_packet_size)
        self.limiter = server.make_limiter_container()
        pipeline = getattr(server, "pipeline", None)
        self.channel = Channel(
            server.broker, server.cm,
            mountpoint=server.mountpoint,
            send=self._send_packets,
            publish_sink=pipeline.submit if pipeline is not None else None,
            session_opts=getattr(server, "session_opts", None),
        )
        self.channel.conninfo.peername = f"{peer[0]}:{peer[1]}"
        self.metrics = getattr(server.app, "metrics", None)
        self.closed = False
        self._loop = asyncio.get_event_loop()
        # TLS listeners: capture the handshake's peer certificate for
        # cert-based identity (emqx_schema peer_cert_as_username|clientid)
        self.cert_identity: dict = {}
        if server.ssl_context is not None:
            from emqx_tpu.broker.tls import peer_cert_identity
            self.cert_identity = peer_cert_identity(
                writer.get_extra_info("peercert"))

    def _transport_wrap(self, data: bytes) -> bytes:
        """Frame serialized MQTT bytes for the wire (identity for raw
        TCP; the WS transport wraps into an RFC6455 binary frame)."""
        return data

    def _send_packets(self, pkts) -> None:
        if self.closed:
            return
        ver = self.channel.conninfo.proto_ver
        limit = self.channel.conninfo.max_packet_out
        chunks = []
        sent_pkts = []
        queue = list(pkts)
        while queue:
            p = queue.pop(0)
            b = serialize(p, ver)
            if limit and len(b) > limit and p.type == P.PUBLISH:
                # MQTT5 3.1.2-25: never exceed the client's announced
                # Maximum-Packet-Size — the message is dropped for THIS
                # client (acks/connacks are never oversized in practice).
                # A QoS>0 drop must also release its inflight slot ("as
                # if it had completed sending") or the window leaks and
                # retry re-drops it forever.
                if self.metrics is not None:
                    self.metrics.inc("delivery.dropped.too_large")
                session = self.channel.session
                if p.qos and p.packet_id is not None and session is not None:
                    # freed slot may pull queued messages forward; they
                    # take the channel's normal unmount/hook postprocess
                    queue.extend(self.channel._postprocess_out(
                        session.discard_delivery(p.packet_id)))
                continue
            chunks.append(b)
            sent_pkts.append(p)
        data = b"".join(chunks)
        pkts = sent_pkts
        if data:
            frame = self._transport_wrap(data)
            try:
                on_loop = asyncio.get_running_loop() is self._loop
            except RuntimeError:
                on_loop = False
            if on_loop:
                self.writer.write(frame)
            else:
                # dispatch from a foreign thread (bridge ingress, app
                # tick in to_thread): asyncio transports are not
                # thread-safe — marshal the write onto the owning loop
                self._loop.call_soon_threadsafe(self.writer.write, frame)
            if self.metrics is not None:
                self.metrics.inc("bytes.sent", len(data))
                for p in pkts:
                    self.metrics.inc_sent_packet(
                        P.TYPE_NAMES.get(p.type, "reserved").lower())
                    if p.type == P.PUBLISH:
                        self.metrics.inc_msg("sent", p.qos)

    async def _on_bytes(self, data: bytes) -> None:
        """Shared MQTT byte-stream stage: limits, accounting, parse,
        channel FSM — both raw-TCP and WS reads land here."""
        # bytes_in limit: pause the socket until tokens free up
        # (the esockd-htb backpressure, emqx_connection.erl:528-535)
        await self._limit("bytes_in", len(data))
        if self.metrics is not None:
            self.metrics.inc("bytes.received", len(data))
        gc_policy = getattr(self.server.app, "gc_policy", None)
        if gc_policy is not None:
            gc_policy.note(1, len(data),
                           getattr(self.server.app, "olp", None))
        for pkt in self.parser.feed(data):
            if pkt.type == P.PUBLISH:
                await self._limit("message_in", 1)
            if self.metrics is not None:
                self.metrics.inc_recv_packet(
                    P.TYPE_NAMES.get(pkt.type, "reserved").lower())
                if pkt.type == P.PUBLISH:
                    self.metrics.inc_msg("received", pkt.qos)
            if pkt.type == P.CONNECT:
                self.parser.set_version(pkt.proto_ver)
                self.channel.conninfo.proto_ver = pkt.proto_ver
                # TLS identity substitution happens at the listener, not
                # the FSM — the channel sees the effective identity
                # (emqx_channel.erl peer_cert_as_username handling)
                sel = self.server.peer_cert_as_username
                if sel and self.cert_identity.get(sel):
                    pkt.username = self.cert_identity[sel]
                sel = self.server.peer_cert_as_clientid
                if sel and self.cert_identity.get(sel):
                    pkt.clientid = self.cert_identity[sel]
            out = self.channel.handle_in(pkt)
            self._send_packets(out)
            if self.channel.conn_state == "disconnected":
                self.closed = True
                break

    async def run(self) -> None:
        try:
            while not self.closed:
                data = await self.reader.read(READ_CHUNK)
                if not data:
                    break
                await self._on_bytes(data)
                await self._drain()
        except FrameError as e:
            log.info("frame error from %s: %s",
                     self.channel.conninfo.peername, e)
            if self.channel.conninfo.proto_ver == P.MQTT_V5:
                self._send_packets([P.Disconnect(reason_code=e.rc)])
                await self._drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            await self.close("sock_closed")

    async def _limit(self, type_: str, n: float) -> None:
        while not self.closed:
            ok, retry = self.limiter.check(type_, n)
            if ok:
                return
            await asyncio.sleep(min(max(retry, 0.005), 1.0))

    async def _drain(self) -> None:
        try:
            await self.writer.drain()
        except ConnectionError:
            pass

    async def close(self, reason: str) -> None:
        if not self.closed:
            self.closed = True
        self.channel.terminate(reason)
        self.server.connections.discard(self)
        congestion = getattr(self.server.app, "congestion", None)
        if congestion is not None:
            congestion.forget(self.channel.conninfo.peername)
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except (ConnectionError, Exception):
            pass

    def housekeep(self) -> None:
        if self.channel.keepalive_expired():
            asyncio.ensure_future(self.close("keepalive_timeout"))
            return
        self._send_packets(self.channel.handle_timeout("retry"))
        self.channel.handle_timeout("expire_awaiting_rel")


class BrokerServer:
    """Listener lifecycle (emqx_listeners:start_listener analogue)."""

    def __init__(
        self,
        broker: Optional[Broker] = None,
        cm: Optional[CM] = None,
        host: str = "127.0.0.1",
        port: int = 1883,
        max_packet_size: int = 1 << 20,
        max_connections: int = 1_000_000,
        mountpoint: str = "",
        app=None,
        limiter=None,
        listener_id: str = "tcp:default",
        ssl_context=None,
        ssl_handshake_timeout: Optional[float] = None,
        peer_cert_as_username: Optional[str] = None,   # "cn" | "dn"
        peer_cert_as_clientid: Optional[str] = None,
        session_opts: Optional[dict] = None,
    ):
        if app is None and broker is None:
            from emqx_tpu.app import BrokerApp

            app = BrokerApp()
        self.app = app
        self.broker = broker or app.broker
        self.cm = cm or (app.cm if app else CM())
        # zone session knobs (mqtt.max_inflight & co) reach every channel
        if session_opts is None and app is not None:
            session_opts = getattr(app, "session_defaults", dict)()
        self.session_opts = dict(session_opts or {})
        self.host, self.port = host, port
        self.max_packet_size = max_packet_size
        self.max_connections = max_connections
        self.mountpoint = mountpoint
        self.connections: set[Connection] = set()
        self.limiter = limiter          # LimiterServer | None
        self.listener_id = listener_id
        self.ssl_context = ssl_context  # ssl.SSLContext | None (ssl/wss)
        self.ssl_handshake_timeout = ssl_handshake_timeout
        self.peer_cert_as_username = peer_cert_as_username
        self.peer_cert_as_clientid = peer_cert_as_clientid
        # device serving path: batch publishes through the app's pipeline
        # when the router model is configured (router.device.enable)
        self.pipeline = getattr(app, "pipeline", None)
        self._server: Optional[asyncio.AbstractServer] = None
        self._housekeeper: Optional[asyncio.Task] = None
        self._flusher: Optional[asyncio.Task] = None

    def make_limiter_container(self):
        from emqx_tpu.broker.limiter import LimiterContainer

        if self.limiter is None:
            return LimiterContainer()
        return self.limiter.make_container(self.listener_id)

    def kernel_summary(self) -> dict:
        """Device-router stage percentiles + counters + trie health
        (the bench harness reads this after a run); {} when the app
        has no kernel telemetry attached."""
        if self.app is None:
            return {}
        fn = getattr(self.app, "kernel_summary", None)
        return fn() if callable(fn) else {}

    async def _on_connect(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        if len(self.connections) >= self.max_connections:
            writer.close()          # esockd max-conn limiting
            return
        olp = getattr(self.app, "olp", None)
        if olp is not None and olp.backoff_new_conn():
            writer.close()          # overload shedding (emqx_olp)
            return
        if self.limiter is not None:
            ok, _retry = self.limiter.connect(self.listener_id)
            if not ok:
                writer.close()      # conn-rate limit: refuse at accept
                return
        conn = Connection(self, reader, writer)
        self.connections.add(conn)
        await conn.run()

    async def start(self) -> None:
        kw = {}
        if self.ssl_context is not None and self.ssl_handshake_timeout:
            # bound slow/stalled handshakes (esockd handshake_timeout;
            # without this asyncio's 60s default governs)
            kw["ssl_handshake_timeout"] = self.ssl_handshake_timeout
        self._server = await asyncio.start_server(
            self._on_connect, self.host, self.port,
            ssl=self.ssl_context, **kw,
        )
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        self._housekeeper = asyncio.create_task(self._housekeep_loop())
        if self.pipeline is not None:
            # the pipeline owns ONE flusher per loop, shared by every
            # listener on the same app (tcp + ws)
            self._flusher = self.pipeline.ensure_flusher()
        log.info("listening on %s:%d", self.host, self.port)

    async def _housekeep_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            before = loop.time()
            await asyncio.sleep(HOUSEKEEP_INTERVAL)
            # scheduling drift = our run-queue signal (emqx_olp)
            lag_ms = (loop.time() - before - HOUSEKEEP_INTERVAL) * 1000
            olp = getattr(self.app, "olp", None)
            if olp is not None:
                olp.note_lag(lag_ms)
            if self.app is not None:
                # off-loop: the tick may block (bridge reconnects, disk
                # queue flushes) and must never stall the accept loop
                await asyncio.to_thread(self.app.tick)
            congestion = getattr(self.app, "congestion", None)
            for conn in list(self.connections):
                conn.housekeep()
                if congestion is not None and not conn.closed:
                    transport = conn.writer.transport
                    congestion.check(
                        conn.channel.conninfo.peername,
                        transport.get_write_buffer_size())

    async def stop(self) -> None:
        if self._housekeeper:
            self._housekeeper.cancel()
        if self.pipeline is not None and self.pipeline.pending():
            # final drain; flush() serializes with any in-flight flusher
            # run, and the shared flusher task is NOT cancelled here —
            # other listeners on this app may still be serving
            await asyncio.to_thread(self.pipeline.flush)
        for conn in list(self.connections):
            await conn.close("server_shutdown")
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def serve_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()


def main() -> None:  # pragma: no cover - CLI entry
    import argparse

    ap = argparse.ArgumentParser(description="emqx_tpu broker")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=1883)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    asyncio.run(BrokerServer(host=args.host, port=args.port).serve_forever())


if __name__ == "__main__":  # pragma: no cover
    main()
