"""MQTT over WebSocket — the ``emqx_ws_connection.erl`` analogue.

The reference rides cowboy; here RFC6455 is implemented in-repo (no
external deps): HTTP upgrade handshake with the ``mqtt`` subprotocol,
an incremental frame decoder (fragmentation, ping/pong, close,
masked-client enforcement), and a listener that feeds the *same*
``Channel`` FSM the TCP server drives — WS binary frames are just a
second byte-transport for the MQTT parser.

Since round 7 the hot WS path lives in the C++ host
(``native/src/ws.h`` + ``host.cc``; enable with
``NativeBrokerServer(ws_port=...)`` or ``ws_bind`` on a ``native``
listener). THIS module stays as the slow-plane oracle and conformance
reference — ``tests/test_native_ws.py`` drives both ends against each
other — and serves upgrade paths the native listener rejects.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
import os
import struct
from typing import Optional

from emqx_tpu.broker.server import BrokerServer, Connection

log = logging.getLogger("emqx_tpu.ws")

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BINARY = 0x0, 0x1, 0x2
OP_CLOSE, OP_PING, OP_PONG = 0x8, 0x9, 0xA


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + WS_GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def encode_frame(opcode: int, payload: bytes, mask: bool = False) -> bytes:
    """One unfragmented frame (FIN=1). Servers send unmasked; clients
    must mask (RFC6455 §5.3)."""
    head = bytearray([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head.append(mask_bit | n)
    elif n < 65536:
        head.append(mask_bit | 126)
        head += struct.pack(">H", n)
    else:
        head.append(mask_bit | 127)
        head += struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        head += key
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return bytes(head) + payload


class WsError(Exception):
    def __init__(self, code: int, reason: str) -> None:
        super().__init__(reason)
        self.code = code


class FrameDecoder:
    """Incremental RFC6455 decoder: feed bytes, get (opcode, payload)
    messages (fragments reassembled, control frames passed through)."""

    def __init__(self, require_mask: bool = True,
                 max_size: int = 1 << 24) -> None:
        self.require_mask = require_mask
        self.max_size = max_size
        self._buf = b""
        self._frag_op: Optional[int] = None
        self._frag_data = b""

    def feed(self, data: bytes) -> list[tuple[int, bytes]]:
        self._buf += data
        out: list[tuple[int, bytes]] = []
        while True:
            frame = self._try_frame()
            if frame is None:
                return out
            fin, opcode, payload = frame
            if opcode in (OP_CLOSE, OP_PING, OP_PONG):
                if not fin:
                    raise WsError(1002, "fragmented control frame")
                out.append((opcode, payload))
                continue
            if opcode == OP_CONT:
                if self._frag_op is None:
                    raise WsError(1002, "continuation without start")
                self._frag_data += payload
                if len(self._frag_data) > self.max_size:
                    raise WsError(1009, "message too big")
                if fin:
                    out.append((self._frag_op, self._frag_data))
                    self._frag_op, self._frag_data = None, b""
                continue
            # data frame start
            if self._frag_op is not None:
                raise WsError(1002, "interleaved fragmented messages")
            if fin:
                out.append((opcode, payload))
            else:
                self._frag_op, self._frag_data = opcode, payload

    def _try_frame(self) -> Optional[tuple[bool, int, bytes]]:
        buf = self._buf
        if len(buf) < 2:
            return None
        b0, b1 = buf[0], buf[1]
        if b0 & 0x70:
            raise WsError(1002, "RSV bits set")
        fin = bool(b0 & 0x80)
        opcode = b0 & 0x0F
        masked = bool(b1 & 0x80)
        if self.require_mask and not masked:
            raise WsError(1002, "client frames must be masked")
        n = b1 & 0x7F
        pos = 2
        if n == 126:
            if len(buf) < 4:
                return None
            (n,) = struct.unpack_from(">H", buf, 2)
            pos = 4
        elif n == 127:
            if len(buf) < 10:
                return None
            (n,) = struct.unpack_from(">Q", buf, 2)
            pos = 10
        if n > self.max_size:
            raise WsError(1009, "frame too big")
        key = b""
        if masked:
            if len(buf) < pos + 4:
                return None
            key = buf[pos:pos + 4]
            pos += 4
        if len(buf) < pos + n:
            return None
        payload = buf[pos:pos + n]
        if masked:
            payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        self._buf = buf[pos + n:]
        return fin, opcode, payload


async def server_handshake(reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter,
                           path: str = "/mqtt") -> bool:
    """Read the HTTP upgrade request, answer 101 (subprotocol ``mqtt``)
    or reject. Returns True when upgraded."""
    try:
        request = await asyncio.wait_for(
            reader.readuntil(b"\r\n\r\n"), timeout=10)
    except (asyncio.IncompleteReadError, asyncio.TimeoutError,
            asyncio.LimitOverrunError):
        return False
    lines = request.decode("latin1").split("\r\n")
    parts = lines[0].split(" ")
    headers = {}
    for line in lines[1:]:
        name, sep, val = line.partition(":")
        if sep:
            headers[name.strip().lower()] = val.strip()
    ok = (
        len(parts) >= 2 and parts[0] == "GET"
        and "websocket" in headers.get("upgrade", "").lower()
        and "upgrade" in headers.get("connection", "").lower()
        and "sec-websocket-key" in headers
    )
    if not ok or (path and parts[1].split("?")[0] != path):
        writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                     b"Content-Length: 0\r\n\r\n")
        await writer.drain()
        return False
    protos = [p.strip() for p in
              headers.get("sec-websocket-protocol", "").split(",") if p]
    resp = [
        "HTTP/1.1 101 Switching Protocols",
        "Upgrade: websocket",
        "Connection: Upgrade",
        f"Sec-WebSocket-Accept: {accept_key(headers['sec-websocket-key'])}",
    ]
    if "mqtt" in protos:
        resp.append("Sec-WebSocket-Protocol: mqtt")
    writer.write(("\r\n".join(resp) + "\r\n\r\n").encode())
    await writer.drain()
    return True


class WsConnection(Connection):
    """A WS-framed MQTT connection: identical channel path (the base
    ``_on_bytes`` stage does limits/accounting/parse/FSM), the socket
    bytes pass through the RFC6455 decoder first and replies wrap into
    binary frames via ``_transport_wrap``."""

    def __init__(self, server: "WsBrokerServer", reader, writer):
        super().__init__(server, reader, writer)
        self.ws = FrameDecoder(require_mask=True)

    # MQTT bytes out → one binary WS frame (the reference emits one WS
    # frame per serialized packet batch too)
    def _transport_wrap(self, data: bytes) -> bytes:
        return encode_frame(OP_BINARY, data)

    async def run(self) -> None:
        from emqx_tpu.mqtt import packet as P
        from emqx_tpu.mqtt.frame import FrameError

        try:
            while not self.closed:
                data = await self.reader.read(65536)
                if not data:
                    break
                try:
                    messages = self.ws.feed(data)
                except WsError as e:
                    self.writer.write(encode_frame(
                        OP_CLOSE, struct.pack(">H", e.code)))
                    break
                for opcode, payload in messages:
                    if opcode == OP_PING:
                        self.writer.write(encode_frame(OP_PONG, payload))
                        continue
                    if opcode == OP_CLOSE:
                        self.writer.write(encode_frame(OP_CLOSE, payload))
                        self.closed = True
                        break
                    if opcode == OP_PONG:
                        continue
                    # text frames are a protocol violation for MQTT-WS,
                    # tolerate by treating payload as bytes
                    await self._on_bytes(payload)
                await self._drain()
        except FrameError as e:
            log.info("mqtt frame error from %s: %s",
                     self.channel.conninfo.peername, e)
            if self.channel.conninfo.proto_ver == P.MQTT_V5:
                self._send_packets([P.Disconnect(reason_code=e.rc)])
                await self._drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            await self.close("sock_closed")


class WsBrokerServer(BrokerServer):
    """WS listener (ws:// — TLS termination is the LB's job here, as in
    the reference's ws vs wss listener split)."""

    def __init__(self, *args, path: str = "/mqtt", **kwargs):
        super().__init__(*args, **kwargs)
        self.path = path
        self.listener_id = kwargs.get("listener_id", "ws:default")

    async def _on_connect(self, reader, writer) -> None:
        if len(self.connections) >= self.max_connections:
            writer.close()
            return
        olp = getattr(self.app, "olp", None)
        if olp is not None and olp.backoff_new_conn():
            writer.close()
            return
        if self.limiter is not None:
            ok, _retry = self.limiter.connect(self.listener_id)
            if not ok:
                writer.close()      # conn-rate limit, same as the TCP path
                return
        if not await server_handshake(reader, writer, self.path):
            writer.close()
            return
        conn = WsConnection(self, reader, writer)
        self.connections.add(conn)
        await conn.run()
