"""Broker listener on the native (C++ epoll) connection host.

The C++ side (``emqx_tpu/native/src/host.cc``) owns sockets, framing
and — since round 4 — the PUBLISH fast path (round 6 extended it from
QoS0/1 to the full QoS0/1/2 ack plane): parse → match → fan-out →
ack exchange runs entirely in C++ against a mirror of the broker
tables, and only the frames that *need* Python (CONNECT/SUBSCRIBE,
retained, $-topics, shared subscriptions, unpermitted topics) come up
to this driver, which runs the same ``Channel`` FSM the asyncio server
uses. This is SURVEY.md §7's "host side in C++" design: the reference
runs its hot loop in per-connection BEAM processes
(emqx_connection.erl:403-440 → emqx_broker.erl:218-232); the GIL makes
that shape a ~14k msg/s ceiling in Python (BENCH_r03), so the hot loop
moves below the GIL instead.

Correctness seams (all of them fail toward the slow path, which is
always correct):

- **table mirror** — every ``broker.subscribe/unsubscribe`` (including
  session resumes) fires ``broker.sub_observers``; subscriptions that
  cannot be natively served (shared groups, persistent sessions,
  subscription ids, subscribers on other transports) are installed as
  *punt markers*: one marker in a publish's match set forwards the
  whole frame to Python, so native fan-out only runs when complete;
- **permits** — a (conn, topic) publish permit is the authz-cache
  analogue (emqx_authz cache): granted only after a first publish
  ran the full Python path and the topic matches no rules, no traces,
  no topic-metrics pattern, and authorization allows it; granted only
  once the pipeline is idle so a fast message can never overtake a
  still-queued slow one on the same topic; flushed on rule changes and
  on a TTL cadence (the authz cache TTL analogue);
- **packet ids** — native QoS1/2 deliveries use pids >= 32768
  (host.cc kNativePidBase), Python sessions stay below
  (session/session.py PKT_ID_SPACE), so subscriber acks route
  unambiguously; publisher-side QoS2 ids route by *awaiting-rel
  ownership*: the plane that accepted the PUBLISH holds the id in its
  awaiting-rel set and completes its PUBREL, so the planes can never
  double-publish one id;
- **batched ack records** (round 6) — the C++ host owns the whole
  elevated-qos window (pid allocation, inflight bitmaps, window-full →
  pending queue) and reports ONE kind-7 record per poll cycle;
  ``_on_ack_batch`` folds it into metrics, reconciles sessions
  (``session.native_ack_sync``) and re-divides the receive-maximum
  budget between the planes (caps always sum <= budget);
- **clustered nodes** — remote routes mirror into the C++ table as
  punt markers via ``router.route_observers`` (fired under the router
  lock, in table order), so a publish with any remote audience takes
  the Python path, which forwards it over the cluster plane;
- **device match lane** (round 5) — with ``device_lane`` on, permitted
  publishes park in C++ while their topics batch through the
  RouterModel kernel; the response names each message's matched filter
  strings and C++ fans out via exact per-filter lookup
  (``router.h MatchFilter``), so the wildcard walk runs on the DEVICE
  at scale while delivery semantics (qos, no-local, shared rotation,
  punt markers) stay in C++. Every failure mode — soft cap, per-topic
  flood, pump death, stale responses — falls back to the per-message
  walk or the Python path, both always correct. Punt markers are
  double-checked against a punt-only trie because the device model
  cannot see remote-route markers.
"""

from __future__ import annotations

import logging
import os
import queue
import random
import struct
import threading
import time
import zlib
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from emqx_tpu import native
from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.cm import CM
from emqx_tpu.core import topic as T
from emqx_tpu.core.message import now_ms
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import FrameError, parse_one, serialize
from emqx_tpu.observe.metrics import DegradationLedger
from emqx_tpu.observe.trace import SpanCollector

log = logging.getLogger("emqx_tpu.native_server")

HOUSEKEEP_INTERVAL = 5.0
PERMIT_TTL_S = 60.0          # authz-cache TTL analogue: periodic re-earn
MAX_PERMITS_PER_CONN = 4096  # mirrors host.cc's per-conn permit cap
# device-lane auto policy (hysteresis): the crossover bench shows the
# per-message C++ walk beating the batched device matcher on small
# tables — the lane only pays once the wildcard table is big
LANE_AUTO_ON_FILTERS = 50_000
LANE_AUTO_OFF_FILTERS = 25_000
LANE_MAX_BATCH = 16_384
LANE_PIPE_DEPTH = 2          # submitted-but-uncollected device batches
LANE_STALE_BACKOFF_S = 30.0  # sit-out after a C++ stale trip
TRUNK_RETRY_S = 1.0          # redial cadence for a down trunk peer
TRUNK_RETRY_CAP_S = 30.0     # exponential-backoff ceiling
# ±25% redial jitter (round 15): a healed partition must not wake every
# peer's redial on the same capped boundary (full-mesh thundering herd)
TRUNK_RETRY_JITTER = 0.25
# Dynamic inflight-cap policy (re-derived for the sharded plane —
# README "Multi-core native plane" carries the full derivation). The
# policy is PER-CONN, and a conn lives on exactly one shard, so it is
# per-shard by construction; the constants are shard-count-invariant:
# - CAP_HEADROOM x occupancy covers demand that doubles within one
#   kind-7 reporting cycle. Reporting stays per-shard-cycle under
#   shards; the only new lag is the N poll threads' folds serializing
#   behind the GIL, measured < 15% cycle stretch at N=2 on the 2-core
#   container — far inside the 2x headroom.
# - the deadband (budget/CAP_DEADBAND_DIV, floored at
#   CAP_DEADBAND_MIN) must exceed per-cycle occupancy jitter, which
#   scales with cycle LENGTH, not shard count: per-shard cycles are
#   unchanged, so 1/8 stands. Re-dividing every wiggle taxed the data
#   plane measurably when tuned (round 6) — the cap op is an
#   enqueue+wake the owner shard must apply before its next read.
CAP_HEADROOM = 2
CAP_DEADBAND_DIV = 8
CAP_DEADBAND_MIN = 8


class _NativeConn:
    __slots__ = ("conn_id", "channel", "server", "fast", "sn", "coap",
                 "recv_budget", "native_cap", "native_ka")

    def __init__(self, server: "NativeBrokerServer", conn_id: int, peer: str):
        self.server = server
        self.conn_id = conn_id
        self.fast = False
        # MQTT-SN datagram conns (peer "sn:..."): their frames arrive
        # pre-translated to MQTT by the C++ gateway; the housekeep
        # keepalive feed covers them even when not fast (UDP peers
        # never deliver a socket-close signal)
        self.sn = peer.startswith("sn:")
        # CoAP datagram conns (peer "coap:..."): same shape — frames
        # arrive pre-translated to MQTT by the C++ gateway
        self.coap = peer.startswith("coap:")
        self.recv_budget = 0     # receive-maximum budget split across planes
        self.native_cap = 0      # the native plane's current share
        # keepalive lives on the C++ timer wheel (armed post-CONNACK):
        # the Python housekeep stops scanning this conn's idle clock
        self.native_ka = False
        pipeline = server.pipeline
        self.channel = Channel(
            server.broker, server.cm,
            mountpoint=server.mountpoint,
            send=self._send_packets,
            publish_sink=pipeline.submit if pipeline is not None else None,
            session_opts=server.session_opts,
        )
        self.channel.conninfo.peername = peer

    def _send_packets(self, pkts) -> None:
        data = b"".join(
            serialize(p, self.channel.conninfo.proto_ver) for p in pkts)
        if data:
            # Python-plane egress implies possible session timer work
            # (retry / awaiting-rel expiry): re-enter the housekeep
            # scan set; the scan drops the conn again once idle
            self.server._scan_watch(self)
            self.server.host.send(self.conn_id, data)


class _ShardedHost:
    """The ``NativeHost`` control surface over N shard hosts (round 12).

    One instance per sharded server; routes each call to the right
    place so every existing call site works unchanged:

    - **per-conn ops** (send/close/fast flags/permits/traces/caps/
      retained delivery/idle probe) go to the shard whose prefix the
      conn id carries (``native.shard_of``) — conn ids are minted with
      bits 56-58 = shard, so the owner is always derivable;
    - **table ops** (sub/shared/durable entries, retained mirror, SN
      predefined ids, lane/qos/telemetry switches, permit flushes,
      trunk ROUTES) broadcast to every shard: the match table is
      replicated, each shard applies ops in its own ApplyPending;
    - **trunk LINK ops** (connect/disconnect) go to the peer's OWNER
      shard — peer P's dialer, replay ring, and authoritative state
      live on shard ``P % N`` (round 15; links used to pin to shard
      0). Every shard's trunk listener shares one port via
      SO_REUSEPORT; non-owner shards ring-forward remote legs to the
      owner (host.cc XShip → kTrunkOwnerBase target);
    - **aggregates** (stats, lane backlog) sum across shards.
    """

    def __init__(self, hosts: list):
        self.hosts = hosts
        self.port = hosts[0].port

    # a wedged poll thread leaks EVERY shard host (any of the N poll
    # threads may still be inside emqx_host_poll) — and the ring group,
    # whose doorbells a leaked host's producers may still write
    @property
    def leaked(self) -> bool:
        return any(h.leaked for h in self.hosts)

    @leaked.setter
    def leaked(self, v: bool) -> None:
        for h in self.hosts:
            h.leaked = v

    # ports resolved by the per-shard listen calls in __init__
    @property
    def ws_port(self) -> int:
        return self.hosts[0].ws_port

    @property
    def trunk_port(self) -> int:
        return self.hosts[0].trunk_port

    @property
    def sn_port(self) -> int:
        return self.hosts[0].sn_port

    @property
    def coap_port(self) -> int:
        return self.hosts[0].coap_port

    def _of(self, conn: int):
        return self.hosts[native.shard_of(conn) % len(self.hosts)]

    # -- per-conn ops (routed by the conn id's shard prefix) -----------------

    def send(self, conn, data):
        self._of(conn).send(conn, data)

    def close_conn(self, conn):
        self._of(conn).close_conn(conn)

    def enable_fast(self, conn, proto_ver, max_inflight=0, clientid=""):
        self._of(conn).enable_fast(conn, proto_ver, max_inflight,
                                   clientid)

    def disable_fast(self, conn):
        self._of(conn).disable_fast(conn)

    def permit(self, conn, topic):
        self._of(conn).permit(conn, topic)

    def set_trace(self, conn, on):
        self._of(conn).set_trace(conn, on)

    def set_inflight_cap(self, conn, cap):
        self._of(conn).set_inflight_cap(conn, cap)

    def set_keepalive(self, conn, deadline_ms):
        self._of(conn).set_keepalive(conn, deadline_ms)

    def retain_deliver(self, conn, filter_, max_qos=0):
        self._of(conn).retain_deliver(conn, filter_, max_qos)

    def conn_idle_ms(self, conn):
        # poll-thread-only on the OWNING shard (the per-shard housekeep
        # scan runs on that shard's thread; C++ refuses -2 otherwise)
        return self._of(conn).conn_idle_ms(conn)

    # -- table ops (broadcast: the match table is replicated) ----------------

    def sub_add(self, owner, filter_, qos=0, flags=0):
        for h in self.hosts:
            h.sub_add(owner, filter_, qos, flags)

    def sub_del(self, owner, filter_):
        for h in self.hosts:
            h.sub_del(owner, filter_)

    def shared_add(self, token, conn, filter_, qos=0, flags=0):
        # the member entry replicates everywhere; a match on a foreign
        # shard ships the delivery to the member's shard over the ring
        for h in self.hosts:
            h.shared_add(token, conn, filter_, qos, flags)

    def shared_del(self, token, conn, filter_):
        for h in self.hosts:
            h.shared_del(token, conn, filter_)

    def durable_add(self, token, filter_, qos=0):
        for h in self.hosts:
            h.durable_add(token, filter_, qos)

    def durable_del(self, token, filter_):
        for h in self.hosts:
            h.durable_del(token, filter_)

    def trunk_route_add(self, peer_id, filter_):
        # remote ENTRIES replicate (any shard can match a publish);
        # the legs converge on shard 0's links over the ring
        for h in self.hosts:
            h.trunk_route_add(peer_id, filter_)

    def trunk_route_del(self, peer_id, filter_):
        for h in self.hosts:
            h.trunk_route_del(peer_id, filter_)

    def coap_send(self, conn, data):
        self._of(conn).coap_send(conn, data)

    def coap_retain_state(self, complete):
        for h in self.hosts:
            h.coap_retain_state(complete)

    def set_coap_ack_timeout(self, ms):
        for h in self.hosts:
            h.set_coap_ack_timeout(ms)

    def sn_predefined(self, topic_id, topic):
        for h in self.hosts:
            h.sn_predefined(topic_id, topic)

    def set_retained(self, topic, payload, qos, deadline_ms=0):
        for h in self.hosts:
            h.set_retained(topic, payload, qos, deadline_ms)

    def retain_del(self, topic):
        for h in self.hosts:
            h.retain_del(topic)

    def permits_flush(self):
        for h in self.hosts:
            h.permits_flush()

    def set_lane(self, enabled):
        for h in self.hosts:
            h.set_lane(enabled)

    def set_max_qos(self, max_qos):
        for h in self.hosts:
            h.set_max_qos(max_qos)

    def set_telemetry(self, enabled, slow_ack_ms=500.0):
        for h in self.hosts:
            h.set_telemetry(enabled, slow_ack_ms)

    def set_telemetry_shift(self, shift):
        for h in self.hosts:
            h.set_telemetry_shift(shift)

    def set_park(self, enabled=True, park_after_ms=0, accept_burst=0,
                 mem_budget_bytes=0):
        for h in self.hosts:
            h.set_park(enabled, park_after_ms, accept_burst,
                       mem_budget_bytes)

    def attach_store(self, store):
        # one shared store: appends batch per flush, its single internal
        # mutex serializes the (rare) concurrent flushes across shards
        for h in self.hosts:
            h.attach_store(store)

    # -- trunk link plane (links SPREAD across shards, round 15) -------------
    # peer P's dialer, replay ring, and authoritative up/down state live
    # on shard P % n (host.cc OwnsTrunkPeer mirrors this rule); every
    # shard's trunk listener shares one port via SO_REUSEPORT so inbound
    # links hash across shards too — the shard-0 hotspot an N-node mesh
    # would otherwise measure is gone.

    def trunk_listen(self, host="127.0.0.1", port=0):
        p = self.hosts[0].trunk_listen(host, port, reuseport=True)
        for h in self.hosts[1:]:
            h.trunk_listen(host, p, reuseport=True)
        return p

    def trunk_connect(self, peer_id, host, port):
        self.hosts[peer_id % len(self.hosts)].trunk_connect(
            peer_id, host, port)

    def trunk_ident(self, peer_id, name):
        # the persisted-ring key lives on the peer's OWNER shard
        self.hosts[peer_id % len(self.hosts)].trunk_ident(peer_id, name)

    def trunk_disconnect(self, peer_id, forget=False):
        self.hosts[peer_id % len(self.hosts)].trunk_disconnect(
            peer_id, forget)

    def set_trunk_ack_timeout(self, ms):
        for h in self.hosts:
            h.set_trunk_ack_timeout(ms)

    # -- faultline (round 15) ------------------------------------------------

    _STORE_SITES = ("store_msync", "store_seg_open")

    def fault_arm(self, site, mode="errno", n_or_prob=0.0, seed=1,
                  key=0):
        # store sites live in the ONE shared store: arm once via shard 0
        # (broadcasting would reset the firing schedule N times)
        if site in self._STORE_SITES:
            self.hosts[0].fault_arm(site, mode, n_or_prob, seed, key)
            return
        # a KEY-scoped conn/trunk arm has exactly one owner shard (the
        # conn id's prefix / peer % n — the round-15 spread rule):
        # route it there so a count-limited arm fires exactly n times,
        # not n per shard (review finding). Unscoped arms (and ring
        # sites, whose key names the DESTINATION while any shard can
        # be the firing producer) broadcast: their counts/schedules
        # are PER SHARD by construction.
        if key:
            if site.startswith("conn_"):
                self._of(key).fault_arm(site, mode, n_or_prob, seed,
                                        key)
                return
            if site.startswith("trunk_"):
                self.hosts[key % len(self.hosts)].fault_arm(
                    site, mode, n_or_prob, seed, key)
                return
        for h in self.hosts:
            h.fault_arm(site, mode, n_or_prob, seed, key)

    def fault_disarm(self, site):
        if site in self._STORE_SITES:
            self.hosts[0].fault_disarm(site)
            return
        for h in self.hosts:
            h.fault_disarm(site)

    def fault_fired(self, site):
        if site in self._STORE_SITES:
            # one shared injector: summing N hosts would count aliases
            return self.hosts[0].fault_fired(site)
        return sum(h.fault_fired(site) for h in self.hosts)

    # -- aggregates ----------------------------------------------------------

    def stats(self):
        out = dict.fromkeys(native.STAT_NAMES, 0)
        for h in self.hosts:
            for k, v in h.stats().items():
                out[k] += v
        return out

    def lane_backlog(self):
        return sum(h.lane_backlog() for h in self.hosts)

    def destroy(self):
        if self.leaked:
            return
        for h in self.hosts:
            h.destroy()

    def __del__(self):  # pragma: no cover
        try:
            self.destroy()
        except Exception:
            pass


class NativeBrokerServer:
    """Same surface as ``BrokerServer`` but socket IO and the QoS0/1
    publish hot path live in C++."""

    def __init__(
        self,
        broker: Optional[Broker] = None,
        cm: Optional[CM] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_packet_size: int = 1 << 20,
        max_connections: int = 1_000_000,
        mountpoint: str = "",
        app=None,
        fast_path: bool = True,
        device_lane: str = "auto",
        session_opts: Optional[dict] = None,
        ws_port: Optional[int] = None,
        ws_path: str = "/mqtt",
        ws_host: Optional[str] = None,
        telemetry: Optional[bool] = None,
        tracing: Optional[bool] = None,
        trace_sample_shift: Optional[int] = None,
        trunk_port: Optional[int] = None,
        trunk_host: Optional[str] = None,
        durable: Optional[bool] = None,
        durable_dir: Optional[str] = None,
        durable_fsync: Optional[str] = None,
        durable_segment_bytes: Optional[int] = None,
        sn_port: Optional[int] = None,
        sn_host: Optional[str] = None,
        sn_gateway_id: int = 1,
        sn_predefined: Optional[dict] = None,
        coap_port: Optional[int] = None,
        coap_host: Optional[str] = None,
        coap_oracle=None,
        shards: int = 1,
        park: Optional[bool] = None,
        park_after_ms: int = 0,
        accept_burst: int = 0,
        conn_mem_budget: int = 0,
    ):
        if not native.available():
            raise RuntimeError(
                f"native host unavailable: {native.build_error()}")
        if app is None and broker is None:
            from emqx_tpu.app import BrokerApp

            app = BrokerApp()
        self.app = app
        self.broker = broker or app.broker
        self.cm = cm or (app.cm if app else CM())
        self.mountpoint = mountpoint
        self.fast_path = fast_path and not mountpoint
        # zone session knobs (mqtt.max_inflight & co) reach every channel
        if session_opts is None and app is not None:
            session_opts = getattr(app, "session_defaults", dict)()
        self.session_opts = dict(session_opts or {})
        # -- multi-core shards (round 12) -----------------------------------
        # shards=N runs N independent epoll hosts, each with its own
        # poll thread, sharing one port via SO_REUSEPORT accept
        # sharding. The match table replicates (every table op
        # broadcasts); cross-shard delivery rides the lock-free SPSC
        # rings of a NativeShardGroup. shards=1 (the default) keeps the
        # exact unsharded host — no group, zero ring overhead.
        self.shards = max(1, min(int(shards), native.MAX_SHARDS))
        self._shard_group: Optional[native.NativeShardGroup] = None
        if self.shards > 1:
            self._shard_group = native.NativeShardGroup(self.shards)
            # shard 0 may bind an ephemeral port; the others join it.
            # EVERY listener sets SO_REUSEPORT (the kernel requires the
            # flag on all members of a reuseport group, first included)
            h0 = native.NativeHost(
                host=host, port=port, max_size=max_packet_size,
                max_conns=max_connections, reuseport=True)
            self.hosts = [h0] + [
                native.NativeHost(
                    host=host, port=h0.port, max_size=max_packet_size,
                    max_conns=max_connections, reuseport=True)
                for _ in range(1, self.shards)]
            for i, h in enumerate(self.hosts):
                h.join_group(self._shard_group, i)
            self.host = _ShardedHost(self.hosts)
        else:
            self.host = native.NativeHost(
                host=host, port=port,
                max_size=max_packet_size, max_conns=max_connections)
            self.hosts = [self.host]
        self.port = self.host.port
        # WebSocket plane (round 7): a second C++ listener runs the
        # RFC6455 handshake + frame codec below the GIL; its conns ride
        # the SAME fast path (permits, lanes, taps, QoS0/1/2 ack plane)
        # as TCP — only the transport framing differs. ws_port=None
        # keeps it off; 0 binds an ephemeral port. broker/ws.py stays
        # the asyncio slow-plane oracle (and serves non-/mqtt paths).
        self.ws_port: Optional[int] = None
        if ws_port is not None:
            # ws_host defaults to the TCP bind host but stays
            # independently configurable (e.g. loopback-only WS next to
            # an all-interfaces TCP listener); with shards every host
            # listens on one port (SO_REUSEPORT, shard 0 resolves it)
            self.ws_port = self.hosts[0].listen_ws(
                ws_host or host, ws_port, ws_path,
                reuseport=self.shards > 1)
            for h in self.hosts[1:]:
                h.listen_ws(ws_host or host, self.ws_port, ws_path,
                            reuseport=True)
        # -- cluster trunk (round 9) ----------------------------------------
        # Cross-node publish forwarding on the C++ plane: peers with a
        # registered trunk get REMOTE entries instead of punt markers
        # for their plain routes, so a cross-node QoS0/1 publish never
        # touches either node's Python plane. Degradation ladder:
        # trunk (up) → punt marker behavior (down/qos2/ring-full) →
        # Python forward_fn (the oracle lane, unchanged).
        self.trunk_port: Optional[int] = None
        if trunk_port is not None:
            self.trunk_port = self.host.trunk_listen(
                trunk_host or host, trunk_port)
        # -- mqtt-sn gateway plane (round 11) -------------------------------
        # A third C++ listener speaks MQTT-SN 1.2 over UDP: the host
        # decodes datagrams with the shared sn.h codec, translates them
        # into MQTT frames, and SN clients ride the SAME permit/punt/
        # lane/tap/ack-plane machinery as TCP and WS — only the framing
        # differs. gateway/mqttsn.py stays the asyncio oracle and the
        # deployment fallback when this listener is off (sn_port=None).
        self.sn_port: Optional[int] = None
        if sn_port is not None:
            # UDP SO_REUSEPORT source-hashes each SN peer onto ONE
            # shard's socket, so a datagram conversation never splits
            # across poll threads
            self.sn_port = self.hosts[0].listen_sn(
                sn_host or host, sn_port, sn_gateway_id,
                reuseport=self.shards > 1)
            for h in self.hosts[1:]:
                h.listen_sn(sn_host or host, self.sn_port, sn_gateway_id,
                            reuseport=True)
            for tid, t in (sn_predefined or {}).items():
                self.host.sn_predefined(int(tid), t)
        # -- coap gateway plane (round 19) ----------------------------------
        # A fourth C++ listener speaks CoAP (RFC 7252) over UDP: the
        # host decodes datagrams with the shared coap.h codec, the /ps
        # pub-sub surface translates into MQTT frames riding the SAME
        # permit/punt/lane/tap/ack-plane machinery as TCP/WS/SN, and
        # observe notifications resolve host-side on the delivery seam.
        # gateway/coap.py stays the asyncio oracle, the deployment
        # fallback (coap_port=None), AND the serving plane for punted
        # exchanges (kind 13: block-wise transfers, props-carrying
        # retained reads, non-/ps paths — ``coap_oracle`` swaps the
        # punt channel class, e.g. the LwM2M channel over /rd).
        self.coap_port: Optional[int] = None
        self._coap_oracle: dict = {}  # conn id → channel @guards(_coap_lock)
        # RLock: an oracle channel's uplink publish can dispatch into
        # ANOTHER oracle channel's handle_deliver on the same thread
        self._coap_lock = threading.RLock()
        self._coap_retain_ok = True
        if coap_port is not None:
            if self.app is None:
                raise ValueError("coap_port requires an app")
            self.coap_port = self.hosts[0].listen_coap(
                coap_host or host, coap_port, reuseport=self.shards > 1)
            for h in self.hosts[1:]:
                h.listen_coap(coap_host or host, self.coap_port,
                              reuseport=True)
            from emqx_tpu.gateway import coap as _coap_mod
            from emqx_tpu.gateway.ctx import GwContext as _GwContext

            self._coap_frame = _coap_mod.Frame()
            srv = self

            class _OracleCtx(_GwContext):
                """The punt seam's broker surface: identical to the
                asyncio gateway's context, except open_session never
                discards a channel belonging to one of THIS server's
                native conns — a device that publishes natively under
                the same clientid keeps its session; the oracle only
                serves the exchanges the native vocabulary excludes."""

                def open_session(self, clientid, channel):
                    old = self.app.cm.lookup_channel(clientid)
                    if old is not None and old is not channel:
                        for conn in list(srv.conns.values()):
                            if conn.channel is old:
                                return
                    super().open_session(clientid, channel)

                def close_session(self, clientid, channel=None,
                                  reason="closed"):
                    # the mirror guard: an oracle channel that never
                    # owned the CM slot (a native conn holds the
                    # identity) must not strip the LIVE session's
                    # subscriptions on its teardown (review finding —
                    # subscriber_down is unconditional in the base)
                    if self.app.cm.lookup_channel(clientid) is not channel:
                        return
                    super().close_session(clientid, channel, reason)

            self._coap_ctx = _OracleCtx(self.app, "coap-native")
            self._coap_factory = coap_oracle or (
                lambda ctx: _coap_mod.Channel(ctx))
        # -- conn-scale plane (round 16) ------------------------------------
        # Hibernation of idle conns + accept-storm governance live in
        # C++ (park.h / wheel.h); this just forwards the knobs. Parking
        # is ON by default (EMQX_NATIVE_PARK=0 is the escape hatch) —
        # it is invisible on the wire: the first byte re-inflates.
        if park is None:
            park = os.environ.get("EMQX_NATIVE_PARK", "1") != "0"
        self.park = bool(park)
        if not self.park or park_after_ms or accept_burst \
                or conn_mem_budget:
            self.host.set_park(self.park, park_after_ms, accept_burst,
                               conn_mem_budget)
        # conns whose Python session may hold timer work (retry /
        # awaiting-rel expiry) — the housekeep scans ONLY these; conns
        # with a native keepalive and an idle session leave the set.
        self._scan_conns: dict = {}      # @guards(_scan_lock)
        self._scan_lock = threading.Lock()
        # node name → {"id", "addr", "port", "up", } under _mirror_lock
        self._trunk_peers: dict[str, dict] = {}  # @guards(_mirror_lock)
        self._trunk_id_nodes: dict[int, str] = {}   # peer id → node name
        self._trunk_id_next = 1
        self._trunk_routes: set[tuple[str, str]] = set()  # (node, topic)
        self._trunk_retry_at = float("inf")         # next redial stamp
        # redial jitter source (round 15): process-seeded; only the
        # ±25% SHAPE matters, never a specific draw
        self._redial_rng = random.Random()
        # faultline (round 15): per-site injected-fault counters seen
        # at the last housekeep fold (faults.* metric slots + the
        # store-site ledger fold ride the deltas)
        self._faults_seen: dict[str, int] = {
            s: 0 for s in native.FAULT_SITES}
        # -- native telemetry plane (round 8) ------------------------------
        # In-host latency histograms + per-conn flight recorders, shipped
        # as batched kind-8 records and folded here into histogram-aware
        # Metrics (observe/metrics.py), prometheus, $SYS, and slow_subs.
        # EMQX_NATIVE_TELEMETRY=0 is the product escape hatch (bench.py's
        # observe_overhead section proves the on-cost < 2%).
        if telemetry is None:
            telemetry = os.environ.get("EMQX_NATIVE_TELEMETRY", "1") != "0"
        self.telemetry = bool(telemetry)
        self._hists = {}                      # @guards(_tele_lock)
        for stage in native.HIST_STAGES:
            self._hists[stage] = self.broker.metrics.register_hist(
                f"latency.native.{stage}")
        # per-shard stage breakdown (the bench's shards section reads
        # it via shard_latency_summary): registered only when sharded,
        # so the unsharded metric surface is byte-identical to round 11
        self._shard_hists: dict[int, dict] = {}
        if self.shards > 1:
            for i in range(self.shards):
                self._shard_hists[i] = {
                    stage: self.broker.metrics.register_hist(
                        f"latency.native.shard{i}.{stage}")
                    for stage in native.HIST_STAGES}
        # kind-7/8/10 records now arrive from N concurrent poll threads
        # (each record carries its shard in the id slot): the folds
        # below mutate shared server state, so each takes its lock
        self._tele_lock = threading.Lock()
        self._ack_lock = threading.Lock()
        self._durable_lock = threading.Lock()
        # serializes the _closed_conns capped insert+evict: EV_CLOSED
        # fires on every shard's poll thread, and two threads evicting
        # the same oldest key would KeyError mid-poll-batch
        self._closed_lock = threading.Lock()
        slow_ms = (self.app.slow_subs.threshold_ms
                   if self.app is not None else 500)
        self.host.set_telemetry(self.telemetry, slow_ack_ms=slow_ms)
        self._slow_ack_ms = slow_ms
        # per-message stage sampling override for bench runs (README
        # "Observability": default 1-in-8, hist deltas flush ~100ms)
        shift = os.environ.get("EMQX_NATIVE_TELEMETRY_SHIFT", "")
        if shift.isdigit():
            self.host.set_telemetry_shift(int(shift))
        # recent flight-recorder dumps: (conn_id, reason, entries)
        self.flight_records: deque = deque(maxlen=64)
        # conns currently trace-punted in C++ (clientid-filter traces);
        # _trace_lock serializes the poll thread's add (enable-fast on
        # a pre-traced clientid) / discard (conn close) against
        # _sync_traces' read-modify-write from management threads — an
        # unsynchronized replace could lose the poll thread's add and
        # strand the conn trace-punted in C++ after the trace stops
        self._traced_conns: set[int] = set()  # @guards(_trace_lock)
        self._trace_lock = threading.Lock()
        # -- native distributed tracing (round 13) --------------------------
        # A deterministic 1-in-2^shift publish sampler tags fast-path
        # publishes with 64-bit trace ids that propagate through every
        # native seam (ring entries, trunk wire v1, durable store); the
        # planes emit kind-12 span events folded here into a bounded
        # SpanCollector, the trace log (mode="native" clientid traces),
        # and prometheus exemplars. The degradation ledger rides the
        # same records: every ladder decision becomes a structured
        # reason event in app.ledger. EMQX_NATIVE_TRACING=0 (or
        # tracing=False) turns the sampler off; telemetry=False gates
        # everything anyway.
        if tracing is None:
            tracing = os.environ.get("EMQX_NATIVE_TRACING", "1") != "0"
        self.tracing = bool(tracing) and self.telemetry
        if trace_sample_shift is None:
            shift_env = os.environ.get("EMQX_NATIVE_TRACE_SHIFT", "")
            trace_sample_shift = (int(shift_env) if shift_env.isdigit()
                                  else 6)   # 1-in-64 default
        self.trace_sample_shift = int(trace_sample_shift)
        self.spans = SpanCollector()
        self.ledger = (app.ledger if app is not None
                       and getattr(app, "ledger", None) is not None
                       else DegradationLedger(self.broker.metrics))
        # per-shard trace-id seeds: node bits keep two-node traces
        # disjoint, shard bits keep concurrent samplers disjoint, bit
        # 63 keeps every seed (and so every id) nonzero
        node_bits = zlib.crc32(self.broker.node.encode()) & 0x3FFF
        for i, h in enumerate(self.hosts):
            h.set_tracing(self.tracing, self.trace_sample_shift,
                          (1 << 63) | (node_bits << 48) | (i << 44))
        # trace ids whose publisher has a running native-mode trace ->
        # that clientid (SPAN lines land on its trace log; the
        # publisher resolves from the ingress span's aux = conn id)
        self._trace_log_ids: OrderedDict = OrderedDict()  # @guards(_tele_lock)
        self._native_traced: set = set()
        if self.app is not None:
            self.app.native_stats_fn = self.fast_stats
            self.app.native_spans_fn = self.spans_recent
            if self.shards > 1:
                self.app.native_shard_stats_fn = self.shard_stats
        # -- durable-session plane (round 10) ------------------------------
        # A persistent session's filter used to become a punt marker —
        # one durable subscriber collapsed every matching publish onto
        # the Python plane. Now it becomes a kSubDurable entry: the C++
        # host appends matching publishes to a host-side message store
        # (native/src/store.h, mmap segments + CRC framing) below the
        # GIL and ships ONE batched kind-10 record per flush; this
        # server reconciles markers (live delivery to the connected
        # session + consumption) and clean_start=false resume replays
        # the pending set through the native delivery machinery.
        # Requires the app's PersistentSessions service (the marker/
        # resume authority); EMQX_DURABLE_STORE=0 is the escape hatch
        # back to punt-everything.
        self._durable_store = None
        self._durable_tokens: dict[str, int] = {}      # sid -> token
        # post-restart settle fast path (round 18): sid -> token
        # resolved by a store lookup when the primary cache is cold;
        # GIL-atomic get/set only, popped on discard (see
        # _durable_consume for why it avoids _mirror_lock)
        self._durable_tok_cache: dict[str, int] = {}
        self._durable_sids: dict[int, str] = {}  # token -> sid @guards(_durable_lock)
        # sid -> filters with a live C++ durable entry (session discard
        # must tear them down, or a dead token keeps accumulating
        # never-consumed markers forever)
        self._durable_filters: dict[str, set] = {}
        # tokens whose session was discarded: durable_del is an async op
        # (applied at the next ApplyPending), so a publish matched in
        # that window still appends a marker AFTER discard's consume
        # sweep — _on_durable consumes those orphans on sight instead of
        # letting them pin segments forever / replay post-wipe
        self._durable_dead: set[int] = set()  # @guards(_durable_lock)
        # sid -> highest guid a resume drain replayed: when a CONNECT
        # and the publish it raced land in the SAME poll batch, the
        # drain (CONNECT handling) replays the message before the
        # queued kind-10 event is folded — _on_durable must not deliver
        # those guids a second time
        self._durable_drain_mark: dict[str, int] = {}  # @guards(_durable_lock)
        self._store_degraded_seen = 0
        # one-shot loud warning for the punt-everything fallback of
        # persistent sessions on a persistence-less app (round 18)
        self._durable_punt_warned = False
        conf = getattr(app, "config", None) if app is not None else None
        if durable is None:
            durable = os.environ.get("EMQX_DURABLE_STORE", "1") != "0"
        if (durable and self.fast_path and app is not None
                and app.persistent is not None):
            conf_on = conf is not None and conf.get("durable.enable")
            if durable_dir is None and conf_on:
                # <base>/store for the native message log, next to the
                # Python session store at <base>/sessions (app.py)
                base = (conf.get("durable.store_dir")
                        or os.path.join(conf.get("node.data_dir", "data"),
                                        "durable"))
                durable_dir = os.path.join(base, "store")
            if durable_fsync is None:
                durable_fsync = (conf.get("durable.fsync") if conf_on
                                 else "batch")
            if durable_segment_bytes is None:
                durable_segment_bytes = (
                    int(conf.get("durable.segment_bytes")) if conf_on
                    else 4 << 20)
            try:
                # ONE recovery path (round 18): when the app's
                # persistence backend is already native-store-backed
                # (session/persistent.py NativeDurableStore), attach to
                # the SAME store instance — sessions, subscriptions,
                # Python-plane messages, fast-path messages and the
                # trunk replay ring all share one segment walk. Two
                # stores on one dir would double-mmap the segments.
                shared = getattr(app.persistent.store, "native", None)
                if shared is not None:
                    self._durable_store = shared
                    self._durable_store_owned = False
                else:
                    # dir "" = anonymous segments: the durable PLANE
                    # (fast path preserved + live kind-10 delivery +
                    # in-process replay) without restart survival
                    self._durable_store = native.NativeStore(
                        durable_dir or "",
                        durable_segment_bytes or 4 << 20,
                        durable_fsync or "batch")
                    self._durable_store_owned = True
                self.host.attach_store(self._durable_store)
                app.persistent.native_drain = self._durable_drain
                app.persistent.native_discard = self._durable_discard
                app.persistent.native_ack = self._durable_consume
                app.native_store_stats_fn = self._durable_store.stats
            except OSError as e:  # pragma: no cover — unwritable dir
                log.warning("durable store unavailable (%s); persistent "
                            "sessions stay on the punt path", e)
                self._durable_store = None
        # -- retained snapshot (round 11) -----------------------------------
        # services/retainer.py stays the authoritative store + oracle;
        # its observer stream mirrors every store/delete/expire into a
        # host-side read-only snapshot so SUBSCRIBE-triggered retained
        # delivery (TCP, WS, SN alike) resolves and writes below the
        # GIL. Messages carrying v5 properties cannot be encoded by the
        # fast path — ANY unmirrorable topic degrades the whole seam to
        # the Python lookup (always correct, never a partial set).
        self._retain_unmirrorable: set = set()
        self._retain_mirrored = False
        # per-poll-thread context (N threads when sharded): the conn
        # whose frame is being handled and which shard host the thread
        # drives (poll-thread-only seams route through these)
        self._tls = threading.local()
        self._poll_idents: set[int] = set()
        self.conns: dict[int, _NativeConn] = {}
        self._stop = threading.Event()
        if self.fast_path and app is not None:
            # replay-then-attach under the store lock: no mutation can
            # slip between the boot snapshot and observer registration
            app.retainer.mirror_attach(self._on_retained_event)
            app.native_retain_fn = self._native_retained
            self._retain_mirrored = True
        self._thread: Optional[threading.Thread] = None
        self._shard_threads: list[threading.Thread] = []
        self._last_housekeep = time.monotonic()
        self._tick_running = threading.Event()
        # device serving path: one poll step's PUBLISHes coalesce into
        # one kernel launch (the epoll batch IS the {active,N} batch)
        self.pipeline = getattr(app, "pipeline", None)
        # -- device match lane (VERDICT r4 #2: the device router ON the
        # C++ data plane). "on"/"off"/"auto": auto flips with table
        # size (LANE_AUTO_* hysteresis, judged each housekeep) because
        # the per-message C++ walk wins below the crossover point.
        self.device_lane = device_lane if fast_path else "off"
        self._lane_on = False
        self._lane_q: queue.SimpleQueue = queue.SimpleQueue()
        self._lane_stop = threading.Event()
        self._lane_thread: Optional[threading.Thread] = None
        self._lane_stale_seen = 0
        self._lane_retry_at = 0.0    # monotonic backoff after stale trip
        # recently closed conns: (clientid, proto_ver, username,
        # peername) kept so a lane frame punted — or a rule tap emitted
        # — AFTER its publisher disconnected can still be honoured; on
        # the walk path both are synchronous so this window cannot occur
        self._closed_conns: dict[int, tuple] = {}  # @guards(_closed_lock)
        # -- rule taps (VERDICT r4 #5: no broad-rule permit cliff) ----------
        # rule FROM filters mirror into the C++ table as NON-delivering
        # tap entries; matched frames copy here and a worker runs the
        # rule engine against them while native fan-out proceeds. The
        # queue is bounded: under sustained rule-eval overload frames
        # are counted into tap_dropped instead of stalling the plane.
        self._rule_taps: dict[str, int] = {}          # filter -> token
        # entries are BATCH records (~≤192KB each): 128 bounds worst-
        # case buffering at ~24MB / a few hundred thousand messages
        self._tap_q: queue.Queue = queue.Queue(maxsize=128)
        self.tap_dropped = 0      # @guards(_tap_lock): N shard threads
        # serializes the tap_dropped read-modify-write: queue.Full is
        # decided per shard poll thread, and two threads folding the
        # drop count with bare += lose updates (nativecheck pyfold
        # finding, round 14)
        self._tap_lock = threading.Lock()
        self._tap_thread: Optional[threading.Thread] = None
        # the mqtt.max_qos_allowed cap must hold on the fast path too:
        # over-cap publishes fall through to the channel's DISCONNECT
        max_qos = getattr(self.broker, "max_qos_allowed", 2)
        if max_qos < 2:
            self.host.set_max_qos(max_qos)
        # one long-lived worker for app.tick() — spawning a thread per
        # housekeep cycle would churn an OS thread every few seconds
        self._tick_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="emqx-native-tick")
        # -- fast-path state ------------------------------------------------
        # punt-marker owner tokens live far above any conn id so the C++
        # table can hold both in one owner space
        self._punt_token_next = 1 << 48
        self._punt_tokens: dict[str, int] = {}          # sid -> token
        # (sid, sub key) -> (owner, real filter, kind) for removal;
        # several sub keys can share one punt (token, real) C++ entry
        # ($share/g1/t + $share/g2/t), so punt entries are refcounted
        self._mirror: dict[tuple[str, str], tuple[int, str, str]] = {}  # @guards(_mirror_lock)
        self._punt_refs: dict[tuple[int, str], int] = {}
        self._token_refs: dict[str, int] = {}           # sid -> live punts
        # serializes the refcounted punt bookkeeping AND the _mirror
        # read-modify-write itself: sub events arrive on broker
        # threads, route events on cluster threads, and the
        # demote/promote re-mirror loops on the poll thread.
        # REENTRANT because _on_sub_event holds it across _add_entry /
        # _del_entry / _token, which acquire it for the punt refcounts
        # (nativecheck pyfold finding, round 14: the unlocked mirror
        # get/set/pop raced the poll-thread loops' snapshot+re-add)
        self._mirror_lock = threading.RLock()
        self._route_punts: set[tuple[str, str]] = set()
        self._fast_conn_of: dict[str, int] = {}         # clientid -> conn
        self._granted: dict[int, set[str]] = {}         # conn -> topics
        self._permit_lock = threading.Lock()
        self._permit_queue: list[tuple[_NativeConn, str]] = []
        self._last_permit_flush = time.monotonic()
        self._stats_seen = {k: 0 for k in native.STAT_NAMES}
        # drained ack-record totals (observability + the windowed-qos1
        # smoke test's "inflight never exceeds receive-maximum" probe)
        self.ack_plane = {"acked": 0, "rel": 0,  # @guards(_ack_lock)
                          "batches": 0,
                          "max_inflight_seen": 0}
        # (group, real filter) -> {"members": {sid: opts},
        #                          "installed": None | "punt" | {sid: conn}}
        # guarded by _shared_lock: subscribe events arrive on broker
        # threads while strategy changes arrive on the config thread,
        # and an interleaved reconcile would desync "installed" from
        # the C++ table
        self._shared_state: dict[tuple[str, str], dict] = {}  # @guards(_shared_lock)
        self._sid_groups: dict[str, set[tuple[str, str]]] = {}
        self._shared_lock = threading.Lock()
        if app is not None:
            if not hasattr(app, "on_shared_strategy_change"):
                app.on_shared_strategy_change = []
            app.on_shared_strategy_change.append(self.reeval_shared_groups)
        self.broker.sub_observers.append(self._on_sub_event)
        self.broker.router.route_observers.append(self._on_route_event)
        # mirror subscriptions that existed before this server started
        # (resumed persistent sessions, other transports on the same app)
        for (sid, topic), opts in list(self.broker.suboption.items()):
            self._on_sub_event("add", sid, topic, opts)
        # restart gap (review finding): sessions recovered from the
        # persistent store have NO broker-table subs until they resume,
        # so the loop above cannot install their entries — a fast
        # publish in that window would bypass BOTH stores and be
        # acked-but-lost. Install durable entries for every stored
        # session's plain filters at boot; the resume's re-fired sub
        # events upsert them idempotently.
        if self._durable_store is not None:
            for sid, rec in self.app.persistent.store.all_sessions():
                for filt, od in (rec.get("subs") or {}).items():
                    grp, real = T.parse_share(filt)
                    if grp is None:
                        tok = self._durable_token(sid)
                        self.host.durable_add(
                            tok, real, int((od or {}).get("qos", 0) or 0))
                        self._durable_filters.setdefault(
                            sid, set()).add(real)
        # ...and pre-existing remote routes (a node joining a live
        # cluster replays the route snapshot before listeners start)
        for topic, dest in self.broker.router.dump():
            self._on_route_event("add", topic, dest)
        # eager permit flushes: a new rule/bridge/trace/metric/rewrite/
        # exhook watcher must see already-fast topics immediately, not
        # after the TTL. (app.exhook is None until configured; a server
        # built before exhook config falls back to the TTL for it.)
        for comp in ("bridges", "trace", "topic_metrics",
                     "rewrite", "exhook"):
            obj = getattr(app, comp, None) if app is not None else None
            if hasattr(obj, "on_topology_change"):
                # traces get a richer callback: clientid traces also
                # punt their conns at the C++ seam (emqx_host_set_trace)
                # so the hook fold sees every publish immediately — the
                # permit flush alone leaves the subscriber-side and any
                # already-granted permit window open
                obj.on_topology_change.append(
                    self._on_trace_change if comp == "trace"
                    else self.flush_permits)
        # rules get a richer callback: tap entries sync FIRST (ops apply
        # FIFO on the poll thread, so post-flush grants see the taps),
        # then the permit flush
        if app is not None and hasattr(app.rules, "on_topology_change"):
            app.rules.on_topology_change.append(self._on_rules_change)
            if self.fast_path:
                self._sync_rule_taps()
        # native-mode traces running BEFORE this server existed must
        # feed the span log from the first sampled publish
        self._native_traced = self._native_trace_clientids()

    # -- fast-path control --------------------------------------------------

    def _on_rules_change(self) -> None:
        self._sync_rule_taps()
        self.flush_permits()

    # -- trace punt (observability) -----------------------------------------
    # A clientid trace must capture publishes from connections already
    # on the native fast path. Closing the blind spot needs BOTH seams:
    # set_trace marks the conn in C++ (its PUBLISHes punt to the Python
    # plane, where the TraceManager hook sees them, and its flight-
    # recorder tail dumps onto the trace log) and flush_permits revokes
    # the topic grants so nothing else on those topics overtakes the
    # punted frames.

    def _traced_clientids(self) -> set:
        """Clientids whose traces PUNT their conns (mode="punt", the
        full-fidelity fallback). mode="native" traces never punt: their
        clients stay on the fast path and the trace log receives the
        sampled span timelines instead (_on_spans)."""
        if self.app is None:
            return set()
        return {t.filter_value for t in self.app.trace.running()
                if t.filter_type == "clientid"
                and getattr(t, "mode", "punt") != "native"}

    def _native_trace_clientids(self) -> set:
        if self.app is None:
            return set()
        return {t.filter_value for t in self.app.trace.running()
                if t.filter_type == "clientid"
                and getattr(t, "mode", "punt") == "native"}

    def _sync_traces(self) -> None:
        """Reconcile the C++ per-conn trace flags with the running
        clientid traces. Thread-safe: set_trace enqueues onto the poll
        thread; _fast_conn_of reads are GIL-atomic snapshots; the
        bookkeeping set updates under _trace_lock (see its comment)."""
        with self._trace_lock:
            want = set()
            for cid in self._traced_clientids():
                conn_id = self._fast_conn_of.get(cid)
                if conn_id is not None:
                    want.add(conn_id)
            for conn_id in want - self._traced_conns:
                self.host.set_trace(conn_id, True)
            for conn_id in self._traced_conns - want:
                self.host.set_trace(conn_id, False)
            self._traced_conns = want

    def _on_trace_change(self) -> None:
        self._sync_traces()
        # refresh the native-mode set the span fold consults (a plain
        # replace: reads are GIL-atomic snapshots)
        self._native_traced = self._native_trace_clientids()
        self.flush_permits()

    def _sync_rule_taps(self) -> None:
        """Reconcile the C++ rule-tap entries with the live FROM
        filters. Thread-safe (sub_add/del enqueue onto the poll
        thread); _mirror_lock serializes concurrent topology events."""
        if not self.fast_path or self.app is None:
            return
        want = set(self.app.rules.publish_filters())
        with self._mirror_lock:
            cur = self._rule_taps
            for f in want - cur.keys():
                tok = self._punt_token_next
                self._punt_token_next += 1
                cur[f] = tok
                self.host.sub_add(tok, f, 0, native.SUB_RULE_TAP)
            for f in list(cur.keys() - want):
                self.host.sub_del(cur.pop(f), f)

    def flush_permits(self) -> None:
        """Topology changed (rule created, authz update, trace started):
        every publisher re-earns its permits through the full path.
        Mutually exclusive with _grant_permits — a flush from a
        management thread landing mid-grant must not leave a stale
        permit for the freshly watched topic (the grant loop would
        otherwise add to an orphaned set and install a C++ permit the
        flush can no longer see)."""
        with self._permit_lock:
            self.host.permits_flush()
            self._granted.clear()

    def fast_stats(self) -> dict[str, int]:
        return self.host.stats()

    # -- retained snapshot (round 11) ---------------------------------------

    def _on_retained_event(self, op: str, topic: str, msg,
                           deadline_ms: int) -> None:
        """Retainer observer: mirror one store/delete into the host
        snapshot. Fired under the retainer lock from any thread —
        host ops enqueue + wake, never block."""
        if self._stop.is_set():
            return
        if op == "del":
            self._retain_unmirrorable.discard(topic)
            self.host.retain_del(topic)
            self._coap_retain_sync()
            return
        props = (msg.headers or {}).get("properties") or {}
        # the native encode carries no v5 property section (fast-path
        # contract); a message with properties (Message-Expiry included
        # — Python forwards the REMAINING interval on delivery) would
        # lose them on the native wire, so those stay Python-served
        if props:
            self._retain_unmirrorable.add(topic)
            self.host.retain_del(topic)
            self._coap_retain_sync()
            return
        self._retain_unmirrorable.discard(topic)
        self.host.set_retained(topic, bytes(msg.payload or b""),
                               int(msg.qos or 0), deadline_ms)
        self._coap_retain_sync()

    def _native_retained(self, sid: str, topic: str, real: str,
                         opts) -> bool:
        """app.native_retain_fn seam (called inside the
        session.subscribed hook): serve this subscription's retained
        set below the GIL when the subscriber is THIS server's live
        fast conn. Degradation ladder: any unmirrorable message, a
        non-fast/foreign subscriber, or an off-poll-thread call falls
        back to the Python retainer lookup (always correct)."""
        if self._retain_unmirrorable or self._stop.is_set():
            return False
        if threading.get_ident() not in self._poll_idents:
            return False          # another server/transport owns this sub
        # the conn whose frame this thread is handling (thread-local:
        # each shard's poll thread serves its own conns)
        conn = getattr(self._tls, "frame_conn", None)
        if (conn is None or not conn.fast
                or conn.channel.clientid != sid
                or conn.channel.conn_state != "connected"):
            return False
        self.host.retain_deliver(conn.conn_id, real,
                                 int(getattr(opts, "qos", 0) or 0))
        return True

    def _coap_retain_sync(self) -> None:
        """Keep the host's plain-GET gate aligned with the mirror:
        ANY props-carrying retained topic makes the snapshot
        incomplete, and native CoAP reads degrade whole to the
        oracle's lookup (never a partial answer)."""
        if self.coap_port is None:
            return
        complete = not self._retain_unmirrorable
        if complete != self._coap_retain_ok:
            self._coap_retain_ok = complete
            self.host.coap_retain_state(complete)

    # -- coap oracle seam (round 19) ----------------------------------------
    # Exchanges the native CoAP vocabulary excludes (block-wise
    # transfers, props-carrying retained reads, non-/ps paths — the
    # LwM2M registration surface) arrive as kind-13 events carrying the
    # raw datagram; a per-peer gateway/coap.py channel (or the
    # configured ``coap_oracle`` class) serves them WHOLE and answers
    # back through the native datagram socket. The channel's ``send``
    # binding also carries broker deliveries (LwM2M downlink commands)
    # to the device over the native transport.

    # @locked(_coap_lock)
    def _coap_channel(self, conn_id: int):
        ch = self._coap_oracle.get(conn_id)
        if ch is None:
            ch = self._coap_factory(self._coap_ctx)
            ch.send = (lambda frames, _cid=conn_id:
                       self._coap_reply(_cid, frames))
            # broker deliveries (cm.dispatch) call handle_deliver from
            # whatever thread published: serialize with the poll
            # thread's handle_in under the (reentrant) channel lock
            orig_hd = ch.handle_deliver

            def _hd(items, _o=orig_hd):
                with self._coap_lock:
                    return _o(items)

            ch.handle_deliver = _hd
            self._coap_oracle[conn_id] = ch
        return ch

    def _coap_reply(self, conn_id: int, frames) -> None:
        """Serialize + ship oracle-channel responses to the peer (the
        channel's ``send`` binding; Frame.serialize is stateless and
        coap_send is a thread-safe op enqueue)."""
        for f in frames or ():
            self.host.coap_send(conn_id, self._coap_frame.serialize(f))

    def _on_coap(self, conn_id: int, dgram: bytes) -> None:
        """Kind-13 fold: one exchange degraded WHOLE to the oracle."""
        with self._coap_lock:
            try:
                ch = self._coap_channel(conn_id)
                msgs, _ = self._coap_frame.parse(dgram, None)
                out = []
                for m in msgs:
                    out.extend(ch.handle_in(m) or [])
            except Exception:
                log.exception("coap oracle channel error (conn %#x)",
                              conn_id)
                return
        self._coap_reply(conn_id, out)

    def _coap_housekeep(self) -> None:
        """Oracle-channel tick: CON retransmits and give-ups (LwM2M
        downlink commands) — the asyncio listener's housekeep twin."""
        with self._coap_lock:
            for conn_id, ch in list(self._coap_oracle.items()):
                hk = getattr(ch, "housekeep", None)
                if hk is None:
                    continue
                try:
                    out = hk()
                except Exception:
                    continue
                self._coap_reply(conn_id, out)

    # -- device match lane --------------------------------------------------
    # Permitted PUBLISHes park in C++ while their topics ride batched
    # RouterModel launches; the response names each message's matched
    # filter strings and C++ fans out by exact per-filter lookup
    # (router.h MatchFilter). The per-message walk remains the correct
    # fallback at every seam: soft cap, pump failure, stale drain.

    def _lane_model(self):
        return getattr(self.broker, "model", None)

    def _set_lane(self, on: bool) -> None:
        if on == self._lane_on:
            return
        if on:
            if self._lane_model() is None:
                return
            self._lane_stop.clear()
            if self._lane_thread is None or not self._lane_thread.is_alive():
                self._lane_thread = threading.Thread(
                    target=self._lane_pump, name="emqx-lane-pump",
                    daemon=True)
                self._lane_thread.start()
            log.info("device lane ON (filters=%s)", self._lane_filters())
        else:
            log.info("device lane OFF")
        self._lane_on = on
        self.host.set_lane(on)   # off drains parked frames to Python

    def _lane_filters(self) -> int:
        model = self._lane_model()
        if model is None:
            return 0
        index = model.index
        live = getattr(index, "live_count", None)
        if callable(live):
            return int(live())
        return sum(f is not None for f in index.filters)

    def _lane_auto(self) -> None:
        """Housekeep-cadence lane policy: stale-trip resync first (the
        C++ side turns itself off when the pump stops answering — the
        Python flag must follow or no re-enable can ever happen), then
        the device_lane=auto size hysteresis."""
        stale = self.fast_stats()["lane_stale"]
        if stale > self._lane_stale_seen:
            self._lane_stale_seen = stale
            if self._lane_on:
                log.warning("device lane stale-tripped in C++; resyncing "
                            "(retry in %ss)", LANE_STALE_BACKOFF_S)
                self._lane_on = False   # C++ already drained + disabled
                # a wedged device would re-trip every few seconds: the
                # walk/Python paths are always correct, so sit out the
                # backoff before trusting the pump again
                self._lane_retry_at = (time.monotonic()
                                       + LANE_STALE_BACKOFF_S)
        if not self._lane_on and time.monotonic() < self._lane_retry_at:
            return
        if self.device_lane == "on":
            self._set_lane(True)
            return
        if self.device_lane != "auto" or self._lane_model() is None:
            return
        n = self._lane_filters()
        if not self._lane_on and n >= LANE_AUTO_ON_FILTERS:
            self._set_lane(True)
        elif self._lane_on and n < LANE_AUTO_OFF_FILTERS:
            self._set_lane(False)

    def _lane_pump(self) -> None:
        """Pump thread: drain lane topics, submit batched device
        launches (up to LANE_PIPE_DEPTH in flight — the double-buffering
        that hides the device round trip), and answer C++ with the
        matched filter strings. Every failure answers 'punt' so the
        frames take the always-correct Python path."""
        model = self._lane_model()
        pending: deque = deque()   # submitted, uncollected device batches
        inbox: deque = deque()     # (seq, topic) awaiting submission
        try:
            while not self._lane_stop.is_set():
                try:
                    items = self._lane_q.get(
                        timeout=0.0005 if (pending or inbox) else 0.05)
                except queue.Empty:
                    items = None
                if items:
                    inbox.extend(items)
                    while True:     # coalesce everything already queued
                        try:
                            inbox.extend(self._lane_q.get_nowait())
                        except queue.Empty:
                            break
                # submission is depth-gated: a burst must not fan into
                # an unbounded launch queue whose tail waits past the
                # C++ stale deadline — excess stays in the inbox and
                # rides the next (larger) batch instead
                while inbox and len(pending) < LANE_PIPE_DEPTH:
                    n = min(len(inbox), LANE_MAX_BATCH)
                    chunk = [inbox.popleft() for _ in range(n)]
                    # items are (shard host, seq, topic): one device
                    # batch may mix shards, the response splits per host
                    seqs = [(h, s) for h, s, _ in chunk]
                    topics = [t for _, _, t in chunk]
                    try:
                        pending.append(
                            (model.publish_batch_submit(topics), seqs))
                    except Exception:
                        log.exception("lane submit failed; punting")
                        self._lane_respond_punt(seqs)
                if pending and (len(pending) >= LANE_PIPE_DEPTH
                                or (items is None and not inbox)):
                    handle, seqs = pending.popleft()
                    try:
                        matched, aux, _slots, fallback = \
                            model.publish_batch_collect(handle)
                    except Exception:
                        log.exception("lane collect failed; punting")
                        self._lane_respond_punt(seqs)
                        continue
                    if aux and any(aux):
                        # aux = co-batched rule FROM filters: they map
                        # to the C++ RULE-TAP entries, so the response
                        # must name them or lane traffic would bypass
                        # the rules. Deduped: a filter both subscribed
                        # AND ruled appears in m and a, and naming it
                        # twice would double-deliver to its subscribers
                        # (MatchFilter appends per name)
                        matched = [
                            m + [x for x in a if x not in m] if a else m
                            for m, a in zip(matched, aux)]
                    self._lane_respond(seqs, matched, fallback)
        except Exception:
            log.exception("lane pump died; lane off")
        finally:
            for handle, seqs in pending:
                # collect (not just punt): publish_batch_submit opened
                # an inflight window on the index — skipping the
                # collect would quarantine freed filter ids forever
                try:
                    model.publish_batch_collect(handle)
                except Exception:
                    pass
                self._lane_respond_punt(seqs)
            if inbox:
                self._lane_respond_punt([(h, s) for h, s, _ in inbox])
            if self._lane_on:
                self._lane_on = False
                self.host.set_lane(False)

    def _lane_respond(self, seqs, matched, fallback) -> None:
        """``seqs`` are (shard host, seq) pairs: lane sequence numbers
        are per-host counters, so each response blob goes back to the
        host whose poll loop parked the frame."""
        fb = set(fallback or ())
        pack = struct.pack
        per: dict = {}
        for i, (h, seq) in enumerate(seqs):
            per.setdefault(h, []).append((i, seq))
        for h, items in per.items():
            parts = [pack("<I", len(items))]
            for i, seq in items:
                if i in fb:
                    # tokenizer reject / K-cap overflow: the kernel
                    # result is incomplete — Python re-matches it
                    parts.append(pack("<QBH", seq, 1, 0))
                    continue
                fs = matched[i]
                parts.append(pack("<QBH", seq, 0, len(fs)))
                for f in fs:
                    b = f.encode()
                    parts.append(pack("<H", len(b)))
                    parts.append(b)
            h.lane_deliver(b"".join(parts))

    def _lane_respond_punt(self, seqs) -> None:
        per: dict = {}
        for h, seq in seqs:
            per.setdefault(h, []).append(seq)
        for h, ss in per.items():
            parts = [struct.pack("<I", len(ss))]
            for seq in ss:
                parts.append(struct.pack("<QBH", seq, 1, 0))
            h.lane_deliver(b"".join(parts))

    def _fast_global(self) -> bool:
        # clustered nodes stay eligible: remote routes mirror into the
        # C++ table as punt markers via router.route_observers, so a
        # publish with any remote audience takes the Python path (which
        # forwards it over the cluster plane)
        return self.fast_path

    def _token(self, sid: str) -> int:
        # keys are NAMESPACED ("c:" clientids, "g:" share groups,
        # "n:" remote nodes) so a hostile clientid like "n:node2" can
        # never collide with an infrastructure token.
        # under _mirror_lock: concurrent first-use from a broker thread
        # and a cluster route thread must not mint two tokens
        with self._mirror_lock:
            tok = self._punt_tokens.get(sid)
            if tok is None:
                tok = self._punt_token_next
                self._punt_token_next += 1
                self._punt_tokens[sid] = tok
            return tok

    def _add_entry(self, sid: str, owner: int, real: str, kind: str,
                   qos: int, flags: int) -> None:
        if kind == "punt":
            with self._mirror_lock:
                key = (owner, real)
                self._punt_refs[key] = self._punt_refs.get(key, 0) + 1
                if self._punt_refs[key] == 1:
                    self._token_refs[sid] = self._token_refs.get(sid, 0) + 1
                    self.host.sub_add(owner, real, 0, native.SUB_PUNT)
        elif kind == "durable":
            # idempotent in C++ (SubTable Upsert keys on owner+filter),
            # so resume re-fires need no refcounting
            self.host.durable_add(owner, real, qos)
            with self._durable_lock:
                dsid = self._durable_sids.get(owner)
                if dsid is not None:
                    self._durable_filters.setdefault(dsid, set()).add(real)
        else:
            self.host.sub_add(owner, real, qos, flags)

    def _del_entry(self, sid: str, owner: int, real: str,
                   kind: str) -> None:
        if kind == "durable":
            self.host.durable_del(owner, real)
            with self._durable_lock:
                dsid = self._durable_sids.get(owner)
                if dsid is not None:
                    filters = self._durable_filters.get(dsid)
                    if filters is not None:
                        filters.discard(real)
                        if not filters:
                            del self._durable_filters[dsid]
            return
        if kind == "punt":
            with self._mirror_lock:
                key = (owner, real)
                n = self._punt_refs.get(key, 0) - 1
                if n > 0:
                    self._punt_refs[key] = n
                    return             # another sub key still needs it
                self._punt_refs.pop(key, None)
                left = self._token_refs.get(sid, 1) - 1
                if left <= 0:
                    # last punt for this sid: free its token so clientid
                    # churn doesn't leak dict entries forever
                    self._token_refs.pop(sid, None)
                    self._punt_tokens.pop(sid, None)
                else:
                    self._token_refs[sid] = left
                self.host.sub_del(owner, real)
                return
        self.host.sub_del(owner, real)

    # -- cluster routes ------------------------------------------------------
    # A remote-node route means subscribers this node cannot see in its
    # broker tables: mirror it as a punt marker so the fast path punts
    # matching publishes to Python, whose _route forwards them over the
    # cluster plane. This replaces the round-4-initial design of
    # disabling the fast path entirely on clustered nodes.

    def _on_route_event(self, op: str, topic: str, dest) -> None:
        node = None
        shared = isinstance(dest, tuple)
        if shared:
            node = dest[1]       # ({group}, node) shared route
        elif isinstance(dest, str):
            node = dest
        if node in (None, "local", self.broker.node):
            return               # local routes come via sub_observers
        # plain routes to a trunk-registered peer become REMOTE entries
        # (the third entry kind) instead of punt markers; shared routes
        # ALWAYS stay punt markers — the publishing node's Python picks
        # the group member cluster-wide (emqx_shared_sub semantics), so
        # the message must reach Python's shared_dispatch
        if not shared and self._trunk_route_event(op, node, topic):
            return
        sid = f"n:{node}"
        key = (sid, topic)
        # the router fires each (topic, dest) add/del exactly once in
        # table order; this set makes the bootstrap dump() replay
        # idempotent against events that raced in before the snapshot
        if op == "add":
            if key in self._route_punts:
                return
            self._route_punts.add(key)
            self._add_entry(sid, self._token(sid), topic, "punt", 0, 0)
        else:
            if key not in self._route_punts:
                return
            self._route_punts.discard(key)
            self._del_entry(sid, self._token(sid), topic, "punt")

    # -- cluster trunk -------------------------------------------------------

    def _trunk_route_event(self, op: str, node: str, topic: str) -> bool:
        """Install/remove a remote entry for a trunk-registered peer.
        Returns False when the peer has no trunk (punt-marker path) or
        when a delete targets a route that predates the registration."""
        with self._mirror_lock:
            peer = self._trunk_peers.get(node)
            key = (node, topic)
            if op == "add":
                if peer is None:
                    return False
                if key not in self._trunk_routes:
                    self._trunk_routes.add(key)
                    self.host.trunk_route_add(peer["id"], topic)
                return True
            if key not in self._trunk_routes:
                return False     # installed as a punt marker pre-register
            self._trunk_routes.discard(key)
            if peer is not None:
                self.host.trunk_route_del(peer["id"], topic)
            return True

    def trunk_register(self, node: str, host: str, port: int) -> None:
        """Wire a peer node's trunk: dial its listener and convert its
        existing plain-route punt markers into remote entries. Install-
        first ordering (ops apply FIFO on the poll thread): the remote
        entry lands BEFORE the punt marker goes, and an overlap punts —
        never a gap, never a double-delivery."""
        if self._stop.is_set():
            # a late hello/bye from the cluster plane must not reach a
            # destroyed host
            return
        with self._mirror_lock:
            peer = self._trunk_peers.get(node)
            if peer is not None and (peer["addr"], peer["port"]) == (host,
                                                                     port):
                # unchanged address: hello/ping re-learn this every
                # heartbeat — a re-dial here would tear down the
                # healthy link every ~5s (dropping in-flight qos0 and
                # re-replaying the qos1 ring); only a DOWN link dials
                pid = peer["id"]
                dial = not peer["up"]
            else:
                dial = True
                if peer is None:
                    pid = self._trunk_id_next
                    self._trunk_id_next += 1
                    peer = self._trunk_peers[node] = {
                        "id": pid, "addr": host, "port": port,
                        "up": False, "backoff": TRUNK_RETRY_S,
                        "retry_at": 0.0}
                    self._trunk_id_nodes[pid] = node
                else:            # address moved: re-dial below
                    pid = peer["id"]
                    peer.update(addr=host, port=port, up=False,
                                backoff=TRUNK_RETRY_S, retry_at=0.0)
        # bind the peer id to its stable NODE NAME BEFORE any remote
        # entry exists (ops apply FIFO on the poll thread): a qos1
        # publish matching a freshly converted route could otherwise
        # seal + journal a trunk batch under the per-process fallback
        # key in the cycle before the ident applied, stranding that
        # record AND skipping the previous life's ring merge (review
        # finding). Idempotent — the C++ side loads once per peer life.
        self.host.trunk_ident(pid, node)
        sid = f"n:{node}"
        # list() snapshot: route observers on other threads mutate the
        # set, and a bare comprehension can die mid-iteration
        converts = [t for (s, t) in list(self._route_punts) if s == sid]
        for topic in converts:
            with self._mirror_lock:
                if (node, topic) in self._trunk_routes:
                    continue
                self._trunk_routes.add((node, topic))
                self.host.trunk_route_add(pid, topic)
            self._route_punts.discard((sid, topic))
            self._del_entry(sid, self._token(sid), topic, "punt")
        # a route delete racing the snapshot above went through the
        # punt path (its key was in neither set at that instant) and
        # the convert re-installed it: re-check the authoritative
        # router table and drop conversions whose route vanished
        for topic in converts:
            if not any(r.dest == node for r in
                       self.broker.router.lookup_routes(topic)):
                self._trunk_route_event("del", node, topic)
        if dial:
            self.host.trunk_connect(pid, host, port)
            self._trunk_retry_at = min(self._trunk_retry_at,
                                       time.monotonic() + TRUNK_RETRY_S)

    def trunk_unregister(self, node: str, forget: bool = True) -> None:
        """Reverse of trunk_register: every remote entry flips back to
        a punt marker (punt-first, same no-gap reasoning) and the link
        drops."""
        if self._stop.is_set():
            return
        with self._mirror_lock:
            peer = self._trunk_peers.pop(node, None)
            if peer is None:
                return
            self._trunk_id_nodes.pop(peer["id"], None)
        sid = f"n:{node}"
        reverts = [t for (n, t) in list(self._trunk_routes) if n == node]
        for topic in reverts:
            self._route_punts.add((sid, topic))
            self._add_entry(sid, self._token(sid), topic, "punt", 0, 0)
            with self._mirror_lock:
                self._trunk_routes.discard((node, topic))
            self.host.trunk_route_del(peer["id"], topic)
        self.host.trunk_disconnect(peer["id"], forget=forget)

    def trunk_peer_status(self) -> dict[str, bool]:
        with self._mirror_lock:
            return {n: p["up"] for n, p in self._trunk_peers.items()}

    # -- faultline (round 15) ------------------------------------------------
    # Deterministic fault injection at the native plane's syscall seams
    # (native/src/fault.h). The server surface is a passthrough: the
    # host routes store sites to the attached durable store and, when
    # sharded, link-scoped sites to every shard. Every fired fault
    # counts a faults.<site> metric and lands in the degradation
    # ledger (reason "fault", aux = the site index) — chaos observable
    # through the same seams as organic degradation.

    def fault_arm(self, site: str, mode: str = "errno",
                  n_or_prob: float = 0.0, seed: int = 1,
                  key: int = 0) -> None:
        """Key-scoped conn/trunk arms land on the one shard that owns
        the object, so counted arms fire exactly n times; UNSCOPED
        arms on a sharded server broadcast — their counts and PRNG
        schedules are per shard."""
        self.host.fault_arm(site, mode, n_or_prob, seed, key)

    def fault_disarm(self, site: str) -> None:
        self.host.fault_disarm(site)

    def fault_fired(self, site: str) -> int:
        return self.host.fault_fired(site)

    def set_trunk_ack_timeout(self, ms: int) -> None:
        """Tighten/relax the silent-link watchdog (host.cc
        TrunkAckScan); the mesh soak drops it to milliseconds so a
        blackholed link resolves into a replay quickly."""
        self.host.set_trunk_ack_timeout(ms)

    def _on_trunk_event(self, peer_id: int, payload: bytes) -> None:
        if not payload:
            return
        sub = payload[0]
        if sub == native.TRUNK_PUNT:
            # receiver-side punts: trunk entries whose local match set
            # needs Python (persistent sessions, other transports, a
            # group flip raced with replication). Local dispatch only —
            # forwarding them again would loop the cluster.
            for _origin, qos, dup, topic, body in native.parse_trunk_punts(
                    payload):
                self._trunk_punt_dispatch(qos, dup, topic, body)
            return
        node = self._trunk_id_nodes.get(peer_id)
        # mirror the link state onto every NON-OWNER shard BEFORE the
        # permit flush below: their TrunkEligible oracle must flip
        # before publishers re-earn permits (the punt→trunk ordering
        # guard, extended across shards). The owner shard (peer % n,
        # round 15) ignores its own mirror entry — OwnsTrunkPeer routes
        # it to the authoritative peer state. Conservative while it
        # lags — a lagging mirror punts, never misroutes.
        for h in self.hosts:
            h.trunk_peer_state(peer_id, sub == native.TRUNK_UP)
        with self._mirror_lock:
            peer = self._trunk_peers.get(node) if node else None
            if peer is not None:
                peer["up"] = sub == native.TRUNK_UP
                if sub == native.TRUNK_UP:
                    peer["backoff"] = TRUNK_RETRY_S
                else:
                    # exponential backoff (capped) with ±25% jitter: a
                    # partitioned peer must not be re-dialed — and
                    # warned about — every second for the partition's
                    # whole duration, and a HEALED partition must not
                    # wake every peer's redial on the same capped
                    # boundary (thundering-herd reconnect in a full
                    # mesh — the round-15 satellite)
                    backoff = peer.get("backoff", TRUNK_RETRY_S)
                    peer["retry_at"] = time.monotonic() + (
                        backoff * self._redial_rng.uniform(
                            1 - TRUNK_RETRY_JITTER,
                            1 + TRUNK_RETRY_JITTER))
                    peer["backoff"] = min(backoff * 2, TRUNK_RETRY_CAP_S)
        if sub == native.TRUNK_UP:
            log.info("trunk up: peer %s (replay done)", node)
            # ordering guard for the punt→trunk flip: every publisher
            # re-earns permits once the pipeline is idle, so a trunked
            # fast message can never overtake a same-topic frame still
            # queued in the Python forward lane
            self.flush_permits()
        else:
            reason = payload[1:].decode("ascii", "replace")
            log.warning("trunk down: peer %s (%s); remote entries degrade "
                        "to punt markers until reconnect", node, reason)
            if peer is not None:
                self._trunk_retry_at = min(self._trunk_retry_at,
                                           peer["retry_at"])

    def _trunk_punt_dispatch(self, qos: int, dup: bool, topic: str,
                             body: bytes) -> None:
        """The receiving half of the Python forward lane, fed from a
        trunk punt record: dispatch to LOCAL subscribers exactly like
        cluster/node.py _h_dispatch does for broker.dispatch casts."""
        from emqx_tpu.core.message import Message

        m = Message(topic=topic, payload=body, qos=qos, from_="$trunk",
                    flags={"retain": False, "dup": dup},
                    headers={"properties": {}, "protocol": "mqtt"})
        deliveries: dict[str, list] = {}
        for route in self.broker.router.match_routes(topic):
            if route.dest == self.broker.node:
                self.broker._dispatch_local(route.topic, m, deliveries)
        if deliveries:
            self.cm.dispatch(deliveries)

    def _trunk_redial(self) -> None:
        now = time.monotonic()
        dial = []
        nxt = float("inf")
        with self._mirror_lock:
            for p in self._trunk_peers.values():
                if p["up"]:
                    continue
                at = p.get("retry_at", 0.0)
                if now >= at:
                    # schedule the NEXT attempt at this peer's backoff
                    # (±25% jitter — see _on_trunk_event); the C++ side
                    # ignores a dial while one is already in flight, so
                    # a slow connect is never torn down
                    p["retry_at"] = now + (
                        p.get("backoff", TRUNK_RETRY_S)
                        * self._redial_rng.uniform(
                            1 - TRUNK_RETRY_JITTER,
                            1 + TRUNK_RETRY_JITTER))
                    dial.append((p["id"], p["addr"], p["port"]))
                    nxt = min(nxt, p["retry_at"])
                else:
                    nxt = min(nxt, at)
        for pid, addr, port in dial:
            self.host.trunk_connect(pid, addr, port)
        self._trunk_retry_at = nxt

    # -- shared groups -------------------------------------------------------
    # A $share group is natively served only while EVERY member is a
    # fast native connection AND the node strategy is round_robin (the
    # only strategy the C++ dispatcher implements — the rest stay on
    # the Python SharedSub). Any other shape installs one punt marker
    # per (group, real filter), owned by a group token.

    def _group_token(self, group: str, real: str) -> int:
        return self._token(f"g:{group}/{real}")   # namespaced token pool

    def _shared_native_ok(self, sid: str, opts) -> bool:
        return (self._fast_global()
                and sid in self._fast_conn_of
                and getattr(opts, "subid", None) is None
                and getattr(self.app, "shared", None) is not None
                and self.app.shared.strategy == "round_robin")

    def _on_shared_event(self, op: str, sid: str, group: str,
                         real: str, opts) -> None:
        with self._shared_lock:
            st = self._shared_state.setdefault(
                (group, real), {"members": {}, "installed": None})
            if op == "add":
                st["members"][sid] = opts
                self._sid_groups.setdefault(sid, set()).add((group, real))
            else:
                st["members"].pop(sid, None)
                grps = self._sid_groups.get(sid)
                if grps is not None:
                    grps.discard((group, real))
                    if not grps:
                        del self._sid_groups[sid]
            self._reconcile_shared(group, real)

    # @locked(_shared_lock)
    def _reconcile_shared(self, group: str, real: str) -> None:
        """Idempotent: diff the desired serving shape for one group
        against what is installed in C++ and apply the delta.
        Caller holds _shared_lock."""
        gkey = (group, real)
        st = self._shared_state.get(gkey)
        if st is None:
            return
        token = self._group_token(group, real)
        members = st["members"]
        installed = st["installed"]
        if not members:
            if installed == "punt":
                self.host.sub_del(token, real)
            elif isinstance(installed, dict):
                for conn in installed.values():
                    self.host.shared_del(token, conn, real)
            self._shared_state.pop(gkey, None)
            with self._mirror_lock:
                self._punt_tokens.pop(f"g:{group}/{real}", None)
            return
        # _fast_conn_of is mutated by the poll thread outside this
        # lock: snapshot with .get and demote to punt on any miss
        # instead of racing into a KeyError
        new_map = ({s: self._fast_conn_of.get(s) for s in members}
                   if all(self._shared_native_ok(s, o)
                          for s, o in members.items()) else None)
        if new_map is not None and None not in new_map.values():
            # install-first ordering: the ops queue applies in FIFO, so
            # adding the group entries BEFORE deleting the punt marker
            # leaves no window where the group is served by neither
            # (overlap is safe — TryFast checks punt markers before any
            # group dispatch, so a punt+group overlap can't
            # double-deliver)
            old = installed if isinstance(installed, dict) else {}
            for s, conn in new_map.items():
                o = members[s]
                # upsert: refreshes qos/nl for existing members too
                self.host.shared_add(
                    token, conn, real, getattr(o, "qos", 0),
                    native.SUB_NO_LOCAL if getattr(o, "nl", 0) else 0)
            if installed == "punt":
                self.host.sub_del(token, real)
            for s, conn in old.items():
                if new_map.get(s) != conn:
                    self.host.shared_del(token, conn, real)
            st["installed"] = new_map
        else:
            # punt-first for the reverse flip, same no-gap reasoning
            if installed != "punt":
                self.host.sub_add(token, real, 0, native.SUB_PUNT)
            if isinstance(installed, dict):
                for conn in installed.values():
                    self.host.shared_del(token, conn, real)
            st["installed"] = "punt"

    def reeval_shared_groups(self) -> None:
        """Strategy change / membership-eligibility change: re-decide
        every group's serving mode (app.on_shared_strategy_change)."""
        with self._shared_lock:
            for group, real in list(self._shared_state):
                self._reconcile_shared(group, real)

    def _reconcile_sid_groups(self, sid: str) -> None:
        """Re-decide only the groups this client belongs to — O(own
        groups), not O(all groups), per connection event."""
        with self._shared_lock:
            for group, real in list(self._sid_groups.get(sid, ())):
                self._reconcile_shared(group, real)

    def _on_sub_event(self, op: str, sid: str, topic: str, opts) -> None:
        """Mirror one broker-table change into the C++ sub table.
        Thread-safe: host.sub_add/del enqueue onto the poll thread."""
        group, real = T.parse_share(topic)
        if group:
            self._on_shared_event(op, sid, group, real, opts)
            return
        # the whole get → add/del → set sequence under _mirror_lock
        # (reentrant: _token/_add_entry re-acquire it for the punt
        # refcounts): a broker-thread unsubscribe used to race the
        # poll thread's demote/promote re-mirror loops through the
        # unlocked read-modify-write (nativecheck pyfold finding,
        # round 14). Never holds across _on_shared_event — group subs
        # returned above and are never _mirror keys.
        with self._mirror_lock:
            self._on_sub_event_locked(op, sid, topic, real, opts)

    # @locked(_mirror_lock)
    def _on_sub_event_locked(self, op: str, sid: str, topic: str,
                             real: str, opts) -> None:
        if op == "add":
            conn_id = self._fast_conn_of.get(sid)
            # group subs never reach here (_on_sub_event routed them)
            if (conn_id is not None
                    and getattr(opts, "subid", None) is None):
                owner, kind = conn_id, "real"
                qos = getattr(opts, "qos", 0)
                flags = native.SUB_NO_LOCAL if getattr(opts, "nl", 0) else 0
            elif self._durable_ok(sid):
                # persistent session with the durable plane up: a
                # kSubDurable entry instead of a punt marker — the
                # publisher and every fast subscriber stay native while
                # the C++ host persists matching publishes for this
                # session (kind-10 reconciliation delivers/consumes)
                owner, kind = self._durable_token(sid), "durable"
                qos = getattr(opts, "qos", 0)
                flags = 0
            else:
                # shared group / non-durable persistent session /
                # subscription id on a fastless conn / subscriber
                # living on another transport: punt marker
                owner, kind = self._token("c:" + sid), "punt"
                qos = flags = 0
                self._warn_durable_punt(sid, topic)
            old = self._mirror.get((sid, topic))
            if old is not None and (old[0], old[1], old[2]) != (
                    owner, real, kind):
                # resubscribe flipped eligibility (e.g. a subscription
                # id appeared): the previously installed entry must go,
                # or it would keep delivering after UNSUBSCRIBE
                self._del_entry("c:" + sid, old[0], old[1], old[2])
            elif old is not None and kind == "punt":
                # duplicate 'add' for the same punt shape (resubscribe,
                # persistent-session resume re-firing every restored
                # sub): the mirror key holds EXACTLY one ref — a second
                # _add_entry would leave the refcount at 2 and the
                # single 'del' at unsubscribe would strand the punt
                # marker (topic slow-pathed forever) and leak tokens
                return
            self._add_entry("c:" + sid, owner, real, kind, qos, flags)
            self._mirror[(sid, topic)] = (owner, real, kind)
        else:
            ent = self._mirror.pop((sid, topic), None)
            if ent is not None:
                self._del_entry("c:" + sid, ent[0], ent[1], ent[2])

    # -- durable-session plane (round 10) -----------------------------------

    # Native store guids live far above Python message-id space so the
    # takeover dedup ({m.id for m in pending}) can never false-match a
    # Python-plane message against a store replay.
    DURABLE_GUID_BASE = 1 << 60

    def _durable_ok(self, sid: str) -> bool:
        return (self._durable_store is not None
                and self.app is not None
                and self.app.persistent is not None
                and self.app.persistent.is_persistent(sid))

    def _warn_durable_punt(self, sid: str, topic: str) -> None:
        """Carried edge (round 18): a persistence-less app used to
        degrade a persistent session's filters to punt-everything
        SILENTLY. Name the fallback once, loudly — the operator is one
        config knob away from the one-recovery-path durable plane."""
        if self._durable_punt_warned or self._durable_store is not None:
            return
        ch = self.cm.lookup_channel(sid)
        ci = getattr(ch, "conninfo", None)
        if ci is None or (ci.clean_start
                          and not ci.expiry_interval_ms):
            return    # clean session: the punt is not a durability story
        self._durable_punt_warned = True
        log.warning(
            "durable filter %r from persistent session %r has no "
            "persistence backing (app.persistent=%s, durable store "
            "off): falling back to PUNT-EVERYTHING — matching "
            "publishes take the Python slow path and queued messages "
            "will NOT survive a broker restart. Set durable.enable "
            "(or attach a persistent store) for the one-recovery-path "
            "durable plane.",
            topic, sid,
            "missing" if (self.app is None or self.app.persistent
                          is None) else "present")

    def _durable_token(self, sid: str) -> int:
        """sid -> store token (stable across restarts: the store
        journals REGISTER records and recovery replays them).

        Two locks: the token mint under _mirror_lock, then the reverse
        map + dead-set bookkeeping under _durable_lock — the kind-10
        fold reads _durable_sids under _durable_lock on the poll
        thread, and writing it under a DIFFERENT lock was no mutual
        exclusion at all (nativecheck pyfold finding, round 14).

        LOCK ORDER: _on_sub_event calls this while holding the
        reentrant _mirror_lock, so _durable_lock nests UNDER
        _mirror_lock here — that is the global order
        (_shared_lock -> _mirror_lock -> _durable_lock); never acquire
        _mirror_lock while holding _durable_lock."""
        with self._mirror_lock:
            tok = self._durable_tokens.get(sid)
            if tok is None:
                tok = self._durable_store.register(sid)
                self._durable_tokens[sid] = tok
        with self._durable_lock:
            self._durable_sids[tok] = sid
            # the store reuses a sid's journaled token across discard/
            # re-register, so a fresh persistent life revives it
            self._durable_dead.discard(tok)
        return tok

    def _durable_consume(self, sid: str, guids: list) -> None:
        """Spend store markers for ``sid`` — also the
        ``PersistentSessions.native_ack`` settle seam (round 18): the
        session calls here when a delivery of a store-backed message
        SETTLES (subscriber ack / qos0 write / final drop). Lookup
        falls back to the store: after a restart the token cache is
        empty but the registration survived."""
        if self._durable_store is None:
            return
        tok = (self._durable_tokens.get(sid)
               or self._durable_tok_cache.get(sid))
        if not tok:
            tok = self._durable_store.lookup(sid)
            if tok:
                # GIL-atomic write, deliberately NOT under _mirror_lock
                # (this runs with _durable_lock held from the kind-10
                # fold, and _mirror_lock must never nest under it):
                # sid→tok is stable within a token life, and a lost
                # race just repeats one lookup. _durable_discard pops
                # it with the primary cache.
                self._durable_tok_cache[sid] = tok
        if tok:
            n = self._durable_store.consume(tok, guids)
            if n:
                self.broker.metrics.inc("messages.durable.settled", n)

    def _on_durable(self, payload: bytes) -> None:
        """Fold ONE batched kind-10 durable record: per entry, deliver
        to each target persistent session's channel (live on ANY local
        transport — the cm holds disconnected channels too, whose
        session mqueue buffers) and consume the store marker when it
        reached a CONNECTED session, mirroring cm.dispatch's
        mark_delivered discipline. No channel at all (restart recovery
        state) leaves the marker for the resume replay.

        With shards, kind-10 records arrive from N poll threads
        concurrently (publishers on two shards can match one durable
        session); _durable_lock serializes the fold against itself and
        against a resume drain on another shard — the drain-watermark
        dedup is only exact when fetch/consume/fold can't interleave."""
        from emqx_tpu.core.message import Message

        with self._durable_lock:
            self._on_durable_locked(payload, Message)

    # @locked(_durable_lock)
    def _on_durable_locked(self, payload: bytes, Message) -> None:
        base, ts, entries = native.parse_durable(payload)
        pers = self.app.persistent if self.app is not None else None
        metrics = self.broker.metrics
        begin = now_ms()
        # consumes BATCH per record: each store.consume call journals a
        # record and pays the policy fsync — per-entry calls turned a
        # 120k-msg blast into 120k msyncs on the poll thread (measured:
        # the plane wedged for >30s draining them)
        consumed: dict[str, list] = {}
        dead: dict[int, list] = {}
        # consume-on-ack (round 18): a marker is spent only when the
        # delivery SETTLES. Effective-qos0 deliveries settle inside
        # handle_deliver (collected through a per-call settle sink so
        # this fold keeps its batched consume); qos1/2 deliveries keep
        # their marker until the subscriber's PUBACK/PUBCOMP reaches
        # the session's settle seam — a conn death between the socket
        # write and the ack keeps the marker, so a restart resume
        # RETRANSMITS instead of losing the message.
        for i, (origin, flags, toks, topic, body,
                _trace, cid) in enumerate(entries):
            guid = base + i
            sids, seen = [], set()
            for tok in toks:
                if tok in self._durable_dead:
                    # discard raced the async durable_del: the entry was
                    # still installed when this batch flushed, but the
                    # session is gone — spend the orphan marker now
                    dead.setdefault(tok, []).append(guid)
                    continue
                sid = self._durable_sids.get(tok)
                if sid is not None and sid not in seen:
                    seen.add(sid)
                    sids.append(sid)
            if not sids:
                continue
            metrics.inc("messages.durable.stored", len(sids))
            # resolve live channels BEFORE building the Message / trie
            # match: the common durable workload is a DISCONNECTED
            # persistent subscriber, and a 100k msg/s blast must not pay
            # a Python payload copy + trie match per entry on the poll
            # thread just to hit the marker-stays continue
            live = []
            for sid in sids:
                if guid <= self._durable_drain_mark.get(sid, 0):
                    # a resume drain in this same event window already
                    # fetched+consumed this guid and replayed it through
                    # the session — delivering again would duplicate
                    # (guids are monotonic and the drain fetches the
                    # whole pending set, so the watermark is exact)
                    continue
                ch = self.cm.lookup_channel(sid)
                if ch is None or ch.session is None:
                    continue       # marker stays: restart-resume replays
                live.append((sid, ch))
            if not live:
                continue
            info = self._conninfo_for(origin)
            msg = Message(
                topic=topic, payload=body, qos=(flags >> 1) & 3,
                # the persisted origin clientid wins (it also survives
                # a restart, where conninfo cannot)
                from_=cid or (info[0] if info else "$durable"),
                id=self.DURABLE_GUID_BASE + guid,
                flags={"retain": False, "dup": bool(flags & 8)},
                headers={"properties": {}, "protocol": "mqtt"},
                timestamp=ts,
            )
            # one trie match per entry, not per target sid — the dict is
            # already keyed by sid
            matches = (pers.router.match_filters(topic)
                       if pers is not None else {})
            for sid, ch in live:
                filt = matches.get(sid, topic)
                msg.extra["deliver_begin_at"] = begin
                sess = ch.session
                # the sink is a FILTER, not a replacement: another
                # thread (a PUBACK handled on a different shard's poll
                # thread, or the asyncio transport) can fire the
                # session's settle_fn concurrently with this fold —
                # its settle must still reach the persistence seam, or
                # an acked message's marker would replay forever; only
                # THIS entry's id collects locally (review finding)
                settled_here: list = []
                old_fn = getattr(sess, "settle_fn", None)
                if sess is not None:
                    this_id = msg.id

                    def sink(mid, _prev=old_fn, _cur=this_id,
                             _out=settled_here):
                        if mid == _cur:
                            _out.append(mid)
                        elif _prev is not None:
                            _prev(mid)

                    sess.settle_fn = sink
                try:
                    ch.send(ch.handle_deliver([(filt, msg)]))
                finally:
                    if sess is not None:
                        sess.settle_fn = old_fn
                if settled_here and ch.conn_state == "connected":
                    # the delivery settled synchronously (effective
                    # qos0 / final drop): the replay marker is spent.
                    # qos1/2 entries keep it until the ack settles
                    # through the session's own settle_fn.
                    consumed.setdefault(sid, []).append(guid)
        for sid, guids in consumed.items():
            self._durable_consume(sid, guids)
        for tok, guids in dead.items():
            self._durable_store.consume(tok, guids)

    def _durable_drain(self, sid: str) -> list:
        """PersistentSessions.native_drain seam: fetch + consume the
        native store's pending set for a resuming session. On the
        native server this runs on the poll thread (CONNECT handling),
        so the replay rides the native delivery machinery — the
        session.deliver packets go straight out through host.send —
        and the drain cost lands on the replay_drain telemetry stage."""
        store = self._durable_store
        if store is None:
            return []
        t0 = time.perf_counter_ns()
        # lookup, never register: a resuming session that never had a
        # durable entry must not mint-and-journal a token per resume
        tok = self._durable_tokens.get(sid) or store.lookup(sid)
        if not tok:
            return []
        # under _durable_lock: a kind-10 fold on ANOTHER shard's poll
        # thread must see fetch + watermark + consume as one step, or
        # the drained-guid dedup stops being exact
        with self._durable_lock:
            rows = self._durable_drain_locked(sid, store, tok)
        # poll-thread-only stamp, routed to THIS thread's shard host; a
        # drain driven from another server's thread (asyncio resume
        # sharing this app) is refused with -2
        host = getattr(self._tls, "host", None) or self.hosts[0]
        host.note_stage("replay_drain", time.perf_counter_ns() - t0)
        return rows

    def _durable_drain_locked(self, sid: str, store, tok: int) -> list:
        from emqx_tpu.core.message import Message

        rows = store.fetch(tok)
        pers = self.app.persistent
        # this process's python ids for Python-plane-persisted copies
        # (the unified store): a takeover mqueue copy carries the
        # python id, so the replay copy must dedup under the SAME id.
        # take_pyid is DESTRUCTIVE — this drain consumes the markers,
        # so the translations retire with the lookup (map hygiene)
        pyid_of = getattr(pers.store, "take_pyid", None) \
            if pers is not None else None
        out, guids = [], []
        for guid, origin, ts, qos, dup, topic, body, trace, cid in rows:
            guids.append(guid)
            if trace:
                # the persisted trace id re-joins its timeline: the
                # replay span marks resume delivery of a sampled
                # publish (poll-thread context, CLOCK_MONOTONIC like
                # the C++ spans)
                self.spans.record(trace, "replay",
                                  time.monotonic_ns(), aux=guid,
                                  node=self.broker.node)
            # the sub_topic header names the MATCHED FILTER: without it
            # a wildcard subscription's replay would miss the session's
            # SubOpts lookup and be dropped as 'late delivery' AFTER
            # its markers were consumed (review finding) — the same
            # contract the Python store replay keeps in persistent.py
            filt = pers.router.match_filters(topic).get(sid, topic)
            pyid = pyid_of(guid) if pyid_of is not None else None
            out.append(Message(
                # the persisted origin clientid keeps no-local honest
                # across the restart (round 18)
                topic=topic, payload=body, qos=qos,
                from_=cid or "$durable",
                id=(pyid if pyid is not None
                    else self.DURABLE_GUID_BASE + guid),
                flags={"retain": False, "dup": dup},
                headers={"properties": {}, "protocol": "mqtt",
                         "sub_topic": filt},
                timestamp=ts,
            ))
        if guids:
            # watermark BEFORE consuming: _on_durable skips delivery of
            # drained guids, and marking first keeps the skip engaged
            # even if a kind-10 fold interleaves with the consume
            self._durable_drain_mark[sid] = max(
                self._durable_drain_mark.get(sid, 0), max(guids))
            store.consume(tok, guids)
            self.broker.metrics.inc("messages.durable.replayed",
                                    len(guids))
        return out

    def _durable_discard(self, sid: str) -> None:
        """PersistentSessions.native_discard seam (clean-start wipe /
        session expiry): drop the session's native markers."""
        store = self._durable_store
        if store is None:
            return
        # lookup, never register: clean-start wipes of sessions that
        # never had durable state must not journal REGISTER records
        # (with session churn that grows the token map without bound)
        tok = self._durable_tokens.get(sid) or store.lookup(sid)
        if not tok:
            return
        # tear down the session's live durable entries too: a dead
        # token left matching would accumulate never-consumed markers
        # (and store segments) forever. durable_del applies at the NEXT
        # ApplyPending, so mark the token dead FIRST — a batch flushed
        # in the gap reaches _on_durable, which consumes the orphans
        with self._durable_lock:
            # the dead-set and filter-map writes hold the SAME lock the
            # kind-10 fold and _del_entry read them under — an unlocked
            # wipe raced _del_entry's filters.discard/del sequence
            # (code-review finding, round 14)
            self._durable_dead.add(tok)
            filters = self._durable_filters.pop(sid, ())
        for filt in filters:
            self.host.durable_del(tok, filt)
        with self._durable_lock:
            # the wipe must not interleave with a concurrent kind-10
            # fold on another shard's poll thread (fetch + consume is
            # one step, same reasoning as the resume drain)
            guids = [row[0] for row in store.fetch(tok)]
            if guids:
                store.consume(tok, guids)
        # retire the REGISTER/SESSION records too (round 18, the
        # session-expiry GC contract): a discarded session's metadata
        # must stop pinning segments. The store mints a FRESH token on
        # re-registration, so the per-sid cache must drop the old one —
        # a stale cached token would persist markers resume can no
        # longer find (acked-but-lost).
        store.unregister(sid)
        self._durable_tok_cache.pop(sid, None)
        with self._mirror_lock:
            self._durable_tokens.pop(sid, None)

    # -- live plane handoff (round 10) --------------------------------------

    def _on_handoff(self, conn_id: int, payload: bytes) -> None:
        """Drain one kind-11 demotion record: the C++ AckState becomes
        Python session state. Awaiting-rel ids adopt into the session's
        qos2 dedup set (a DUP retransmit straddling the demotion now
        answers PUBREC without re-delivering), unacked native
        deliveries adopt as window entries the client's acks retire,
        and window-full pending frames re-enqueue into the mqueue —
        which also makes them resume-replayable (take_pending), the
        retransmit-on-reconnect story the ROADMAP tracked."""
        conn = self.conns.get(conn_id)
        if conn is None:
            return      # demotion raced the close; teardown owns cleanup
        ho = native.parse_handoff(payload)
        ch = conn.channel
        sess = getattr(ch, "session", None)
        if conn.fast:
            self._demote_python_side(conn)
        if sess is None:
            return
        if conn.recv_budget:
            # the whole receive-maximum budget returns to the session
            sess.inflight.max_size = conn.recv_budget
            conn.native_cap = 0
        pending = []
        if ho["pending"]:
            from emqx_tpu.core.message import Message

            for frame in ho["pending"]:
                try:
                    pkt = parse_one(frame, ch.conninfo.proto_ver)
                except Exception:  # noqa: BLE001 — defensive
                    continue
                filt = self._match_sub(sess, pkt.topic)
                if filt is None:
                    continue
                pending.append((filt, Message(
                    topic=pkt.topic, payload=pkt.payload, qos=pkt.qos,
                    from_="$native",
                    flags={"retain": False, "dup": False},
                    headers={"properties": {}, "protocol": "mqtt"})))
        pkts = sess.adopt_native_window(
            ho["awaiting"], ho["inflight"], pending)
        if pkts:
            conn._send_packets(pkts)

    @staticmethod
    def _match_sub(sess, topic: str):
        if topic in sess.subscriptions:
            return topic
        for filt in sess.subscriptions:
            if T.match(topic, filt):
                return filt
        return None

    def _demote_python_side(self, conn: _NativeConn) -> None:
        """Python-side inverse of _maybe_enable_fast, driven by the
        kind-11 record so a bare host.disable_fast also reconciles:
        permits/grants drop, the clientid leaves the fast map, and the
        client's REAL entries re-mirror as punt/durable shapes so
        post-demotion deliveries run on the plane that owns the window."""
        ch = conn.channel
        cid = ch.clientid
        conn.fast = False
        with self._permit_lock:
            self._granted.pop(conn.conn_id, None)
        if self._fast_conn_of.get(cid) == conn.conn_id:
            del self._fast_conn_of[cid]
        # snapshot under the lock, iterate outside it: _on_sub_event
        # re-acquires it per key, and holding across the loop would
        # also order _mirror_lock under whatever the re-adds take
        with self._mirror_lock:
            mirror_items = list(self._mirror.items())
        for (sid, topic), (owner, real, kind) in mirror_items:
            if sid == cid and kind == "real":
                opts = self.broker.suboption.get((sid, topic))
                if opts is not None:
                    self._on_sub_event("add", sid, topic, opts)
        self._reconcile_sid_groups(cid)

    def promote(self, clientid: str) -> bool:
        """Re-enable the fast plane for a live clean-session conn after
        a demotion — the symmetric half of the kind-11 handoff. Nothing
        moves back into C++: every exchange the Python session holds
        stays Python-owned by construction (low pids route to it, and
        a PUBREL/DUP for an id the native awaiting-rel set doesn't own
        forwards), so promotion is a budget re-split plus fresh native
        state. Returns True when the conn re-qualified."""
        for conn in list(self.conns.values()):
            if (conn.channel.clientid == clientid and not conn.fast
                    and conn.channel.conn_state == "connected"):
                self._maybe_enable_fast(conn)
                return conn.fast
        return False

    def _maybe_enable_fast(self, conn: _NativeConn) -> None:
        """Post-CONNACK: clean sessions with no expiry get the fast
        path; persistent sessions keep every message in Python so their
        mqueue/inflight state stays authoritative."""
        ch = conn.channel
        ci = ch.conninfo
        if not self._fast_global():
            return
        if not ci.clean_start or ci.expiry_interval_ms:
            return
        conn.fast = True
        max_inflight = 0
        sess = getattr(ch, "session", None)
        if sess is not None and getattr(sess, "max_inflight", 0):
            # the client's Receive Maximum bounds ALL unacked QoS1/2
            # deliveries; native and Python deliver independently on the
            # same wire, so the budget is split between the planes. The
            # split starts half/half and is then re-divided every
            # batched ack cycle (_on_ack_batch): the busy plane grows,
            # the idle one shrinks, and the two caps always sum to the
            # budget so the client's window is never violated.
            budget = min(int(sess.max_inflight), 32766)
            max_inflight = max(1, budget // 2)
            sess.inflight.max_size = max(1, budget - max_inflight)
            conn.recv_budget = budget
            conn.native_cap = max_inflight
        # the clientid rides along (round 18): durable appends stamp it
        # into persisted entries so no-local / from_ survive a restart
        self.host.enable_fast(conn.conn_id, ci.proto_ver, max_inflight,
                              ch.clientid or "")
        self._fast_conn_of[ch.clientid] = conn.conn_id
        if ch.clientid in self._traced_clientids():
            # a running clientid trace predates this connection: punt
            # its publishes from the first frame, not the next sync
            with self._trace_lock:
                self.host.set_trace(conn.conn_id, True)
                self._traced_conns.add(conn.conn_id)
        # an earlier mirror pass may have installed this client's subs
        # as punt markers (it wasn't fast yet); re-mirror them as real
        # (_on_sub_event handles removal of the old entry on the flip);
        # snapshot under the lock, re-add outside (the demote shape)
        with self._mirror_lock:
            mirror_items = list(self._mirror.items())
        for (sid, topic), (owner, real, kind) in mirror_items:
            if sid == ch.clientid and owner != conn.conn_id:
                opts = self.broker.suboption.get((sid, topic))
                if opts is not None:
                    self._on_sub_event("add", sid, topic, opts)
        # shared groups this client belongs to may now be fully native
        self._reconcile_sid_groups(ch.clientid)

    def _slow_consumers_watch(self, ch, topic: str, *,
                              msg_events: bool | None = None) -> bool:
        """True when ANY message-plane consumer needs to see every
        publish on ``topic`` — the complete enumeration of everything
        the slow path's 'message.publish' fold can do with a live,
        non-retained, non-$ message. A topic a consumer watches never
        earns a permit; every consumer fires an eager flush hook on
        change (rules, bridges, traces, topic metrics, pub rewrites,
        exhook providers), with the permit TTL as the backstop."""
        app = self.app
        if app.rules.rules_for_topic(topic) and not self._rule_taps:
            # rules must see every message. With the tap mirror active
            # (fast_path servers sync it at startup and on every rule
            # change) the matched frames COPY to the rule runtime from
            # the fast path itself, so rules no longer veto permits —
            # the FROM '#' cliff (130x collapse to the Python plane) is
            # gone. _rule_taps empty means taps aren't mirrored (e.g.
            # rules exist but the sync hasn't run): keep the veto.
            return True
        if (msg_events if msg_events is not None
                else app.rules.watches_message_events()):
            # a $events/message_delivered|acked|dropped rule consumes
            # per-delivery events that only the Python plane fires —
            # native deliveries/acks/drops would silently bypass it, so
            # NO topic may hold a permit while one exists (create_rule's
            # on_topology_change flush revokes existing permits eagerly;
            # the grant loop precomputes msg_events once per cycle)
            return True
        if any(t.matches(ch.clientid, topic, str(ch.conninfo.peername))
                for t in app.trace.running()    # locked snapshot
                if getattr(t, "mode", "punt") != "native"):
            return True                 # traced topics stay observable
            # (native-mode traces deliberately do NOT veto the permit:
            # they observe via the sampled span plane, keeping the
            # traced workload on the fast path)
        if any(T.match(topic, f) for f in app.topic_metrics.topics()):
            return True
        rw = getattr(app, "rewrite", None)
        if rw is not None and any(
                r.action in ("publish", "all")
                and T.match(topic, r.source_topic)
                for r in rw.pub_rules):
            return True                 # topic rewrite redirects these
        br = getattr(app, "bridges", None)
        if br is not None:
            for b in br.bridges.values():
                local = ((b.conf.get("egress") or {}).get("local") or {})
                filt = local.get("topic")
                if filt and T.match(topic, filt):
                    return True         # direct egress forwards these
        ex = getattr(app, "exhook", None)
        if ex is not None:
            try:
                watchers = list(ex.servers.values())
            except RuntimeError:        # REST thread resizing the dict
                return True             # conservative: treat as watched
            if any(h.startswith("message.")
                   for s in watchers for h in s.hooks_wanted):
                return True             # providers watch the message plane
        return False

    def _grant_permits(self, queued=None) -> None:
        """Runs after pipeline.flush() in _step: every queued slow-path
        publish already delivered, so granting now preserves per-topic
        ordering across the slow→fast transition. Holds _permit_lock so
        a concurrent flush_permits (trace started on a REST thread)
        cannot interleave: grants re-check the consumer list under the
        lock, so they either complete before the flush (which then
        clears them) or start after it (and see the new watcher).
        ``queued`` is the pre-flush snapshot _step took (None = drain
        the live queue, the pre-shard call shape)."""
        with self._permit_lock:
            self._grant_permits_locked(queued)

    def _grant_permits_locked(self, queued=None) -> None:
        if queued is None:
            queued, self._permit_queue = self._permit_queue, []
        if not queued:
            return
        # topic-independent veto, hoisted so its O(rules) scan runs once
        # per grant cycle, not once per queued topic; the result feeds
        # _slow_consumers_watch below so the per-topic path skips it too
        msg_events = (self.app is not None
                      and self.app.rules.watches_message_events())
        if msg_events:
            return
        for conn, topic in queued:
            ch = conn.channel
            if (not conn.fast or ch.conn_state != "connected"
                    or not self._fast_global()):
                continue
            granted = self._granted.setdefault(conn.conn_id, set())
            if topic in granted or len(granted) >= MAX_PERMITS_PER_CONN:
                continue
            app = self.app
            if app is not None and self._slow_consumers_watch(
                    ch, topic, msg_events=msg_events):
                continue
            verdict = ch.hooks.run_fold(
                "client.authorize",
                (dict(clientid=ch.clientid,
                      username=ch.conninfo.username,
                      peername=ch.conninfo.peername),
                 "publish", topic),
                "allow")
            if verdict != "allow":
                continue
            granted.add(topic)
            self.host.permit(conn.conn_id, topic)

    # -- event loop ---------------------------------------------------------

    def _step_host(self, host, timeout_ms: int = 100) -> None:
        """Drain one poll cycle of ONE shard host. Runs concurrently on
        N poll threads when sharded: per-conn work is naturally
        shard-local (a conn id names its owner shard), the shared folds
        (acks/telemetry/durable) take their locks inside."""
        lane_buf = None
        for kind, conn_id, payload in host.poll(timeout_ms):
            if kind == native.EV_OPEN:
                conn = _NativeConn(
                    self, conn_id, payload.decode("ascii", "replace"))
                self.conns[conn_id] = conn
                # scanned until a native keepalive is armed and the
                # session proves idle (the housekeep drops it then)
                with self._scan_lock:
                    self._scan_conns[conn_id] = conn
            elif kind == native.EV_FRAME:
                conn = self.conns.get(conn_id)
                if conn is not None:
                    self._on_frame(conn, payload)
                else:
                    self._orphan_frame(conn_id, payload)
            elif kind == native.EV_LANE:
                # conn field carries the lane sequence number; the item
                # remembers its host so the pump answers the right shard
                # (lane seqs are per-host counters)
                if lane_buf is None:
                    lane_buf = []
                lane_buf.append(
                    (host, conn_id, payload.decode("utf-8", "replace")))
            elif kind == native.EV_TAP:
                self._on_tap(conn_id, payload)
            elif kind == native.EV_ACKS:
                # the id slot carries the producing shard (round 12);
                # conn ids inside the record are globally unique
                self._on_ack_batch(payload)
            elif kind == native.EV_TELEMETRY:
                self._on_telemetry(payload, conn_id)
            elif kind == native.EV_SPANS:
                # the id slot carries the producing shard (like 7/8/10)
                self._on_spans(payload, conn_id)
            elif kind == native.EV_TRUNK:
                self._on_trunk_event(conn_id, payload)
            elif kind == native.EV_DURABLE:
                self._on_durable(payload)
            elif kind == native.EV_HANDOFF:
                self._on_handoff(conn_id, payload)
            elif kind == native.EV_COAP:
                self._on_coap(conn_id, payload)
            elif kind == native.EV_CLOSED:
                with self._trace_lock:
                    self._traced_conns.discard(conn_id)
                with self._scan_lock:
                    self._scan_conns.pop(conn_id, None)
                with self._coap_lock:
                    och = self._coap_oracle.pop(conn_id, None)
                    if och is not None:
                        try:
                            och.terminate(payload.decode(
                                "ascii", "replace"))
                        except Exception:
                            pass
                conn = self.conns.pop(conn_id, None)
                if conn is not None:
                    ch = conn.channel
                    if conn.fast:
                        # a lane punt / rule tap may still surface this
                        # conn's frames (up to the stale deadline)
                        with self._closed_lock:
                            self._closed_conns[conn_id] = (
                                ch.clientid, ch.conninfo.proto_ver,
                                ch.conninfo.username,
                                ch.conninfo.peername)
                            if len(self._closed_conns) > 4096:
                                self._closed_conns.pop(
                                    next(iter(self._closed_conns)))
                    self._forget_fast(conn)
                    ch.terminate(payload.decode("ascii", "replace"))
        if lane_buf:
            self._lane_q.put(lane_buf)

    def _step(self, timeout_ms: int = 100) -> None:
        """One shard-0 loop step plus the server-global duties (the
        pipeline flush, permit grants, trunk redial, housekeep).
        Secondary shards run bare _step_host loops (_run_shard) with
        only their own conns' keepalive scan."""
        self._step_host(self.hosts[0], timeout_ms)
        # snapshot the permit queue BEFORE the flush: entries appended
        # by any shard's poll thread had their publishes submitted
        # first (handle_in submits, _on_frame appends after), so every
        # snapshotted entry's traffic is covered by THIS flush — while
        # an entry appended mid-flush could still have a publish queued
        # in the pipeline, and granting it now would let a fast message
        # overtake a queued slow one
        pending = None
        if self._permit_queue:
            with self._permit_lock:
                pending, self._permit_queue = self._permit_queue, []
        if self.pipeline is not None:
            self.pipeline.flush()
        if pending:
            self._grant_permits(pending)
        now = time.monotonic()
        if now >= self._trunk_retry_at:
            self._trunk_redial()
        if now - self._last_housekeep >= HOUSEKEEP_INTERVAL:
            self._last_housekeep = now
            self._housekeep()

    def _on_frame(self, conn: _NativeConn, frame: bytes) -> None:
        ch = conn.channel
        # context for the native retained seam: the session.subscribed
        # hook fires INSIDE handle_in, and _native_retained must know
        # which conn's SUBSCRIBE it is serving (thread-local: each
        # shard's poll thread handles only its own conns' frames)
        self._tls.frame_conn = conn
        try:
            pkt = parse_one(frame, ch.conninfo.proto_ver)
            if pkt.type == P.CONNECT:
                ch.conninfo.proto_ver = pkt.proto_ver
            out = ch.handle_in(pkt)
        except (FrameError, IndexError) as e:
            # per-connection fault isolation: a bad frame (or a channel
            # protocol error) drops this client, never the poll thread —
            # same containment the asyncio server gets from its per-conn task
            log.info("frame error from %s: %s", ch.conninfo.peername, e)
            if ch.conninfo.proto_ver == P.MQTT_V5:
                rc = getattr(e, "rc", P.RC_MALFORMED_PACKET)
                conn._send_packets([P.Disconnect(reason_code=rc)])
            self._drop(conn, "frame_error")
            return
        except Exception:
            log.exception("channel error from %s", ch.conninfo.peername)
            self._drop(conn, "channel_error")
            return
        finally:
            self._tls.frame_conn = None
        conn._send_packets(out)
        if ch.conn_state == "disconnected":
            self._drop(conn, "normal")
            return
        if pkt.type == P.CONNECT and ch.conn_state == "connected":
            # keepalive moves onto the C++ timer wheel for EVERY conn
            # (the host's last_rx stamp covers fast, slow, and SN
            # transports alike): the Python housekeep's O(N) idle
            # sweep is gone — C++ closes as "keepalive_timeout", the
            # same reason string the old Python path used
            ka = ch.conninfo.keepalive
            self.host.set_keepalive(
                conn.conn_id, ka * 1500 if ka else 0)
            conn.native_ka = True
            self._maybe_enable_fast(conn)
        elif (conn.fast and pkt.type == P.PUBLISH
              and not pkt.retain and pkt.topic
              and not pkt.topic.startswith("$")):
            # this publish took the full path (no permit yet): queue the
            # topic for a permit decision once the pipeline is idle.
            # All QoS levels qualify since round 6: the C++ host owns
            # the QoS2 exchange (awaiting-rel dedup + PUBREC/PUBREL/
            # PUBCOMP) for permitted topics
            self._permit_queue.append((conn, pkt.topic))

    def _conninfo_for(self, conn_id: int):
        """(clientid, proto_ver, username, peername) for a live or
        recently closed conn; None when unknown."""
        conn = self.conns.get(conn_id)
        if conn is not None:
            ci = conn.channel.conninfo
            return (conn.channel.clientid, ci.proto_ver, ci.username,
                    ci.peername)
        # under _closed_lock: the capped insert+evict runs on every
        # shard's poll thread while this reads from the tap worker
        with self._closed_lock:
            return self._closed_conns.get(conn_id)

    @staticmethod
    def _tap_count(batch: bytes) -> int:
        """Entries in one tap batch (header-only walk, drop accounting).
        Entry: [u64 publisher][u8 flags][u16 tlen][topic] +
        (flags bit0 ? [u32 plen][payload] : payload of previous entry);
        flags bits 1-2 = qos, bit 3 = publisher DUP."""
        n = pos = 0
        blen = len(batch)
        while pos + 11 <= blen:
            flags = batch[pos + 8]
            tlen = int.from_bytes(batch[pos + 9:pos + 11], "little")
            pos += 11 + tlen
            if flags & 1:
                if pos + 4 > blen:
                    break
                pos += 4 + int.from_bytes(batch[pos:pos + 4], "little")
            n += 1
        return n

    def _on_tap(self, _conn_id: int, batch: bytes) -> None:
        """Natively-delivered publishes that matched rule-tap entries,
        BATCHED into one record per C++ poll cycle and PRE-PARSED
        (host.cc EmitTap: topic/qos fields + payload-deduped bytes, the
        round-7 copy elision). The poll thread does ONE queue put per
        batch — decoding and conninfo resolution happen on the worker
        (per-message work here measurably throttled the data plane).
        Bounded: under sustained rule-eval overload whole batches drop,
        message-counted into tap_dropped."""
        try:
            self._tap_q.put_nowait(batch)
        except queue.Full:
            # under _tap_lock: += is a read-modify-write, and N shard
            # poll threads hitting Full together lost drop counts
            with self._tap_lock:
                self.tap_dropped += self._tap_count(batch)

    def _tap_worker(self) -> None:
        """Evaluate rules against tapped publishes off the poll thread.
        They were already natively delivered; only the rule engine sees
        them here (app.rules.ingest → same _fire path the hook fold
        uses). The entries arrive pre-parsed from C++, so no MQTT
        re-parse runs here — with full-frame copies + parse_one this
        worker's GIL hold was a chunk of the rule-tap tax on the data
        plane (BENCH_r05 rule_tap_vs_free=0.59). The rest is GIL
        latency: rule evaluation is ~20µs/message of pure Python, so
        without explicit releases the poll thread waits up to the 5 ms
        switch interval per GIL acquisition. Discipline: sleep(0)
        every 8 messages (~160 µs of work) hands the GIL over promptly
        — rule evaluation is elastic, the data plane is not. (No
        thread-priority drop: see the inline note at the yield.)
        conninfo lookups read self.conns cross-thread: GIL-safe, and a
        conn closed mid-read falls back to the recently-closed map (or
        is skipped)."""
        from emqx_tpu.core.message import Message

        ingest = self.app.rules.ingest
        done_since_yield = 0
        while not self._stop.is_set():
            try:
                batch = self._tap_q.get(timeout=0.2)
            except queue.Empty:
                continue
            pos, blen = 0, len(batch)
            payload = b""           # dedup carry (within one batch only)
            while pos + 11 <= blen:
                publisher = int.from_bytes(batch[pos:pos + 8], "little")
                flags = batch[pos + 8]
                tlen = int.from_bytes(batch[pos + 9:pos + 11], "little")
                pos += 11
                topic = batch[pos:pos + tlen].decode("utf-8", "replace")
                pos += tlen
                if flags & 1:
                    if pos + 4 > blen:
                        break       # truncated batch: defensive stop
                    plen = int.from_bytes(batch[pos:pos + 4], "little")
                    pos += 4
                    payload = batch[pos:pos + plen]
                    pos += plen
                info = self._conninfo_for(publisher)
                if info is None:
                    continue
                clientid, _proto_ver, username, peername = info
                try:
                    # fast-path publishes carry no v5 properties (the
                    # permit requires an empty property section), so
                    # the Message builds straight from the tap fields
                    msg = Message(
                        topic=topic, payload=payload,
                        qos=(flags >> 1) & 3, from_=clientid,
                        flags={"retain": False, "dup": bool(flags & 8)},
                        headers={"properties": {},
                                 "username": username,
                                 "peername": peername,
                                 "protocol": "mqtt"},
                    )
                    ingest(msg)
                except Exception:  # noqa: BLE001 — one bad entry/rule
                    log.exception("rule tap evaluation failed")
                done_since_yield += 1
                if done_since_yield >= 8:
                    # release the GIL mid-batch: the C++ plane only
                    # runs while a thread sits inside emqx_host_poll,
                    # so every ms the poll thread spends WAITING for
                    # the GIL is a stalled data plane. ~160µs stints
                    # bound that wait; the sleep(0) costs ~1µs per 8
                    # messages of ~20µs each. (Deliberately NOT paired
                    # with a lower thread priority: a deprioritized
                    # holder parked mid-stint is a priority inversion
                    # on the GIL.)
                    done_since_yield = 0
                    time.sleep(0)

    def _on_ack_batch(self, batch: bytes) -> None:
        """Drain ONE batched ack record (host.cc kind 7) — the per-poll
        cycle summary of every native window event: slots freed by
        PUBACK/PUBCOMP, publisher PUBREL completions, and the live
        inflight/pending depths per connection.

        Three jobs, all cycle-rate instead of message-rate:
        - fold the deltas into the node metrics (the slow path counts
          these inline per packet);
        - reconcile each session: gauges + mqueue handoff for
          natively-freed window slots (session.native_ack_sync);
        - re-divide the receive-maximum budget between the planes: the
          native cap tracks observed native demand, Python keeps the
          rest. Caps always sum to <= the budget and the cap op applies
          on the poll thread BEFORE the next socket read, so the
          client's Receive Maximum holds at every instant."""
        if len(batch) < 4:
            return
        n = int.from_bytes(batch[:4], "little")
        pos = 4
        tot_acked = tot_rel = max_seen = 0
        for _ in range(n):
            if pos + 24 > len(batch):
                break
            cid = int.from_bytes(batch[pos:pos + 8], "little")
            acked = int.from_bytes(batch[pos + 8:pos + 12], "little")
            rel = int.from_bytes(batch[pos + 12:pos + 16], "little")
            inflight_now = int.from_bytes(batch[pos + 16:pos + 20],
                                          "little")
            pending_now = int.from_bytes(batch[pos + 20:pos + 24],
                                         "little")
            pos += 24
            tot_acked += acked
            tot_rel += rel
            if inflight_now > max_seen:
                max_seen = inflight_now
            conn = self.conns.get(cid)
            if conn is None or not conn.fast:
                continue
            sess = getattr(conn.channel, "session", None)
            if sess is None:
                continue
            pkts = sess.native_ack_sync(inflight_now, pending_now, acked)
            if pkts:
                conn._send_packets(pkts)
            budget = conn.recv_budget
            if budget:
                # native demand estimate: current occupancy doubled
                # (headroom for the next cycle) or occupancy + queued
                # backlog, floored at the half split; Python retains at
                # least its live occupancy + one slot. Hysteresis: a
                # per-cycle cap op for every occupancy wiggle measurably
                # taxed the data plane — only re-divide on a real shift
                reserve = max(len(sess.inflight), 1)
                want = max(budget // 2, CAP_HEADROOM * inflight_now,
                           min(inflight_now + pending_now, budget))
                cap = max(1, min(want, budget - reserve))
                if abs(cap - conn.native_cap) >= max(CAP_DEADBAND_MIN,
                                                     budget
                                                     // CAP_DEADBAND_DIV):
                    conn.native_cap = cap
                    self.host.set_inflight_cap(cid, cap)
                    sess.inflight.max_size = max(1, budget - cap)
        # kind-7 records arrive from N poll threads when sharded: the
        # shared totals fold under _ack_lock (each conn's session sync
        # above is shard-local — a conn lives on exactly one shard)
        with self._ack_lock:
            ap = self.ack_plane
            ap["acked"] += tot_acked
            ap["rel"] += tot_rel
            ap["batches"] += 1
            if max_seen > ap["max_inflight_seen"]:
                ap["max_inflight_seen"] = max_seen
        m = self.broker.metrics
        if tot_acked:
            m.inc("messages.acked", tot_acked)
            m.inc("messages.native.acked", tot_acked)

    def _on_telemetry(self, payload: bytes, shard: int = 0) -> None:
        """Fold ONE batched kind-8 telemetry record (host.cc): per-cycle
        histogram deltas into the node metrics' LatencyHistograms,
        slow-ack samples into slow_subs (the native plane's entry into
        the slow-subscriber ranking), and flight-recorder dumps into
        the recent-dumps ring + any matching clientid trace log.
        Runs on the poll thread: cycle-rate, small records, no I/O.
        ``shard`` is the record's id-slot field (round 12): N poll
        threads fold concurrently under _tele_lock, and the deltas
        land in both the global and the per-shard histograms."""
        stages = native.HIST_STAGES
        shard_hists = self._shard_hists.get(shard)
        for rec in native.parse_telemetry(payload):
            kind = rec[0]
            if kind == "hist":
                _, stage_i, cnt, sum_ns, buckets = rec
                if stage_i < len(stages):
                    with self._tele_lock:
                        self._hists[stages[stage_i]].observe_delta(
                            cnt, sum_ns, buckets)
                        if shard_hists is not None:
                            shard_hists[stages[stage_i]].observe_delta(
                                cnt, sum_ns, buckets)
            elif kind == "slow_ack":
                _, conn_id, rtt_us, _qos, topic = rec
                info = self._conninfo_for(conn_id)
                if info is not None and self.app is not None:
                    # rank the SUBSCRIBER whose ack lagged, like the
                    # delivery.completed hook does on the Python plane
                    self.app.slow_subs.record(
                        info[0], topic, rtt_us // 1000, plane="native")
            else:  # flight-recorder dump
                _, conn_id, reason, entries = rec
                self.flight_records.append((conn_id, reason, entries))
                info = self._conninfo_for(conn_id)
                if info is None or self.app is None:
                    continue
                why = native.FR_REASON_NAMES.get(reason, str(reason))
                detail = (f"conn={conn_id} reason={why} "
                          + "; ".join(native.format_flight(entries)))
                self.app.trace.log_for_client(info[0], "FLIGHT", detail)
                if reason != 3:  # abnormal close / protocol error
                    log.debug("flight recorder dump (%s) for %s: %s",
                              why, info[0], detail)

    def _on_spans(self, payload: bytes, shard: int = 0) -> None:
        """Fold ONE batched kind-12 trace record: span points into the
        SpanCollector (+ the trace log for native-mode clientid traces
        + prometheus exemplars), ledger entries into the degradation
        ledger (fixed messages.ledger.* slots + the bounded event
        ring). Cycle-rate and sampled — runs on the poll thread under
        _tele_lock (N producers when sharded)."""
        stages = native.SPAN_STAGES
        reasons = native.LEDGER_REASONS
        node = self.broker.node
        with self._tele_lock:
            for rec in native.parse_spans(payload):
                if rec[0] == "span":
                    _, tid, stage_i, t_ns, aux = rec
                    stage = (stages[stage_i] if stage_i < len(stages)
                             else f"stage{stage_i}")
                    self.spans.record(tid, stage, t_ns, shard=shard,
                                      aux=aux, node=node)
                    if stage == "ingress" and self._native_traced:
                        info = self._conninfo_for(aux)
                        if (info is not None
                                and info[0] in self._native_traced):
                            self._trace_log_ids[tid] = info[0]
                            while len(self._trace_log_ids) > 256:
                                self._trace_log_ids.popitem(last=False)
                    cid = self._trace_log_ids.get(tid)
                    if cid is not None and self.app is not None:
                        # only deliver_write defines bit 63 (the span
                        # cap's truncation marker) — other stages' aux
                        # passes through untouched
                        trunc = ""
                        if stage == "deliver_write" and aux >> 63:
                            trunc, aux = " truncated", aux & ~(1 << 63)
                        self.app.trace.log_for_client(
                            cid, "SPAN",
                            f"trace={tid:016x} {stage} shard={shard} "
                            f"aux={aux} t_ns={t_ns}{trunc}")
                    # exemplars: hang the trace id off the stage
                    # histograms its timeline measures
                    if stage == "route":
                        self._exemplar(tid, "ingress", t_ns,
                                       "ingress_route")
                    elif stage == "ack":
                        # ack aux carries the delivery qos in bits
                        # 60-61 (host.cc TeleAckRtt) so a qos2
                        # exchange's exemplar lands on qos2_rtt
                        qos = (aux >> 60) & 3
                        self._exemplar(tid, "deliver_write", t_ns,
                                       "qos2_rtt" if qos == 2
                                       else "qos1_rtt")
                else:
                    _, reason_i, count, tid, aux, _t_ns = rec
                    name = (reasons[reason_i - 1]
                            if 1 <= reason_i <= len(reasons)
                            else f"reason{reason_i}")
                    self.ledger.record(name, count, shard=shard,
                                       trace_id=tid, aux=aux)

    # @locked(_tele_lock)
    def _exemplar(self, tid: int, from_stage: str, t_ns: int,
                  hist: str) -> None:
        """Attach ``t_ns - t(from_stage)`` of trace ``tid`` as an
        OpenMetrics exemplar on ``hist`` (caller holds _tele_lock)."""
        for t0, stage, _sh, _n, _aux in self.spans.trace(tid):
            if stage == from_stage:
                if t_ns > t0:
                    self._hists[hist].put_exemplar(tid, t_ns - t0)
                return

    def spans_recent(self, limit: int = 32) -> list[dict]:
        """Assembled recent traces, JSON-shaped (the mgmt surface)."""
        out = []
        for tid, spans in self.spans.recent(limit):
            # deliver_write aux bit 63 = the 8-per-publish span cap
            # clipped this fan-out (host.cc kSpanTruncBit): surface it
            # so a stitched timeline never silently reads as the full
            # audience. Only deliver_write defines the bit — other
            # stages' aux passes through unmasked (ack already packs
            # qos into bits 60-61).
            out.append({
                "trace_id": f"{tid:016x}",
                "spans": [{"t_ns": t, "stage": s, "shard": sh,
                           "node": n,
                           "aux": (a & ~(1 << 63)
                                   if s == "deliver_write" else a),
                           "truncated": (s == "deliver_write"
                                         and bool(a >> 63))}
                          for t, s, sh, n, a in spans],
            })
        return out

    def latency_summary(self) -> dict[str, dict]:
        """Broker-side stage percentiles (p50/p99/p999 in µs + counts)
        for every stage with observations — the bench.py artifact
        surface next to the loadgen-side numbers."""
        return {stage: h.summary()
                for stage, h in self._hists.items() if h.count > 0}

    def shard_latency_summary(self) -> dict[int, dict]:
        """Per-shard stage percentiles (bench surface for the shards
        section); empty on an unsharded server."""
        return {shard: {stage: h.summary()
                        for stage, h in hists.items() if h.count > 0}
                for shard, hists in self._shard_hists.items()}

    def shard_stats(self) -> list[dict[str, int]]:
        """Raw per-shard host counters in shard order (the aggregate is
        ``fast_stats``)."""
        return [h.stats() for h in self.hosts]

    def _orphan_frame(self, conn_id: int, frame: bytes) -> None:
        """A frame surfaced for a conn we already tore down — in
        practice a lane punt replaying a parked PUBLISH after its
        publisher disconnected. The message was accepted while the
        connection was live (permit = authorization already ran), so it
        must still be published; only QoS<=1 non-retained plain-name
        frames can ever park on the lane, and the publisher being gone
        means no ack is owed."""
        info = self._closed_conns.get(conn_id)
        if info is None:
            return                     # unknown conn: nothing to honour
        clientid, proto_ver, _username, _peername = info
        try:
            pkt = parse_one(frame, proto_ver)
        except Exception:  # noqa: BLE001 — defensive: drop, don't crash
            return
        if pkt.type != P.PUBLISH or pkt.qos > 1 or pkt.retain \
                or not pkt.topic or pkt.topic.startswith("$"):
            return
        from emqx_tpu.core.message import Message

        props = dict(pkt.properties or {})
        props.pop("Topic-Alias", None)  # connection-scoped
        msg = Message(
            topic=pkt.topic, payload=pkt.payload, qos=pkt.qos,
            from_=clientid,
            flags={"retain": False, "dup": pkt.dup},
            headers={"properties": props, "protocol": "mqtt"},
        )
        if self.pipeline is not None:
            self.pipeline.submit(msg)
        else:
            self.cm.dispatch(self.broker.publish(msg))

    def _forget_fast(self, conn: _NativeConn) -> None:
        cid = conn.channel.clientid
        with self._trace_lock:
            self._traced_conns.discard(conn.conn_id)
        if self._fast_conn_of.get(cid) == conn.conn_id:
            del self._fast_conn_of[cid]
        if conn.fast:
            conn.fast = False
            # no-op when the conn is already closing; clears native
            # permits/inflight if a future caller revokes eligibility
            # on a live connection
            self.host.disable_fast(conn.conn_id)
        self._granted.pop(conn.conn_id, None)
        # groups this client served natively fall back to punt until the
        # session teardown removes the membership (or a reconnect
        # re-qualifies it)
        self._reconcile_sid_groups(cid)

    def _scan_watch(self, conn: _NativeConn) -> None:
        """(Re-)enter a conn into the housekeep scan set — called on
        every Python-plane packet egress, so a session that regrows
        retry/awaiting state is scanned again until it drains."""
        with self._scan_lock:
            self._scan_conns[conn.conn_id] = conn

    def _drop(self, conn: _NativeConn, reason: str) -> None:
        with self._scan_lock:
            self._scan_conns.pop(conn.conn_id, None)
        self.conns.pop(conn.conn_id, None)
        self._forget_fast(conn)
        conn.channel.terminate(reason)
        self.host.close_conn(conn.conn_id)

    def _housekeep(self) -> None:
        # app.tick() can block on bridge reconnects / disk-queue flushes;
        # run it off the poll thread (the asyncio server offloads it with
        # asyncio.to_thread for the same reason) so frame processing and
        # keepalive handling never stall behind it.  _tick_running keeps
        # at most one tick in flight.
        if (self.app is not None and not self._tick_running.is_set()
                and not self._stop.is_set()):
            self._tick_running.set()

            def _tick():
                try:
                    self.app.tick()
                except Exception:  # pragma: no cover - defensive
                    log.exception("app.tick failed")
                finally:
                    self._tick_running.clear()

            try:
                self._tick_pool.submit(_tick)
            except RuntimeError:  # pragma: no cover — stop() raced this
                # housekeep between the _stop check and the submit;
                # the pool is gone, the poll loop exits on its next
                # _stop check. Silence beats "poll step failed" noise.
                self._tick_running.clear()
        self._merge_fast_metrics()
        self._lane_auto()
        if self._durable_store is not None:
            # unlink all-consumed store segments / compact thin tails
            self._durable_store.gc()
            degraded = self._durable_store.stats()["degraded"]
            if degraded > self._store_degraded_seen:
                # mid-run segment-open/mmap failure (disk full?): the
                # store fell back to anonymous segments — qos1 PUBACKs
                # keep flowing but restart survival is GONE for the
                # degraded stretch; say so loudly, once per incident
                delta = degraded - self._store_degraded_seen
                self._store_degraded_seen = degraded
                self.ledger.record("store_degraded", delta,
                                   detail=self._durable_store.dir)
                log.error(
                    "durable store degraded to in-memory segments "
                    "(%d incidents): acked messages in this stretch "
                    "will NOT survive a restart — check disk space at "
                    "%r", degraded, self._durable_store.dir)
        if self.app is not None and self.telemetry:
            # follow a live slow_subs.threshold change (config update)
            # down to the C++ slow-ack report floor
            thr = self.app.slow_subs.threshold_ms
            if thr != self._slow_ack_ms:
                self._slow_ack_ms = thr
                self.host.set_telemetry(True, slow_ack_ms=thr)
        if time.monotonic() - self._last_permit_flush >= PERMIT_TTL_S:
            # the authz-cache TTL analogue: permits re-earn periodically
            # so an authz/banned change can't be outrun forever
            self._last_permit_flush = time.monotonic()
            if self._granted:
                self.flush_permits()
        if self.coap_port is not None:
            self._coap_housekeep()
        self._housekeep_conns(0)

    def _housekeep_conns(self, shard: int) -> None:
        """Session-timer scan for ONE shard's ACTIVE conns. Must run
        on that shard's poll thread: conn_idle_ms walks poll-thread-
        owned C++ state, and channel timeouts must not race the thread
        handling the conn's frames. Shard 0's scan rides the global
        housekeep.

        Round 16: the full-conn keepalive sweep is GONE — keepalive
        deadlines live on the C++ timer wheel (set_keepalive at
        CONNACK), so this loop walks only the scan set: conns whose
        Python session may hold retry/awaiting-rel work. A conn leaves
        the set once its session drains (and re-enters through
        _scan_watch on any Python-plane egress), so housekeep cost
        tracks ACTIVE sessions, not the parked million."""
        sharded = self.shards > 1
        with self._scan_lock:
            scan = list(self._scan_conns.values())
        for conn in scan:
            if sharded and native.shard_of(conn.conn_id) != shard:
                continue
            if conn.conn_id not in self.conns:   # raced a teardown
                with self._scan_lock:
                    self._scan_conns.pop(conn.conn_id, None)
                continue
            ch = conn.channel
            if not conn.native_ka:
                # pre-CONNACK (or legacy-armed) conns: the old path —
                # feed the idle clock for transports whose frames never
                # reach the channel, enforce keepalive in Python
                if conn.fast or conn.sn or conn.coap:
                    idle = self.host.conn_idle_ms(conn.conn_id)
                    if idle >= 0:
                        ch.last_packet_at = max(
                            ch.last_packet_at, now_ms() - idle)
                if ch.keepalive_expired():
                    self._drop(conn, "keepalive_timeout")
                    continue
            conn._send_packets(ch.handle_timeout("retry"))
            ch.handle_timeout("expire_awaiting_rel")
            if conn.native_ka:
                sess = getattr(ch, "session", None)
                # idle-check and pop under ONE lock hold: a concurrent
                # delivery grows the session BEFORE its _scan_watch
                # re-add, so evaluating idleness inside the lock means
                # either we see the growth (no pop) or the re-add
                # serializes after our pop (conn stays scanned) — never
                # a popped conn with live retry state
                with self._scan_lock:
                    if sess is None or (sess.inflight.is_empty()
                                        and not sess.awaiting_rel):
                        # no session timer work left: leave the scan
                        # until the next egress re-enters us
                        self._scan_conns.pop(conn.conn_id, None)

    def _merge_fast_metrics(self) -> None:
        """Fold the C++ counters into the node metrics so $SYS /
        Prometheus see fast-path traffic (the slow path increments these
        inline; the fast path batches them per housekeep)."""
        stats = self.host.stats()
        m = self.broker.metrics
        seen = self._stats_seen
        d_in = stats["fast_in"] - seen["fast_in"]
        d_out = stats["fast_out"] - seen["fast_out"]
        d_q1 = stats["qos1_in"] - seen["qos1_in"]
        d_q2 = stats["qos2_in"] - seen["qos2_in"]
        d_lto = stats["lane_topic_overflow"] - seen["lane_topic_overflow"]
        d_drop = (stats["drops_backpressure"] + stats["drops_inflight"]
                  - seen["drops_backpressure"] - seen["drops_inflight"]
                  + d_lto)
        if d_in:
            m.inc("messages.received", d_in)
            m.inc("messages.publish", d_in)
            m.inc("messages.native.received", d_in)
            # per-qos splits (the slow path counts these per packet)
            if d_q1:
                m.inc("messages.qos1.received", d_q1)
                m.inc("messages.native.qos1.received", d_q1)
            if d_q2:
                m.inc("messages.qos2.received", d_q2)
                m.inc("messages.native.qos2.received", d_q2)
            d_q0 = d_in - d_q1 - d_q2
            if d_q0 > 0:
                m.inc("messages.qos0.received", d_q0)
        if d_lto:
            # distinct from delivery backpressure: INBOUND per-topic
            # lane flood (host.cc kLaneTopicMax) — logged loud so
            # operators can tell the two overload shapes apart
            m.inc("messages.native.lane_topic_overflow", d_lto)
            log.warning(
                "device-lane per-topic overload: dropped %d publishes "
                "beyond the in-flight cap (lane_topic_overflow=%d total)",
                d_lto, stats["lane_topic_overflow"])
        if d_out:
            m.inc("messages.sent", d_out)
            m.inc("messages.delivered", d_out)
        if d_drop:
            m.inc("messages.dropped", d_drop)
        # faultline (round 15): per-site injected-fault counters fold
        # into the fixed faults.* metric slots. Host-plane fires are
        # already ledger-visible below the GIL (kind-12, reason
        # "fault"); STORE-site fires happen under the store mutex on
        # arbitrary threads, so their ledger entries fold here instead.
        for i, site in enumerate(native.FAULT_SITES):
            fired = self.host.fault_fired(site)
            d_f = fired - self._faults_seen[site]
            if d_f:
                self._faults_seen[site] = fired
                m.inc(f"faults.{site}", d_f)
                if site in ("store_msync", "store_seg_open"):
                    self.ledger.record("fault", d_f, aux=i, detail=site)
        # conn-scale plane (round 16): hibernation + accept-shed
        # counters fold into the fixed conns.* slots (accept_shed
        # LEDGER entries arrive separately through the kind-12 fold)
        for slot, name in (("conns_parked", "conns.parked"),
                           ("conns_inflated", "conns.inflated"),
                           ("conns_shed", "conns.shed")):
            d_c = stats[slot] - seen[slot]
            if d_c:
                m.inc(name, d_c)
        d_fwd = stats["trunk_out"] - seen["trunk_out"]
        if d_fwd:
            # the native half of the messages.forward split (ISSUE 4
            # satellite): trunked legs next to the Python forward lane's
            # messages.forward.slow — both fixed slots render at zero
            m.inc("messages.forward", d_fwd)
            m.inc("messages.forward.native", d_fwd)
        self._stats_seen = stats

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Run the poll loop on a background thread."""
        if self.device_lane == "on":
            self._set_lane(True)
        if self.fast_path and self.app is not None:
            self._tap_thread = threading.Thread(
                target=self._tap_worker, name="emqx-rule-tap",
                daemon=True)
            self._tap_thread.start()
        self._thread = threading.Thread(
            target=self._run, name="emqx-native-host", daemon=True)
        self._thread.start()
        # shards 1..N-1 (round 12): one poll thread per shard host,
        # each driving its own epoll loop + its own conns' keepalive;
        # server-global duties stay on shard 0's thread
        for i in range(1, self.shards):
            t = threading.Thread(
                target=self._run_shard, args=(i,),
                name=f"emqx-native-host-s{i}", daemon=True)
            t.start()
            self._shard_threads.append(t)

    def _register_poll_thread(self, host) -> None:
        self._tls.host = host
        self._poll_idents.add(threading.get_ident())

    def _run(self) -> None:
        self._register_poll_thread(self.hosts[0])
        while not self._stop.is_set():
            try:
                self._step(timeout_ms=50)
            except Exception:  # noqa: BLE001 — the poll thread IS the
                # broker: one bad housekeep/grant cycle (e.g. a raising
                # authorize hook) must log, not stop serving every conn
                log.exception("native poll step failed; continuing")

    def _run_shard(self, idx: int) -> None:
        host = self.hosts[idx]
        self._register_poll_thread(host)
        last_hk = time.monotonic()
        while not self._stop.is_set():
            try:
                self._step_host(host, timeout_ms=50)
                now = time.monotonic()
                if now - last_hk >= HOUSEKEEP_INTERVAL:
                    last_hk = now
                    self._housekeep_conns(idx)
            except Exception:  # noqa: BLE001 — same containment as _run
                log.exception("native shard %d poll step failed; "
                              "continuing", idx)

    def stop(self) -> None:
        # Signal EVERY worker before joining any (VERDICT r5 weak #2 /
        # next #9): the old order signalled the poll thread only after
        # a lane join, and a poll step stuck in a cold-compile
        # pipeline.flush could outlive the 5s join — the executor
        # shutdown below then raced the still-running _housekeep into
        # "cannot schedule new futures after shutdown" (and worse, the
        # host destroy raced the poll itself).
        if getattr(self, "_leaked", False):
            return  # a wedged poll thread owns the host forever
        self._stop.set()
        self._lane_stop.set()
        if self._lane_thread is not None:
            self._lane_thread.join(timeout=30)
            self._lane_thread = None
        if self._tap_thread is not None:
            self._tap_thread.join(timeout=5)
            self._tap_thread = None
        poll_dead = True
        if self._thread is not None:
            # a first-flush XLA compile can hold one step for seconds;
            # wait generously — the executor/host teardown below is only
            # safe once the poll thread is provably done stepping
            self._thread.join(timeout=30)
            poll_dead = not self._thread.is_alive()
            self._thread = None
        for t in self._shard_threads:
            # EVERY shard's poll thread must be provably done before
            # any host (or the ring group) can be torn down: a live
            # producer shard writes into the group the destroy frees
            t.join(timeout=30)
            if t.is_alive():
                poll_dead = False
        self._shard_threads = []
        try:
            self.broker.sub_observers.remove(self._on_sub_event)
        except ValueError:
            pass
        if self._retain_mirrored and self.app is not None:
            try:
                self.app.retainer.observers.remove(self._on_retained_event)
            except ValueError:
                pass
            if self.app.native_retain_fn == self._native_retained:
                self.app.native_retain_fn = None
        try:
            self.broker.router.route_observers.remove(self._on_route_event)
        except ValueError:
            pass
        for comp in ("bridges", "trace", "topic_metrics",
                     "rewrite", "exhook"):
            obj = getattr(self.app, comp, None) if self.app else None
            if hasattr(obj, "on_topology_change"):
                try:
                    obj.on_topology_change.remove(
                        self._on_trace_change if comp == "trace"
                        else self.flush_permits)
                except ValueError:
                    pass
        if (self.app is not None
                and self.app.native_stats_fn == self.fast_stats):
            self.app.native_stats_fn = None
        if (self.app is not None
                and self.app.native_spans_fn == self.spans_recent):
            self.app.native_spans_fn = None
        if (self.app is not None
                and self.app.native_shard_stats_fn == self.shard_stats):
            self.app.native_shard_stats_fn = None
        if self.app is not None and hasattr(self.app.rules,
                                            "on_topology_change"):
            try:
                self.app.rules.on_topology_change.remove(
                    self._on_rules_change)
            except ValueError:
                pass
        if self.app is not None and hasattr(self.app,
                                            "on_shared_strategy_change"):
            try:
                self.app.on_shared_strategy_change.remove(
                    self.reeval_shared_groups)
            except ValueError:
                pass
        for conn in list(self.conns.values()):
            conn.channel.terminate("server_shutdown")
        self.conns.clear()
        if (self.app is not None and self.app.persistent is not None
                and self.app.persistent.native_drain
                == self._durable_drain):
            self.app.persistent.native_drain = None
            self.app.persistent.native_discard = None
            self.app.persistent.native_ack = None
        if poll_dead:
            self._tick_pool.shutdown(wait=False)
            self.host.destroy()
            if self._shard_group is not None:
                # hosts first, THEN the group: the group owns the
                # doorbell fds a dying host's producers may still ring
                self._shard_group.destroy()
                self._shard_group = None
            if self._durable_store is not None:
                # the host borrowed the store pointer; with the host
                # destroyed (poll thread provably done) it can close —
                # unless the app's persistence backend owns it (the
                # shared one-recovery-path store outlives this server)
                if getattr(self, "_durable_store_owned", True):
                    self._durable_store.close()
                self._durable_store = None
        else:  # pragma: no cover — pathological wedge
            # STICKY: a wedged poll thread may still be inside
            # emqx_host_poll — nothing may ever free these hosts or the
            # ring group (not a second stop(), not __del__ at gc time)
            self._leaked = True
            self.host.leaked = True
            if self._shard_group is not None:
                self._shard_group.leaked = True
            log.warning("native poll thread still alive after 30s; "
                        "leaking host/executor to avoid a use-after-free")
