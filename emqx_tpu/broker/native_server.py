"""Broker listener on the native (C++ epoll) connection host.

The C++ side (``emqx_tpu/native/src/host.cc``) owns sockets and framing;
this driver consumes complete-frame events, runs the same ``Channel`` FSM
the asyncio server uses, and pushes serialized replies back down. One
Python thread drives the loop — the C++ host does the per-byte work
(accept, read, frame-split, write, backpressure), which is the part the
reference delegates to the BEAM's C core (emqx_connection.erl:132
``{active,N}`` batching).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from emqx_tpu import native
from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.cm import CM
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import FrameError, parse_one, serialize

log = logging.getLogger("emqx_tpu.native_server")

HOUSEKEEP_INTERVAL = 5.0


class _NativeConn:
    __slots__ = ("conn_id", "channel", "server")

    def __init__(self, server: "NativeBrokerServer", conn_id: int, peer: str):
        self.server = server
        self.conn_id = conn_id
        pipeline = server.pipeline
        self.channel = Channel(
            server.broker, server.cm,
            mountpoint=server.mountpoint,
            send=self._send_packets,
            publish_sink=pipeline.submit if pipeline is not None else None,
        )
        self.channel.conninfo.peername = peer

    def _send_packets(self, pkts) -> None:
        data = b"".join(
            serialize(p, self.channel.conninfo.proto_ver) for p in pkts)
        if data:
            self.server.host.send(self.conn_id, data)


class NativeBrokerServer:
    """Same surface as ``BrokerServer`` but socket IO lives in C++."""

    def __init__(
        self,
        broker: Optional[Broker] = None,
        cm: Optional[CM] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_packet_size: int = 1 << 20,
        max_connections: int = 1_000_000,
        mountpoint: str = "",
        app=None,
    ):
        if not native.available():
            raise RuntimeError(
                f"native host unavailable: {native.build_error()}")
        if app is None and broker is None:
            from emqx_tpu.app import BrokerApp

            app = BrokerApp()
        self.app = app
        self.broker = broker or app.broker
        self.cm = cm or (app.cm if app else CM())
        self.mountpoint = mountpoint
        self.host = native.NativeHost(
            host=host, port=port,
            max_size=max_packet_size, max_conns=max_connections)
        self.port = self.host.port
        self.conns: dict[int, _NativeConn] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_housekeep = time.monotonic()
        self._tick_running = threading.Event()
        # device serving path: one poll step's PUBLISHes coalesce into
        # one kernel launch (the epoll batch IS the {active,N} batch)
        self.pipeline = getattr(app, "pipeline", None)
        # one long-lived worker for app.tick() — spawning a thread per
        # housekeep cycle would churn an OS thread every few seconds
        self._tick_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="emqx-native-tick")

    # -- event loop ---------------------------------------------------------

    def _step(self, timeout_ms: int = 100) -> None:
        for kind, conn_id, payload in self.host.poll(timeout_ms):
            if kind == native.EV_OPEN:
                self.conns[conn_id] = _NativeConn(
                    self, conn_id, payload.decode("ascii", "replace"))
            elif kind == native.EV_FRAME:
                conn = self.conns.get(conn_id)
                if conn is not None:
                    self._on_frame(conn, payload)
            elif kind == native.EV_CLOSED:
                conn = self.conns.pop(conn_id, None)
                if conn is not None:
                    conn.channel.terminate(payload.decode("ascii", "replace"))
        if self.pipeline is not None:
            self.pipeline.flush()
        now = time.monotonic()
        if now - self._last_housekeep >= HOUSEKEEP_INTERVAL:
            self._last_housekeep = now
            self._housekeep()

    def _on_frame(self, conn: _NativeConn, frame: bytes) -> None:
        ch = conn.channel
        try:
            pkt = parse_one(frame, ch.conninfo.proto_ver)
            if pkt.type == P.CONNECT:
                ch.conninfo.proto_ver = pkt.proto_ver
            out = ch.handle_in(pkt)
        except (FrameError, IndexError) as e:
            # per-connection fault isolation: a bad frame (or a channel
            # protocol error) drops this client, never the poll thread —
            # same containment the asyncio server gets from its per-conn task
            log.info("frame error from %s: %s", ch.conninfo.peername, e)
            if ch.conninfo.proto_ver == P.MQTT_V5:
                rc = getattr(e, "rc", P.RC_MALFORMED_PACKET)
                conn._send_packets([P.Disconnect(reason_code=rc)])
            self._drop(conn, "frame_error")
            return
        except Exception:
            log.exception("channel error from %s", ch.conninfo.peername)
            self._drop(conn, "channel_error")
            return
        conn._send_packets(out)
        if ch.conn_state == "disconnected":
            self._drop(conn, "normal")

    def _drop(self, conn: _NativeConn, reason: str) -> None:
        self.conns.pop(conn.conn_id, None)
        conn.channel.terminate(reason)
        self.host.close_conn(conn.conn_id)

    def _housekeep(self) -> None:
        # app.tick() can block on bridge reconnects / disk-queue flushes;
        # run it off the poll thread (the asyncio server offloads it with
        # asyncio.to_thread for the same reason) so frame processing and
        # keepalive handling never stall behind it.  _tick_running keeps
        # at most one tick in flight.
        if self.app is not None and not self._tick_running.is_set():
            self._tick_running.set()

            def _tick():
                try:
                    self.app.tick()
                except Exception:  # pragma: no cover - defensive
                    log.exception("app.tick failed")
                finally:
                    self._tick_running.clear()

            self._tick_pool.submit(_tick)
        for conn in list(self.conns.values()):
            ch = conn.channel
            if ch.keepalive_expired():
                self._drop(conn, "keepalive_timeout")
                continue
            conn._send_packets(ch.handle_timeout("retry"))
            ch.handle_timeout("expire_awaiting_rel")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Run the poll loop on a background thread."""
        self._thread = threading.Thread(
            target=self._run, name="emqx-native-host", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._step(timeout_ms=50)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for conn in list(self.conns.values()):
            conn.channel.terminate("server_shutdown")
        self.conns.clear()
        self._tick_pool.shutdown(wait=False)
        self.host.destroy()
