"""Application assembly — the ``emqx_machine``/``emqx_sup`` analogue.

Builds the broker with its standard services wired onto hookpoints, in
the same composition the reference boots: shared-sub dispatch, retainer,
delayed publish — each attached via hooks, no core changes
(SURVEY.md §2.2: "emqx_retainer, emqx_slow_subs, etc register via hooks").
"""

from __future__ import annotations

from typing import Optional

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.cm import CM
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.shared_sub import SharedSub
from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message
from emqx_tpu.services.delayed import Delayed
from emqx_tpu.services.retainer import Retainer


class BrokerApp:
    """Broker + CM + standard services, hook-wired."""

    def __init__(
        self,
        node: str = "node1",
        shared_strategy: str = "round_robin",
        max_retained: int = 0,
        retained_expiry_ms: int = 0,
        router_model=None,
        forward_fn=None,
        access_control=None,
        persistent_store=None,   # session.persistent store; None = disabled
    ):
        from emqx_tpu.observe.alarm import AlarmManager
        from emqx_tpu.observe.metrics import Metrics
        from emqx_tpu.observe.stats import Stats
        from emqx_tpu.observe.sys import SysHeartbeat

        self.hooks = Hooks()
        self._tickers: list = []
        self.exhook = None                 # ExhookMgr once configured
        # set by NativeBrokerServer: () -> dict of C++ host stat slots,
        # so the prometheus scrape carries the fast-path counters
        # (emqx_native_*) next to the node metrics
        self.native_stats_fn = None
        # retained delivery on the native plane (round 11): set by the
        # native server to (sid, topic, real, opts) -> bool; True means
        # the host resolved+delivered the retained set below the GIL
        # and the Python lookup must NOT run (a double delivery
        # otherwise). None / False falls back to the retainer here.
        self.native_retain_fn = None
        # native distributed tracing (round 13): set by the native
        # server to (limit) -> list of assembled span timelines (the
        # queryable last-N ring the mgmt API serves); per-shard stat
        # dicts for the shard-labelled prometheus series
        self.native_spans_fn = None
        self.native_shard_stats_fn = None
        # the durable store's slot dict (round 18): set by the native
        # server (or the app's own NativeDurableStore boot) so the
        # one-recovery-path surface scrapes as emqx_native_store_*
        self.native_store_stats_fn = None
        self.metrics = Metrics()
        # degradation ledger (round 13): structured reason events for
        # every native/Python degradation-ladder decision, folded into
        # the fixed messages.ledger.* slots + a bounded event ring
        # ($SYS heartbeat + mgmt API)
        from emqx_tpu.observe.metrics import DegradationLedger
        self.ledger = DegradationLedger(self.metrics)
        self.stats = Stats()
        self.alarms = AlarmManager(on_change=self._on_alarm)
        # security layer (emqx_access_control): banned/authn/authz hooks.
        # Default-constructed = anonymous allow-all, as an unconfigured
        # reference broker behaves.
        if access_control is None:
            from emqx_tpu.access.control import AccessControl
            access_control = AccessControl()
        self.access = access_control
        self.access.attach(self.hooks)
        # persistent sessions (opt-in, like the reference's
        # persistent_session_store.enable) — must exist before the CM so
        # resume can consult it
        self.persistent = None
        if persistent_store is not None:
            from emqx_tpu.session.persistent import PersistentSessions

            self.persistent = PersistentSessions(
                store=persistent_store,
                is_persistent=self._session_is_persistent,
            )
            # the one-recovery-path store's slots scrape even without a
            # native server attached (the asyncio-only durable broker)
            nst = getattr(persistent_store, "native", None)
            if nst is not None:
                self.native_store_stats_fn = nst.stats
        self.cm = CM(persistence=self.persistent)
        self.shared = SharedSub(node=node, strategy=shared_strategy)
        self.broker = Broker(
            node=node,
            hooks=self.hooks,
            router_model=router_model,
            forward_fn=forward_fn,
            shared_dispatch=self._shared_dispatch,
            metrics=self.metrics,
        )
        self.broker.shared_dispatch_batch = self._shared_dispatch_batch
        self.broker.ledger = self.ledger   # device-failover events
        # device serving path (router.device): coalesces the servers'
        # publishes into batched kernel launches (broker/pipeline.py)
        self.pipeline = None
        if self.broker.model is not None:
            from emqx_tpu.broker.pipeline import PublishPipeline
            self.pipeline = PublishPipeline(self.broker, self.cm)
        # kernel-plane observability fold (round 19): device counters +
        # stage timings from the router model's collect seam land in
        # the shared Metrics/ledger/span surfaces; attached only when
        # the model computes counters (EMQX_TPU_KERNEL_TELEMETRY=0
        # leaves the model's telemetry hook unset — zero fold cost)
        self.device_metrics = None
        if (self.broker.model is not None
                and getattr(self.broker.model, "kernel_telemetry", False)):
            from emqx_tpu.observe.device_metrics import DeviceMetricsFold
            from emqx_tpu.observe.trace import SpanCollector
            self.device_metrics = DeviceMetricsFold(
                self.metrics, ledger=self.ledger, spans=SpanCollector(),
                model=self.broker.model, node=node)
            self.broker.model.telemetry = self.device_metrics
            # the kernel fold's sampled traces serve the tracing-spans
            # mgmt surface when no native server attaches (a booted
            # native server overrides this with its own richer ring)
            if self.native_spans_fn is None:
                self.native_spans_fn = self.device_metrics.spans_recent
        self.sys = SysHeartbeat(
            node=node, publish_fn=self._publish_dispatch,
            metrics=self.metrics, stats=self.stats, ledger=self.ledger,
            kernel=self.device_metrics,
        )
        self.retainer = Retainer(
            max_retained=max_retained, default_expiry_ms=retained_expiry_ms
        )
        self.delayed = Delayed(publish_fn=self._publish_dispatch)
        from emqx_tpu.rules.engine import RuleEngine
        self.rules = RuleEngine(node=node,
                                publish_fn=self._publish_dispatch)
        self.rules.attach(self.hooks)
        if self.broker.model is not None:
            # co-batch rule FROM filters with router match on the device
            # (config 5): publish_batch feeds fan-out AND rule matching
            self.rules.attach_model(self.broker.model)
            self.broker.rules_matched_fn = self.rules.on_matched
            self.broker.rules_gate_fn = self.rules.publish_gate
        from emqx_tpu.bridge.bridge import BridgeManager
        self.bridges = BridgeManager(
            rules=self.rules, publish_fn=self._publish_dispatch,
            hooks=self.hooks)
        from emqx_tpu.gateway.ctx import GatewayManager
        self.gateway = GatewayManager(self)
        from emqx_tpu.broker.olp import Congestion, GcPolicy, Olp
        from emqx_tpu.observe.trace import TraceManager
        from emqx_tpu.services.slow_subs import SlowSubs
        self.trace = TraceManager()
        self.trace.attach(self.hooks)
        self.slow_subs = SlowSubs()
        self.slow_subs.attach(self.hooks)
        self.olp = Olp()
        self.gc_policy = GcPolicy()
        self.congestion = Congestion(alarms=self.alarms)
        from emqx_tpu.access.psk import PskStore
        from emqx_tpu.observe.statsd import StatsdPusher
        from emqx_tpu.services.auto_subscribe import AutoSubscribe
        from emqx_tpu.services.rewrite import TopicRewrite
        from emqx_tpu.services.telemetry import Telemetry
        from emqx_tpu.services.topic_metrics import TopicMetrics
        self.rewrite = TopicRewrite()
        self.rewrite.attach(self.hooks)
        self.topic_metrics = TopicMetrics()
        self.topic_metrics.attach(self.hooks)
        self.auto_subscribe = AutoSubscribe(self)
        self.auto_subscribe.attach(self.hooks)
        self.telemetry = Telemetry(self)
        self.statsd = StatsdPusher(self)
        self.psk = PskStore(enable=False)
        from emqx_tpu.observe.monitor import DashboardMonitor
        from emqx_tpu.observe.sysmon import SysMon
        from emqx_tpu.services.plugins import PluginManager
        self.monitor = DashboardMonitor(self)
        self.plugins = PluginManager(self, install_dir="plugins")
        self.sysmon = SysMon(self.alarms, olp=self.olp)
        from emqx_tpu.broker.listeners import Listeners
        self.listeners = Listeners(self)

        # hook wiring — delayed intercepts first (STOP), retainer observes
        self.delayed.attach(self.hooks, priority=100)
        self.hooks.add("message.publish", self._retain_on_publish, priority=-100)
        self.hooks.add("session.subscribed", self._retained_on_subscribe)
        self.hooks.add("session.subscribed", self._shared_on_subscribe)
        self.hooks.add("session.unsubscribed", self._shared_on_unsubscribe)
        self.hooks.add("session.terminated", self._shared_on_terminated)
        self.hooks.add("session.discarded", self._shared_on_terminated)
        if self.persistent is not None:
            self.persistent.attach(self.hooks)
            self.hooks.add("client.disconnected", self._persistent_on_disc)
            self.hooks.add(
                "client.connected",
                lambda ci: self.persistent.note_connected(ci.clientid))
        self._wire_observability()

    # -- observability -------------------------------------------------------

    def _wire_observability(self) -> None:
        m, hooks = self.metrics, self.hooks
        hooks.add("client.connected",
                  lambda ci: m.inc("client.connected"), priority=-1000)
        hooks.add("client.disconnected",
                  lambda ci, reason: m.inc("client.disconnected"),
                  priority=-1000)
        hooks.add("client.connack",
                  lambda ci, rc: m.inc("client.connack"), priority=-1000)
        hooks.add("message.delivered",
                  lambda cid, topic: m.inc("messages.delivered"),
                  priority=-1000)
        hooks.add("message.acked",
                  lambda cid, pid: m.inc("messages.acked"), priority=-1000)
        for ev in ("created", "resumed", "takenover", "discarded",
                   "terminated"):
            hooks.add(f"session.{ev}",
                      (lambda ev: lambda *a: m.inc(f"session.{ev}"))(ev),
                      priority=-1000)
        s, cm, broker = self.stats, self.cm, self.broker
        s.set_updater("connections.count",
                      lambda: sum(1 for _ in cm.all_channels()),
                      "connections.max")
        s.set_updater(
            "live_connections.count",
            lambda: sum(1 for _, ch in cm.all_channels()
                        if getattr(ch, "conn_state", "") == "connected"),
            "live_connections.max")
        s.set_updater("sessions.count",
                      lambda: sum(1 for _ in cm.all_channels()),
                      "sessions.max")
        s.set_updater("topics.count",
                      lambda: len(broker.router.topics()), "topics.max")
        s.set_updater("subscribers.count",
                      lambda: sum(len(v) for v in broker.subscriber.values()),
                      "subscribers.max")
        s.set_updater("subscriptions.count",
                      lambda: len(broker.suboption), "subscriptions.max")
        s.set_updater("suboptions.count", lambda: len(broker.suboption),
                      "suboptions.max")
        s.set_updater("subscriptions.shared.count",
                      lambda: sum(1 for (_, t) in broker.suboption
                                  if T.parse_share(t)[0]),
                      "subscriptions.shared.max")
        s.set_updater("retained.count", lambda: len(self.retainer),
                      "retained.max")
        s.set_updater("delayed.count", lambda: len(self.delayed),
                      "delayed.max")

    def _on_alarm(self, event: str, alarm) -> None:
        """$SYS alarm notification (emqx_alarm publishes to
        $SYS/brokers/<node>/alarms/activate|deactivate)."""
        import json as _json

        self._publish_dispatch(Message(
            topic=f"$SYS/brokers/{self.broker.node}/alarms/{event}",
            payload=_json.dumps(
                {"name": alarm.name, "message": alarm.message}).encode(),
            qos=0, from_="$SYS", flags={"sys": True},
        ))

    def prometheus(self, openmetrics: bool = False) -> str:
        """Text exposition. ``openmetrics=True`` adds trace-id
        exemplars on histogram buckets — OpenMetrics-flavoured output
        a classic 0.0.4 parser would reject, so it is opt-in
        (the scrape endpoint's ``?format=openmetrics``)."""
        from emqx_tpu.observe import prometheus

        self.stats.tick()
        native = None
        if self.native_stats_fn is not None:
            try:
                native = self.native_stats_fn()
            except Exception:  # noqa: BLE001 — a dying server must not
                native = None  # break the scrape endpoint
        shards = None
        if self.native_shard_stats_fn is not None:
            try:
                shards = self.native_shard_stats_fn()
            except Exception:  # noqa: BLE001 — same containment
                shards = None
        store = None
        if self.native_store_stats_fn is not None:
            try:
                store = self.native_store_stats_fn()
            except Exception:  # noqa: BLE001 — same containment
                store = None
        kern = None
        if self.device_metrics is not None:
            try:
                kern = self.device_metrics.gauges()
            except Exception:  # noqa: BLE001 — same containment
                kern = None
        return prometheus.render(self.metrics, self.stats,
                                 node=self.broker.node, native=native,
                                 native_shards=shards,
                                 native_store=store, kernel=kern,
                                 openmetrics=openmetrics)

    def kernel_summary(self) -> dict:
        """Device-router stage percentiles + counter totals + trie
        health — the bench/server convenience surface; {} when no
        device model (or kernel telemetry disabled)."""
        if self.device_metrics is None:
            return {}
        out = self.device_metrics.kernel_summary()
        out["gauges"] = self.device_metrics.gauges()
        return out

    @classmethod
    def from_config(cls, conf, node: str = None, **overrides) -> "BrokerApp":
        """Build the app from a checked ``Config`` tree — the
        emqx_machine boot path (config drives every service knob).
        Authn provider specs (``authentication`` array) and authz source
        specs (``authorization.sources``) instantiate by ``mechanism`` /
        ``type`` exactly as the reference's factory does."""
        from emqx_tpu.access.authn import (
            AuthnChain, BuiltinDbProvider, JwtProvider,
        )
        from emqx_tpu.access.authz import Authz, BuiltinSource, FileSource
        from emqx_tpu.access.control import AccessControl

        def _hash_spec(spec):
            from emqx_tpu.access.hashing import HashSpec
            alg = spec.get("password_hash_algorithm") or {}
            if isinstance(alg, str):
                alg = {"name": alg}
            kw = {}
            for field, conv in (("salt_position", str), ("mac_fun", str),
                                ("iterations", int), ("dk_length", int),
                                ("salt_rounds", int)):
                if alg.get(field) is not None:
                    kw[field] = conv(alg[field])
            return HashSpec(name=alg.get("name", "plain"), **kw)

        def _db_client(backend, spec):
            if backend == "redis":
                from emqx_tpu.connector.redis import RedisClient
                host, _, port = str(
                    spec.get("server", "127.0.0.1:6379")).partition(":")
                return RedisClient(host, int(port or 6379),
                                   password=spec.get("password") or None,
                                   db=int(spec.get("database", 0) or 0))
            host, _, port = str(spec.get("server", "")).partition(":")
            kw = dict(host=host or "127.0.0.1",
                      database=spec.get("database", "mqtt"))
            if backend == "mysql":
                from emqx_tpu.connector.mysql import MySqlClient
                return MySqlClient(port=int(port or 3306),
                                   user=spec.get("username", "root"),
                                   password=spec.get("password", ""), **kw)
            if backend == "postgresql":
                from emqx_tpu.connector.pgsql import PgClient
                return PgClient(port=int(port or 5432),
                                user=spec.get("username", "postgres"),
                                password=spec.get("password", ""), **kw)
            if backend == "ldap":
                from emqx_tpu.connector.ldap import LdapClient
                return LdapClient(host=host or "127.0.0.1",
                                  port=int(port or 389),
                                  bind_dn=spec.get("bind_dn", ""),
                                  bind_password=spec.get(
                                      "bind_password", ""))
            from emqx_tpu.connector.mongodb import MongoClient
            return MongoClient(port=int(port or 27017), **kw)

        providers = []
        for spec in conf.get("authentication", []) or []:
            mech = spec.get("mechanism", "password_based")
            backend = spec.get("backend", "built_in_database")
            if mech == "jwt":
                jwks_fn = None
                if spec.get("endpoint"):        # JWKS URL (emqx_authn_jwt)
                    import json as _json
                    import urllib.request as _rq
                    url = str(spec["endpoint"])

                    def jwks_fn(u=url):
                        with _rq.urlopen(u, timeout=5) as r:
                            return _json.loads(r.read())
                # asymmetric key sources default to RS256 — falling back
                # to HS256-with-empty-secret would let anyone mint valid
                # tokens (JwtProvider also hard-refuses that combination)
                default_alg = ("RS256" if spec.get("endpoint")
                               or spec.get("public_key") else "HS256")
                providers.append(JwtProvider(
                    secret=str(spec.get("secret", "")).encode(),
                    algorithm=spec.get("algorithm", default_alg),
                    public_key_pem=(
                        str(spec["public_key"]).encode()
                        if spec.get("public_key") else None),
                    jwks_fn=jwks_fn,
                    verify_claims=spec.get("verify_claims")))
            elif mech == "password_based" and backend == "built_in_database":
                p = BuiltinDbProvider(
                    user_id_type=spec.get("user_id_type", "username"))
                for u in spec.get("bootstrap_users", []) or []:
                    p.add_user(u["user_id"], u["password"],
                               bool(u.get("is_superuser")))
                providers.append(p)
            elif mech == "password_based" and backend == "redis":
                from emqx_tpu.access.redis_backends import RedisAuthnProvider
                cmd = spec.get("cmd")
                providers.append(RedisAuthnProvider(
                    _db_client("redis", spec),
                    cmd=cmd.split() if isinstance(cmd, str) else cmd,
                    hash_spec=_hash_spec(spec)))
            elif mech == "password_based" and backend in (
                    "mysql", "postgresql"):
                from emqx_tpu.access.db_backends import SqlAuthnProvider
                providers.append(SqlAuthnProvider(
                    _db_client(backend, spec), query=spec.get("query"),
                    hash_spec=_hash_spec(spec), backend=backend))
            elif mech == "password_based" and backend == "mongodb":
                from emqx_tpu.access.db_backends import MongoAuthnProvider
                providers.append(MongoAuthnProvider(
                    _db_client("mongodb", spec),
                    collection=spec.get("collection", "mqtt_user"),
                    filter_=spec.get("filter"),
                    hash_spec=_hash_spec(spec)))
            elif mech == "password_based" and backend == "ldap":
                from emqx_tpu.access.ldap_backends import LdapAuthnProvider
                providers.append(LdapAuthnProvider(
                    _db_client("ldap", spec),
                    base_dn=spec.get("base_dn", "dc=emqx,dc=io"),
                    filter_=spec.get("filter")))
            # unknown specs are skipped (optional backends not built)
        sources = []
        for spec in conf.get("authorization.sources", []) or []:
            stype = spec.get("type", "file")
            if stype == "file" and spec.get("rules"):
                sources.append(FileSource.parse(spec["rules"]))
            elif stype == "built_in_database":
                sources.append(BuiltinSource())
            elif stype == "redis":
                from emqx_tpu.access.redis_backends import RedisAclSource
                cmd = spec.get("cmd")
                sources.append(RedisAclSource(
                    _db_client("redis", spec),
                    cmd=cmd.split() if isinstance(cmd, str) else cmd))
            elif stype in ("mysql", "postgresql"):
                from emqx_tpu.access.db_backends import SqlAclSource
                sources.append(SqlAclSource(
                    _db_client(stype, spec), query=spec.get("query"),
                    backend=stype))
            elif stype == "mongodb":
                from emqx_tpu.access.db_backends import MongoAclSource
                sources.append(MongoAclSource(
                    _db_client("mongodb", spec),
                    collection=spec.get("collection", "mqtt_acl"),
                    filter_=spec.get("filter")))
            elif stype == "ldap":
                from emqx_tpu.access.ldap_backends import LdapAclSource
                sources.append(LdapAclSource(
                    _db_client("ldap", spec),
                    base_dn=spec.get("base_dn", "dc=emqx,dc=io"),
                    filter_=spec.get("filter")))
        az_conf = conf.get("authorization")
        fl = conf.get("flapping_detect")
        ac = AccessControl(
            authn=AuthnChain(providers),
            authz=Authz(sources, no_match=az_conf["no_match"]),
            flapping_enable=fl["enable"],
            cache_enable=az_conf["cache"]["enable"],
            cache_max=az_conf["cache"]["max_size"],
            cache_ttl_ms=int(az_conf["cache"]["ttl"] * 1000),
            **({"max_count": fl["max_count"],
                "window_s": float(fl["window_time"]),
                "ban_duration_s": float(fl["ban_time"])}
               if fl["enable"] else {}),
        )
        # router.device: put the TPU kernel on the serving path — build
        # the RouterModel the broker registers subscriptions into and the
        # pipeline batches publishes through (VERDICT r1 item 1; the
        # reference's product IS its hot path, emqx_broker.erl:218-232)
        # durable-session plane (round 10, unified round 18):
        # durable.enable boots the PersistentSessions service on the
        # ONE native durable store (sessions, subscriptions, messages,
        # markers and the trunk replay ring share its segments); the
        # native server attaches to the SAME store instance, so a
        # persistence-enabled broker has one recovery path walked once
        # at boot. A pre-round-18 JSON sessions.log is boot-migrated
        # once. Falls back to MemStore (no restart survival) with a
        # loud warning when the native toolchain is unavailable.
        if conf.get("durable.enable") and "persistent_store" not in overrides:
            import os as _os2

            from emqx_tpu import native as _native
            base = (conf.get("durable.store_dir")
                    or _os2.path.join(conf.get("node.data_dir", "data"),
                                      "durable"))
            if _native.available():
                from emqx_tpu.session.persistent import NativeDurableStore
                overrides["persistent_store"] = NativeDurableStore(
                    base,
                    segment_bytes=int(conf.get("durable.segment_bytes")),
                    fsync=conf.get("durable.fsync") or "batch")
            else:
                # still install persistence (in-memory): disconnect
                # survival, offline queuing and resume keep working —
                # only RESTART survival is gone without the native store
                import logging as _logging

                from emqx_tpu.session.persistent import MemStore
                overrides["persistent_store"] = MemStore()
                _logging.getLogger("emqx_tpu.app").warning(
                    "durable.enable set but the native store is "
                    "unavailable (%s): sessions persist in MEMORY only "
                    "— no restart survival", _native.build_error())
        if conf.get("router.device.enable") and "router_model" not in overrides:
            from emqx_tpu.models.router_model import RouterModel
            from emqx_tpu.router.index import TrieIndex
            model = RouterModel(
                TrieIndex(max_levels=int(conf.get("router.device.max_levels"))),
                n_sub_slots=int(conf.get("router.device.n_sub_slots")),
                K=int(conf.get("router.device.frontier_k")),
                M=int(conf.get("router.device.match_cap")),
                ret_cap=int(conf.get("router.device.return_cap")),
            )
            # Boot-time device touch ON THIS THREAD: JAX backend init from
            # a worker thread (where the pipeline's first flush would
            # otherwise trigger it) can deadlock against callers blocked
            # on the model lock; the empty-index upload is also the right
            # place to pay the init cost — at boot, not first publish.
            model.refresh()
            overrides["router_model"] = model
        app = cls(
            node=node or conf.get("node.name", "node1").split("@")[0],
            shared_strategy=conf.get("shared_subscription_strategy"),
            max_retained=conf.get("retainer.max_retained_messages"),
            retained_expiry_ms=int(
                conf.get("retainer.msg_expiry_interval") * 1000),
            access_control=ac,
            **overrides,
        )
        if app.pipeline is not None:
            app.pipeline.max_batch = int(conf.get("router.device.batch_max"))
            app.pipeline.min_device_batch = int(
                conf.get("router.device.min_batch"))
            app.pipeline.depth = int(
                conf.get("router.device.pipeline_depth"))
            app.pipeline.spill_ms = float(
                conf.get("router.device.spill_ms"))
        app.config = conf
        app.broker.exclusive_enabled = bool(
            conf.get("mqtt.exclusive_subscription"))
        app.broker.max_qos_allowed = int(conf.get("mqtt.max_qos_allowed"))
        for spec in conf.get("rewrite") or []:
            app.rewrite.add_rule(
                action=spec.get("action", "all"),
                source_topic=spec["source_topic"],
                re=spec["re"], dest_topic=spec["dest_topic"])
        for spec in conf.get("auto_subscribe.topics") or []:
            app.auto_subscribe.add(
                topic=spec["topic"], qos=int(spec.get("qos", 0)),
                nl=int(spec.get("nl", 0)), rh=int(spec.get("rh", 0)),
                rap=int(spec.get("rap", 0)))
        app.telemetry.enable = bool(conf.get("telemetry.enable"))
        app.statsd.enable = bool(conf.get("statsd.enable"))
        host, _, port = str(conf.get("statsd.server")).partition(":")
        app.statsd.addr = (host, int(port or 8125))
        app.statsd.flush_interval_s = float(
            conf.get("statsd.flush_time_interval"))
        app.psk.enable = bool(conf.get("psk_authentication.enable"))
        if app.psk.enable and conf.get("psk_authentication.init_file"):
            app.psk.separator = conf.get("psk_authentication.separator")
            try:
                app.psk.import_file(
                    conf.get("psk_authentication.init_file"))
            except OSError:
                pass
        app.sysmon.cpu_high = float(
            conf.get("sysmon.os.cpu_high_watermark"))
        app.sysmon.cpu_low = float(conf.get("sysmon.os.cpu_low_watermark"))
        app.sysmon.mem_high = float(
            conf.get("sysmon.os.mem_high_watermark"))
        gc_conf = conf.get("force_gc")
        app.gc_policy.enable = bool(gc_conf["enable"])
        app.gc_policy.count_budget = int(gc_conf["count"])
        app.gc_policy.bytes_budget = int(gc_conf["bytes"])
        import os as _os
        app.plugins.install_dir = _os.path.join(
            conf.get("node.data_dir", "data"), "plugins")
        app.plugins.scan()
        app.plugins.ensure_started()      # enabled plugins, in order
        if app.persistent is not None:
            # operator retention bound for stored sessions (0 = each
            # session's own expiry interval governs)
            app.persistent.session_expiry_cap_ms = int(
                float(conf.get("durable.session_expiry")) * 1000)
        ss = app.slow_subs
        ss.enable = bool(conf.get("slow_subs.enable"))
        ss.threshold_ms = int(float(conf.get("slow_subs.threshold")) * 1000)
        ss.top_k = int(conf.get("slow_subs.top_k_num"))
        ss.expire_interval_s = float(conf.get("slow_subs.expire_interval"))
        app.sys.heartbeat_s = float(
            conf.get("sys_topics.sys_heartbeat_interval"))
        app.sys.tick_s = float(conf.get("sys_topics.sys_msg_interval"))
        # exhook providers (emqx_exhook_schema: servers with url +
        # failed_action + pool_size; url schemes: grpc:// and http:// =
        # the real gRPC HookProvider, grpcs://www and https:// = TLS gRPC,
        # framed:// and tcp:// = the documented JSON framing). A bad
        # scheme or missing grpcio is a CONFIG error (fail boot loudly);
        # a provider merely unreachable stays registered and the
        # housekeeping tick retries (reference auto_reconnect).
        _SCHEMES = {"grpc": "grpc", "http": "grpc",
                    "grpcs": "grpcs", "https": "grpcs",
                    "framed": "framed", "tcp": "framed"}
        for spec in conf.get("exhook.servers") or []:
            from urllib.parse import urlparse as _urlparse

            from emqx_tpu.exhook.server import ExhookMgr, ExhookServer
            if app.exhook is None:
                app.exhook = ExhookMgr(metrics=app.metrics)
                app.exhook.attach(app.hooks)
                app.add_ticker(app.exhook.tick)
            u = _urlparse(str(spec.get("url", "")))
            if u.scheme not in _SCHEMES:
                raise ValueError(
                    f"exhook server {spec.get('name')!r}: unknown url "
                    f"scheme {u.scheme!r} (grpc|grpcs|framed)")
            server = ExhookServer(
                name=str(spec.get("name", u.hostname or "default")),
                host=u.hostname or "127.0.0.1", port=int(u.port or 9000),
                transport=_SCHEMES[u.scheme],
                pool_size=int(spec.get("pool_size", 4)),
                timeout_s=float(spec.get("request_timeout", 5.0)),
                failed_action=str(spec.get("failed_action", "deny")))
            # auto_reconnect: false disables retry (EMQX semantics);
            # true = default interval; a number/duration = that interval
            ar = spec.get("auto_reconnect", 5.0)
            if ar is False:
                retry = None
            elif ar is True:
                retry = 5.0
            else:
                retry = float(ar)
            app.exhook.enable_async(server, retry_interval_s=retry)
        # structured console logging (emqx_logger_jsonfmt/textfmt +
        # ?SLOG surface; log.console in emqx_conf_schema)
        from emqx_tpu.observe.logfmt import setup_logging
        setup_logging(level=conf.get("log.level"),
                      formatter=conf.get("log.formatter"),
                      to=conf.get("log.to"),
                      file_path=conf.get("log.file"))
        # live-update seams: strategy + retainer limits apply immediately
        conf.add_listener(app._on_config_change)
        return app

    def _on_config_change(self, path: tuple, value) -> None:
        if path[:1] == ("shared_subscription_strategy",):
            self.shared.strategy = value
            # the native host serves round_robin groups in C++; any
            # other strategy must move them back onto the Python path
            for cb in getattr(self, "on_shared_strategy_change", ()):
                cb()
        elif path[:1] == ("retainer",):
            self.retainer.max_retained = self.config.get(
                "retainer.max_retained_messages")
            self.retainer.default_expiry_ms = int(
                self.config.get("retainer.msg_expiry_interval") * 1000)

    # -- delayed -----------------------------------------------------------

    def _publish_dispatch(self, msg: Message) -> None:
        self.cm.dispatch(self.broker.publish(msg))

    # -- retainer ----------------------------------------------------------

    def _retain_on_publish(self, msg: Message):
        self.retainer.on_publish(msg)
        if msg.retain and not msg.payload:
            # an empty retained publish clears the slot and is NOT routed
            return msg.set_header("allow_publish", False)
        return None

    def _retained_on_subscribe(self, sid: str, topic: str, opts,
                               is_new: bool = True) -> None:
        rh = getattr(opts, "rh", 0)
        if rh == 2 or (rh == 1 and not is_new):
            # rh=1: send retained only when the subscription did not
            # previously exist (MQTT5 3.8.3.1)
            return
        group, real = T.parse_share(topic)
        if group:
            return                      # shared subs get no retained msgs
        fn = self.native_retain_fn
        if fn is not None and fn(sid, topic, real, opts):
            return                      # served below the GIL
        msgs = self.retainer.match(real)
        if msgs:
            self.cm.dispatch({sid: [(topic, m) for m in msgs]})

    # -- persistent sessions -------------------------------------------------

    def _session_is_persistent(self, sid: str) -> bool:
        ch = self.cm.lookup_channel(sid)
        return (ch is not None
                and getattr(ch.conninfo, "expiry_interval_ms", 0) > 0)

    def _persistent_on_disc(self, ci, reason) -> None:
        if ci.expiry_interval_ms > 0 and ci.clientid:
            self.persistent.note_disconnected(
                ci.clientid, ci.expiry_interval_ms)

    # -- shared subs --------------------------------------------------------

    def _shared_on_subscribe(self, sid: str, topic: str, opts,
                             is_new: bool = True) -> None:
        group, real = T.parse_share(topic)
        if group:
            self.shared.join(group, real, sid)

    def _shared_on_unsubscribe(self, sid: str, topic: str) -> None:
        group, real = T.parse_share(topic)
        if group:
            self.shared.leave(group, real, sid)

    def _shared_on_terminated(self, sid: str, *args) -> None:
        self.shared.member_down(sid)

    def _shared_deliver_fn(self, sid: str, node: str) -> bool:
        ch = self.cm.lookup_channel(sid)
        return ch is not None and ch.conn_state == "connected"

    def _shared_dispatch(self, group: str, topic: str, msg: Message):
        return [
            (sid, sub_topic)
            for sid, _node, sub_topic in self.shared.dispatch(
                group, topic, msg, deliver_fn=self._shared_deliver_fn)
        ]

    def _shared_dispatch_batch(self, legs):
        """broker.shared_dispatch_batch seam: all of a publish batch's
        shared legs resolve under ONE SharedSub lock hold
        (broker/shared_sub.py dispatch_batch)."""
        picks = self.shared.dispatch_batch(
            legs, deliver_fn=self._shared_deliver_fn)
        return [[(p[0], p[2])] if p is not None else [] for p in picks]

    def session_defaults(self) -> dict:
        """Zone session knobs for new channels (emqx_schema mqtt.*):
        servers pass these as ``session_opts`` so a configured
        ``mqtt.max_inflight`` / ``max_awaiting_rel`` / queue policy
        actually reaches the Session (previously only the per-client
        Receive-Maximum clamp applied)."""
        conf = getattr(self, "config", None)
        if conf is None:
            return {}
        from emqx_tpu.session.mqueue import MQueueOpts

        return {
            "max_inflight": int(conf.get("mqtt.max_inflight")),
            "max_awaiting_rel": int(conf.get("mqtt.max_awaiting_rel")),
            "retry_interval_ms": int(
                float(conf.get("mqtt.retry_interval")) * 1000),
            "await_rel_timeout_ms": int(
                float(conf.get("mqtt.await_rel_timeout")) * 1000),
            "max_subscriptions": int(conf.get("mqtt.max_subscriptions")),
            "upgrade_qos": bool(conf.get("mqtt.upgrade_qos")),
            "mqueue_opts": MQueueOpts(
                max_len=int(conf.get("mqtt.max_mqueue_len")),
                store_qos0=bool(conf.get("mqtt.mqueue_store_qos0"))),
        }

    # -- housekeeping (server timer) ----------------------------------------

    def add_ticker(self, fn) -> None:
        """Register extra housekeeping work (cluster heartbeat etc.)."""
        self._tickers.append(fn)

    def tick(self) -> None:
        self.delayed.tick()
        self.stats.tick()
        self.sys.tick()
        self.trace.tick()
        self.slow_subs.gc()
        self.telemetry.tick()
        self.statsd.tick()
        self.monitor.tick()
        self.sysmon.tick()
        self.access.banned.expire()
        for fn in self._tickers:
            fn()
        if self.persistent is not None:
            self.persistent.gc()
        self.bridges.tick()
        if self.access.flapping is not None:
            self.access.flapping.gc()
        for p in self.access.authn.providers:
            if hasattr(p, "gc"):
                p.gc()
        # delayed wills + session-expiry deadlines of
        # disconnected-but-registered channels
        for _cid, ch in self.cm.all_channels():
            if getattr(ch, "pending_will_at", None) is not None:
                ch.will_tick()
            if getattr(ch, "session_expire_at", None) is not None:
                ch.expire_tick()
