"""Application assembly — the ``emqx_machine``/``emqx_sup`` analogue.

Builds the broker with its standard services wired onto hookpoints, in
the same composition the reference boots: shared-sub dispatch, retainer,
delayed publish — each attached via hooks, no core changes
(SURVEY.md §2.2: "emqx_retainer, emqx_slow_subs, etc register via hooks").
"""

from __future__ import annotations

from typing import Optional

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.cm import CM
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.shared_sub import SharedSub
from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message
from emqx_tpu.services.delayed import Delayed
from emqx_tpu.services.retainer import Retainer


class BrokerApp:
    """Broker + CM + standard services, hook-wired."""

    def __init__(
        self,
        node: str = "node1",
        shared_strategy: str = "round_robin",
        max_retained: int = 0,
        retained_expiry_ms: int = 0,
        router_model=None,
        forward_fn=None,
        access_control=None,
    ):
        from emqx_tpu.observe.alarm import AlarmManager
        from emqx_tpu.observe.metrics import Metrics
        from emqx_tpu.observe.stats import Stats
        from emqx_tpu.observe.sys import SysHeartbeat

        self.hooks = Hooks()
        self.metrics = Metrics()
        self.stats = Stats()
        self.alarms = AlarmManager(on_change=self._on_alarm)
        # security layer (emqx_access_control): banned/authn/authz hooks.
        # Default-constructed = anonymous allow-all, as an unconfigured
        # reference broker behaves.
        if access_control is None:
            from emqx_tpu.access.control import AccessControl
            access_control = AccessControl()
        self.access = access_control
        self.access.attach(self.hooks)
        self.cm = CM()
        self.shared = SharedSub(node=node, strategy=shared_strategy)
        self.broker = Broker(
            node=node,
            hooks=self.hooks,
            router_model=router_model,
            forward_fn=forward_fn,
            shared_dispatch=self._shared_dispatch,
            metrics=self.metrics,
        )
        self.sys = SysHeartbeat(
            node=node, publish_fn=self._publish_dispatch,
            metrics=self.metrics, stats=self.stats,
        )
        self.retainer = Retainer(
            max_retained=max_retained, default_expiry_ms=retained_expiry_ms
        )
        self.delayed = Delayed(publish_fn=self._publish_dispatch)

        # hook wiring — delayed intercepts first (STOP), retainer observes
        self.delayed.attach(self.hooks, priority=100)
        self.hooks.add("message.publish", self._retain_on_publish, priority=-100)
        self.hooks.add("session.subscribed", self._retained_on_subscribe)
        self.hooks.add("session.subscribed", self._shared_on_subscribe)
        self.hooks.add("session.unsubscribed", self._shared_on_unsubscribe)
        self.hooks.add("session.terminated", self._shared_on_terminated)
        self.hooks.add("session.discarded", self._shared_on_terminated)
        self._wire_observability()

    # -- observability -------------------------------------------------------

    def _wire_observability(self) -> None:
        m, hooks = self.metrics, self.hooks
        hooks.add("client.connected",
                  lambda ci: m.inc("client.connected"), priority=-1000)
        hooks.add("client.disconnected",
                  lambda ci, reason: m.inc("client.disconnected"),
                  priority=-1000)
        hooks.add("client.connack",
                  lambda ci, rc: m.inc("client.connack"), priority=-1000)
        hooks.add("message.delivered",
                  lambda cid, topic: m.inc("messages.delivered"),
                  priority=-1000)
        hooks.add("message.acked",
                  lambda cid, pid: m.inc("messages.acked"), priority=-1000)
        for ev in ("created", "resumed", "takenover", "discarded",
                   "terminated"):
            hooks.add(f"session.{ev}",
                      (lambda ev: lambda *a: m.inc(f"session.{ev}"))(ev),
                      priority=-1000)
        s, cm, broker = self.stats, self.cm, self.broker
        s.set_updater("connections.count",
                      lambda: sum(1 for _ in cm.all_channels()),
                      "connections.max")
        s.set_updater(
            "live_connections.count",
            lambda: sum(1 for _, ch in cm.all_channels()
                        if getattr(ch, "conn_state", "") == "connected"),
            "live_connections.max")
        s.set_updater("sessions.count",
                      lambda: sum(1 for _ in cm.all_channels()),
                      "sessions.max")
        s.set_updater("topics.count",
                      lambda: len(broker.router.topics()), "topics.max")
        s.set_updater("subscribers.count",
                      lambda: sum(len(v) for v in broker.subscriber.values()),
                      "subscribers.max")
        s.set_updater("subscriptions.count",
                      lambda: len(broker.suboption), "subscriptions.max")
        s.set_updater("suboptions.count", lambda: len(broker.suboption),
                      "suboptions.max")
        s.set_updater("subscriptions.shared.count",
                      lambda: sum(1 for (_, t) in broker.suboption
                                  if T.parse_share(t)[0]),
                      "subscriptions.shared.max")
        s.set_updater("retained.count", lambda: len(self.retainer),
                      "retained.max")
        s.set_updater("delayed.count", lambda: len(self.delayed),
                      "delayed.max")

    def _on_alarm(self, event: str, alarm) -> None:
        """$SYS alarm notification (emqx_alarm publishes to
        $SYS/brokers/<node>/alarms/activate|deactivate)."""
        import json as _json

        self._publish_dispatch(Message(
            topic=f"$SYS/brokers/{self.broker.node}/alarms/{event}",
            payload=_json.dumps(
                {"name": alarm.name, "message": alarm.message}).encode(),
            qos=0, from_="$SYS", flags={"sys": True},
        ))

    def prometheus(self) -> str:
        from emqx_tpu.observe import prometheus

        self.stats.tick()
        return prometheus.render(self.metrics, self.stats,
                                 node=self.broker.node)

    # -- delayed -----------------------------------------------------------

    def _publish_dispatch(self, msg: Message) -> None:
        self.cm.dispatch(self.broker.publish(msg))

    # -- retainer ----------------------------------------------------------

    def _retain_on_publish(self, msg: Message):
        self.retainer.on_publish(msg)
        if msg.retain and not msg.payload:
            # an empty retained publish clears the slot and is NOT routed
            return msg.set_header("allow_publish", False)
        return None

    def _retained_on_subscribe(self, sid: str, topic: str, opts,
                               is_new: bool = True) -> None:
        rh = getattr(opts, "rh", 0)
        if rh == 2 or (rh == 1 and not is_new):
            # rh=1: send retained only when the subscription did not
            # previously exist (MQTT5 3.8.3.1)
            return
        group, real = T.parse_share(topic)
        if group:
            return                      # shared subs get no retained msgs
        msgs = self.retainer.match(real)
        if msgs:
            self.cm.dispatch({sid: [(topic, m) for m in msgs]})

    # -- shared subs --------------------------------------------------------

    def _shared_on_subscribe(self, sid: str, topic: str, opts,
                             is_new: bool = True) -> None:
        group, real = T.parse_share(topic)
        if group:
            self.shared.join(group, real, sid)

    def _shared_on_unsubscribe(self, sid: str, topic: str) -> None:
        group, real = T.parse_share(topic)
        if group:
            self.shared.leave(group, real, sid)

    def _shared_on_terminated(self, sid: str, *args) -> None:
        self.shared.member_down(sid)

    def _shared_dispatch(self, group: str, topic: str, msg: Message):
        def deliver_fn(sid: str) -> bool:
            ch = self.cm.lookup_channel(sid)
            return ch is not None and ch.conn_state == "connected"
        return self.shared.dispatch(group, topic, msg, deliver_fn=deliver_fn)

    # -- housekeeping (server timer) ----------------------------------------

    def tick(self) -> None:
        self.delayed.tick()
        self.stats.tick()
        self.sys.tick()
        self.access.banned.expire()
        if self.access.flapping is not None:
            self.access.flapping.gc()
        for p in self.access.authn.providers:
            if hasattr(p, "gc"):
                p.gc()
        # delayed wills of disconnected-but-registered channels
        for _cid, ch in self.cm.all_channels():
            if getattr(ch, "pending_will_at", None) is not None:
                ch.will_tick()
