"""Protobuf wire codec for the ``emqx.exhook.v2`` surface.

A from-scratch proto3 encoder/decoder (varint + length-delimited wire
types only — this service uses nothing else) plus schema tables
mirroring ``apps/emqx_exhook/priv/protos/exhook.proto`` field-for-field
(message names, field numbers and types are the gRPC interop contract
with stock HookProviders; the COMMENT there pins the package to
``emqx.exhook.v2`` for all of EMQX 5.x).

The translator functions at the bottom map between this wire surface
and the framed-transport dict shapes (exhook/proto.py) so both
transports feed the same ``ExhookMgr`` logic.

tests/test_exhook_grpc.py cross-checks this codec against the official
``google.protobuf`` runtime via dynamically-built descriptors — the
differential oracle for field numbers/types.
"""

from __future__ import annotations

import time
from typing import Any, Optional

# ---------------------------------------------------------------------------
# proto3 wire primitives


def _varint(n: int) -> bytes:
    if n < 0:                          # int64 negatives: 10-byte two's cpl
        n += 1 << 64
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    n = shift = 0
    while True:
        if pos >= len(data) or shift > 63:
            raise ValueError("pb: truncated varint")
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not (b & 0x80):
            return n, pos
        shift += 7


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


# field kinds: varint-backed ("u32", "u64", "i64", "bool", "enum") and
# length-delimited ("str", "bytes", "msg", "map_ss"); any kind may be
# ("rep", inner) for repeated fields. "obool" = bool inside a oneof:
# ALWAYS serialized when the caller supplies it — oneof presence is the
# signal, so a False verdict must still appear on the wire
_VARINT_KINDS = {"u32", "u64", "i64", "bool", "enum", "obool"}


def encode(schema: dict, values: dict) -> bytes:
    """dict (by field name) → wire bytes. proto3 defaults (0 / "" /
    empty) are omitted."""
    by_name = {spec[0]: (num, spec) for num, spec in schema.items()}
    out = bytearray()
    for name, v in values.items():
        if name not in by_name or v is None:
            continue
        num, spec = by_name[name]
        kind = spec[1]
        if isinstance(kind, tuple) and kind[0] == "rep":
            for item in v:
                out += _encode_one(num, kind[1],
                                   spec[2] if len(spec) > 2 else None, item)
        elif kind == "map_ss":
            for k, mv in v.items():
                entry = encode({1: ("key", "str"), 2: ("value", "str")},
                               {"key": str(k), "value": str(mv)})
                out += _key(num, 2) + _varint(len(entry)) + entry
        else:
            if v in (0, "", b"", False) and kind not in ("msg", "obool"):
                continue                       # proto3 default
            out += _encode_one(num, kind,
                               spec[2] if len(spec) > 2 else None, v)
    return bytes(out)


def _encode_one(num: int, kind: str, sub: Optional[dict], v: Any) -> bytes:
    if kind in _VARINT_KINDS:
        if kind in ("bool", "obool"):
            v = 1 if v else 0
        return _key(num, 0) + _varint(int(v))
    if kind == "str":
        b = v.encode() if isinstance(v, str) else bytes(v)
        return _key(num, 2) + _varint(len(b)) + b
    if kind == "bytes":
        b = v if isinstance(v, (bytes, bytearray)) else str(v).encode()
        return _key(num, 2) + _varint(len(b)) + bytes(b)
    if kind == "msg":
        b = encode(sub, v)
        return _key(num, 2) + _varint(len(b)) + b
    raise ValueError(f"pb: unknown kind {kind}")


def decode(schema: dict, data: bytes) -> dict:
    """wire bytes → dict by field name; unknown fields skipped; absent
    fields get proto3 defaults."""
    out: dict = {}
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        num, wire = tag >> 3, tag & 0x07
        spec = schema.get(num)
        if wire == 0:
            v, pos = _read_varint(data, pos)
            if spec:
                out[spec[0]] = _coerce_varint(spec[1], v)
        elif wire == 2:
            ln, pos = _read_varint(data, pos)
            chunk = data[pos:pos + ln]
            if len(chunk) != ln:
                raise ValueError("pb: truncated length-delimited field")
            pos += ln
            if spec:
                _put_len_delim(out, spec, chunk)
        elif wire == 5:
            pos += 4                           # fixed32 (unused here)
        elif wire == 1:
            pos += 8                           # fixed64 (unused here)
        else:
            raise ValueError(f"pb: unsupported wire type {wire}")
    _fill_defaults(schema, out)
    return out


def _coerce_varint(kind, v: int):
    if isinstance(kind, tuple):                # repeated varint (unused)
        return v
    if kind in ("bool", "obool"):
        return bool(v)
    if kind == "i64" and v >= (1 << 63):
        return v - (1 << 64)
    return v


def _put_len_delim(out: dict, spec: tuple, chunk: bytes) -> None:
    name, kind = spec[0], spec[1]
    sub = spec[2] if len(spec) > 2 else None
    if isinstance(kind, tuple) and kind[0] == "rep":
        inner = kind[1]
        if inner == "str":
            out.setdefault(name, []).append(chunk.decode("utf-8",
                                                         "replace"))
        elif inner == "msg":
            out.setdefault(name, []).append(decode(sub, chunk))
        else:
            raise ValueError(f"pb: repeated {inner} unsupported")
    elif kind == "map_ss":
        entry = decode({1: ("key", "str"), 2: ("value", "str")}, chunk)
        out.setdefault(name, {})[entry["key"]] = entry["value"]
    elif kind == "str":
        out[name] = chunk.decode("utf-8", "replace")
    elif kind == "bytes":
        out[name] = chunk
    elif kind == "msg":
        out[name] = decode(sub, chunk)
    else:
        raise ValueError(f"pb: field {name} kind {kind} with wire type 2")


def _fill_defaults(schema: dict, out: dict) -> None:
    for spec in schema.values():
        name, kind = spec[0], spec[1]
        if name in out:
            continue
        if isinstance(kind, tuple):
            out[name] = []
        elif kind == "map_ss":
            out[name] = {}
        elif kind == "obool":
            continue                           # oneof member: presence only
        elif kind in _VARINT_KINDS:
            out[name] = False if kind == "bool" else 0
        elif kind == "str":
            out[name] = ""
        elif kind == "bytes":
            out[name] = b""
        # "msg": stays absent (proto3 message presence)


# ---------------------------------------------------------------------------
# emqx.exhook.v2 schemas (exhook.proto field numbers)

REQUEST_META = {1: ("node", "str"), 2: ("version", "str"),
                3: ("sysdescr", "str"), 4: ("cluster_name", "str")}

BROKER_INFO = {1: ("version", "str"), 2: ("sysdescr", "str"),
               3: ("uptime", "i64"), 4: ("datetime", "str")}

HOOK_SPEC = {1: ("name", "str"), 2: ("topics", ("rep", "str"))}

CONN_INFO = {1: ("node", "str"), 2: ("clientid", "str"),
             3: ("username", "str"), 4: ("peerhost", "str"),
             5: ("sockport", "u32"), 6: ("proto_name", "str"),
             7: ("proto_ver", "str"), 8: ("keepalive", "u32")}

CLIENT_INFO = {1: ("node", "str"), 2: ("clientid", "str"),
               3: ("username", "str"), 4: ("password", "str"),
               5: ("peerhost", "str"), 6: ("sockport", "u32"),
               7: ("protocol", "str"), 8: ("mountpoint", "str"),
               9: ("is_superuser", "bool"), 10: ("anonymous", "bool"),
               11: ("cn", "str"), 12: ("dn", "str")}

MESSAGE = {1: ("node", "str"), 2: ("id", "str"), 3: ("qos", "u32"),
           4: ("from", "str"), 5: ("topic", "str"), 6: ("payload", "bytes"),
           7: ("timestamp", "u64"), 8: ("headers", "map_ss")}

PROPERTY = {1: ("name", "str"), 2: ("value", "str")}

TOPIC_FILTER = {1: ("name", "str"), 2: ("qos", "u32")}

SUB_OPTS = {1: ("qos", "u32"), 2: ("share", "str"), 3: ("rh", "u32"),
            4: ("rap", "u32"), 5: ("nl", "u32")}

LOADED_RESPONSE = {1: ("hooks", ("rep", "msg"), HOOK_SPEC)}

VALUED_RESPONSE = {1: ("type", "enum"),          # 0 CONTINUE 1 IGNORE 2 STOP
                   3: ("bool_result", "obool"),  # oneof value
                   4: ("message", "msg", MESSAGE)}

EMPTY_SUCCESS: dict = {}

_META = ("meta", "msg", REQUEST_META)

REQUEST_SCHEMAS: dict[str, dict] = {
    "OnProviderLoaded": {1: ("broker", "msg", BROKER_INFO), 2: _META},
    "OnProviderUnloaded": {1: _META},
    "OnClientConnect": {1: ("conninfo", "msg", CONN_INFO),
                        2: ("props", ("rep", "msg"), PROPERTY), 3: _META},
    "OnClientConnack": {1: ("conninfo", "msg", CONN_INFO),
                        2: ("result_code", "str"),
                        3: ("props", ("rep", "msg"), PROPERTY), 4: _META},
    "OnClientConnected": {1: ("clientinfo", "msg", CLIENT_INFO), 2: _META},
    "OnClientDisconnected": {1: ("clientinfo", "msg", CLIENT_INFO),
                             2: ("reason", "str"), 3: _META},
    "OnClientAuthenticate": {1: ("clientinfo", "msg", CLIENT_INFO),
                             2: ("result", "bool"), 3: _META},
    "OnClientAuthorize": {1: ("clientinfo", "msg", CLIENT_INFO),
                          2: ("type", "enum"),   # 0 PUBLISH 1 SUBSCRIBE
                          3: ("topic", "str"), 4: ("result", "bool"),
                          5: _META},
    "OnClientSubscribe": {1: ("clientinfo", "msg", CLIENT_INFO),
                          2: ("props", ("rep", "msg"), PROPERTY),
                          3: ("topic_filters", ("rep", "msg"), TOPIC_FILTER),
                          4: _META},
    "OnClientUnsubscribe": {1: ("clientinfo", "msg", CLIENT_INFO),
                            2: ("props", ("rep", "msg"), PROPERTY),
                            3: ("topic_filters", ("rep", "msg"),
                                TOPIC_FILTER),
                            4: _META},
    "OnSessionCreated": {1: ("clientinfo", "msg", CLIENT_INFO), 2: _META},
    "OnSessionSubscribed": {1: ("clientinfo", "msg", CLIENT_INFO),
                            2: ("topic", "str"),
                            3: ("subopts", "msg", SUB_OPTS), 4: _META},
    "OnSessionUnsubscribed": {1: ("clientinfo", "msg", CLIENT_INFO),
                              2: ("topic", "str"), 3: _META},
    "OnSessionResumed": {1: ("clientinfo", "msg", CLIENT_INFO), 2: _META},
    "OnSessionDiscarded": {1: ("clientinfo", "msg", CLIENT_INFO), 2: _META},
    "OnSessionTakenover": {1: ("clientinfo", "msg", CLIENT_INFO), 2: _META},
    "OnSessionTerminated": {1: ("clientinfo", "msg", CLIENT_INFO),
                            2: ("reason", "str"), 3: _META},
    "OnMessagePublish": {1: ("message", "msg", MESSAGE), 2: _META},
    "OnMessageDelivered": {1: ("clientinfo", "msg", CLIENT_INFO),
                           2: ("message", "msg", MESSAGE), 3: _META},
    "OnMessageDropped": {1: ("message", "msg", MESSAGE),
                         2: ("reason", "str"), 3: _META},
    "OnMessageAcked": {1: ("clientinfo", "msg", CLIENT_INFO),
                       2: ("message", "msg", MESSAGE), 3: _META},
}

# RPCs answering ValuedResponse; every other one answers EmptySuccess
# except OnProviderLoaded (LoadedResponse)
VALUED_RPCS = {"OnClientAuthenticate", "OnClientAuthorize",
               "OnMessagePublish"}

SERVICE = "emqx.exhook.v2.HookProvider"


def method_path(rpc: str) -> str:
    return f"/{SERVICE}/{rpc}"


# ---------------------------------------------------------------------------
# framed-dict ↔ proto-dict translation (broker side)

_ENUM_TYPE = {"publish": 0, "subscribe": 1}
_TYPE_NAMES = {0: "CONTINUE", 1: "IGNORE", 2: "STOP_AND_RETURN"}


def _pb_clientinfo(ci: dict) -> dict:
    peer = str(ci.get("peerhost") or ci.get("peername") or "")
    host, _, port = peer.rpartition(":")
    out = {"clientid": str(ci.get("clientid") or ""),
           "username": str(ci.get("username") or ""),
           "peerhost": host or peer,
           "node": str(ci.get("node") or "emqx_tpu@127.0.0.1")}
    if ci.get("password") is not None:
        pw = ci["password"]
        out["password"] = (pw.decode("utf-8", "replace")
                           if isinstance(pw, bytes) else str(pw))
    if port.isdigit():
        out["sockport"] = int(port)
    if ci.get("proto_ver") is not None:
        out["protocol"] = str(ci["proto_ver"])
    if ci.get("mountpoint"):
        out["mountpoint"] = str(ci["mountpoint"])
    if ci.get("is_superuser"):
        out["is_superuser"] = True
    return out


# the proto's headers map carries ONLY these string keys (exhook.proto
# Message.headers comment: username/protocol/peerhost readonly +
# allow_publish writable) — broker-internal structured headers
# (properties dicts etc.) never cross the wire
_WIRE_HEADERS = ("username", "protocol", "peerhost", "allow_publish")


def _pb_message(m: dict) -> dict:
    payload = m.get("payload", b"")
    if isinstance(payload, str):
        payload = payload.encode()
    src = m.get("headers") or {}
    headers = {k: str(src[k]) for k in _WIRE_HEADERS if src.get(k)
               is not None}
    return {"id": str(m.get("id") or ""), "qos": int(m.get("qos") or 0),
            "from": str(m.get("from") or ""),
            "topic": str(m.get("topic") or ""), "payload": payload,
            "timestamp": int(m.get("timestamp") or time.time() * 1000),
            "headers": headers,
            "node": str(m.get("node") or "emqx_tpu@127.0.0.1")}


def _from_pb_message(pm: dict) -> dict:
    headers = {k: v for k, v in (pm.get("headers") or {}).items()
               if k in _WIRE_HEADERS}
    return {"id": pm.get("id") or "", "qos": pm.get("qos", 0),
            "from": pm.get("from", ""), "topic": pm.get("topic", ""),
            "payload": pm.get("payload", b""),
            "timestamp": pm.get("timestamp", 0),
            "headers": headers, "flags": {}}


def build_request(rpc: str, args: dict, meta: Optional[dict] = None) -> bytes:
    """framed-transport args (exhook/proto.py shapes) → request bytes."""
    v: dict[str, Any] = {"meta": meta or {"node": "emqx_tpu@127.0.0.1",
                                          "version": "5.0.14"}}
    if rpc == "OnProviderLoaded":
        b = args.get("broker") or {}
        v["broker"] = {"version": str(b.get("version", "5.0.14")),
                       "sysdescr": str(b.get("sysdescr", "emqx_tpu")),
                       "uptime": int(b.get("uptime", 0)),
                       "datetime": str(b.get("datetime", ""))}
    elif rpc in ("OnClientConnect", "OnClientConnack"):
        # these two carry ConnInfo (not ClientInfo) + connack's
        # result_code; the hook ships positional args through the
        # notify shape
        plain = args.get("args") or []
        dicts = [a for a in plain if isinstance(a, dict)]
        ci = dicts[0] if dicts else (args.get("conninfo") or {})
        peer = str(ci.get("peerhost") or ci.get("peername") or "")
        host, _, port = peer.rpartition(":")
        conninfo = {"clientid": str(ci.get("clientid") or ""),
                    "username": str(ci.get("username") or ""),
                    "peerhost": host or peer,
                    "proto_name": str(ci.get("proto_name") or "MQTT"),
                    "proto_ver": str(ci.get("proto_ver") or ""),
                    "node": "emqx_tpu@127.0.0.1"}
        if port.isdigit():
            conninfo["sockport"] = int(port)
        if ci.get("keepalive"):
            conninfo["keepalive"] = int(ci["keepalive"])
        v["conninfo"] = conninfo
        if rpc == "OnClientConnack":
            rcs = [a for a in plain if isinstance(a, (int, str))
                   and not isinstance(a, bool)]
            rc = rcs[0] if rcs else args.get("result_code", 0)
            v["result_code"] = ("success" if rc in (0, "0", "success")
                                else str(rc))
    elif rpc == "OnClientAuthenticate":
        v["clientinfo"] = _pb_clientinfo(args.get("clientinfo") or {})
    elif rpc == "OnClientAuthorize":
        v["clientinfo"] = _pb_clientinfo(args.get("clientinfo") or {})
        v["type"] = _ENUM_TYPE.get(str(args.get("type", "publish")), 0)
        v["topic"] = str(args.get("topic", ""))
    elif rpc in ("OnMessagePublish", "OnMessageDropped"):
        v["message"] = _pb_message(args.get("message") or {})
        if args.get("reason"):
            v["reason"] = str(args["reason"])
    elif rpc in ("OnMessageDelivered", "OnMessageAcked"):
        v["clientinfo"] = _pb_clientinfo(args.get("clientinfo") or {})
        v["message"] = _pb_message(args.get("message") or {})
    else:
        # notify RPCs: the framed transport ships {"args": [...]} — pick
        # out recognizable positional payloads for the proto fields
        plain = args.get("args") or []
        dicts = [a for a in plain if isinstance(a, dict)]
        strs = [a for a in plain if isinstance(a, str)]
        if dicts:
            first = dicts[0]
            if "topic" in first and "payload" in first:
                v["message"] = _pb_message(first)
            else:
                v["clientinfo"] = _pb_clientinfo(first)
        if strs and rpc in ("OnClientDisconnected", "OnSessionTerminated"):
            v["reason"] = strs[0]
        elif strs and rpc in ("OnSessionSubscribed",
                              "OnSessionUnsubscribed"):
            v["topic"] = strs[0]
            if rpc == "OnSessionSubscribed" and len(dicts) > 1:
                v["subopts"] = {k: dicts[1][k] for k in
                                ("qos", "rh", "rap", "nl")
                                if isinstance(dicts[1].get(k), int)}
    schema = REQUEST_SCHEMAS[rpc]
    return encode(schema, {k: x for k, x in v.items()
                           if any(s[0] == k for s in schema.values())})


def parse_response(rpc: str, data: bytes) -> Any:
    """response bytes → the framed-transport result shape the
    ExhookMgr logic consumes."""
    if rpc == "OnProviderLoaded":
        resp = decode(LOADED_RESPONSE, data)
        return {"hooks": [h["name"] for h in resp.get("hooks", [])]}
    if rpc in VALUED_RPCS:
        resp = decode(VALUED_RESPONSE, data)
        out: dict[str, Any] = {
            "type": _TYPE_NAMES.get(resp.get("type", 0), "CONTINUE")}
        value: dict[str, Any] = {}
        if "message" in resp and resp["message"] is not None:
            pm = resp["message"]
            if (pm.get("headers") or {}).get("allow_publish") == "false":
                value["drop"] = True
            else:
                value["message"] = _from_pb_message(pm)
        else:
            value["result"] = bool(resp.get("bool_result"))
        out["value"] = value
        return out
    return {}                                   # EmptySuccess
