"""Provider-side harness — what an external HookProvider service runs
(the reference's test fixture is an in-repo gRPC echo server,
``apps/emqx_exhook/test/emqx_exhook_demo_svr.erl``).

Subclass ``HookProvider``, override the RPCs you care about, and
``serve``. The default implementation answers ``OnProviderLoaded`` with
every overridden hookpoint and CONTINUEs everything else.
"""

from __future__ import annotations

import logging
import socketserver
import threading
from typing import Any, Optional

from emqx_tpu.exhook import proto

log = logging.getLogger("emqx_tpu.exhook.provider")


class HookProvider:
    """Override rpc methods named after the proto (``on_client_connect``,
    ``on_message_publish``, ...). Each receives the args dict and returns
    a response dict or None (→ CONTINUE)."""

    def hooks(self) -> list[str]:
        """Hookpoints to register — default: every overridden handler."""
        wanted = []
        for hookpoint, rpc in proto.HOOK_RPCS.items():
            meth = getattr(self, _snake(rpc), None)
            if meth is not None and not getattr(meth, "__isabstract__",
                                                False):
                base = getattr(HookProvider, _snake(rpc), None)
                if meth.__func__ is not base:
                    wanted.append(hookpoint)
        return wanted

    def dispatch(self, rpc: str, args: dict) -> Any:
        if rpc == "OnProviderLoaded":
            return {"hooks": self.hooks()}
        if rpc == "OnProviderUnloaded":
            return {}
        meth = getattr(self, _snake(rpc), None)
        if meth is None:
            return {"type": proto.CONTINUE}
        resp = meth(args)
        return resp if resp is not None else {"type": proto.CONTINUE}

    # default no-op handlers (subclasses override a subset)
    def on_client_connect(self, args):          # noqa: D102
        return None

    def on_client_connack(self, args):
        return None

    def on_client_connected(self, args):
        return None

    def on_client_disconnected(self, args):
        return None

    def on_client_authenticate(self, args):
        return None

    def on_client_authorize(self, args):
        return None

    def on_client_subscribe(self, args):
        return None

    def on_client_unsubscribe(self, args):
        return None

    def on_session_created(self, args):
        return None

    def on_session_subscribed(self, args):
        return None

    def on_session_unsubscribed(self, args):
        return None

    def on_session_resumed(self, args):
        return None

    def on_session_discarded(self, args):
        return None

    def on_session_takenover(self, args):
        return None

    def on_session_terminated(self, args):
        return None

    def on_message_publish(self, args):
        return None

    def on_message_publish_batch(self, args):
        """Default batch = per-message on_message_publish fan-in."""
        results = []
        for m in args.get("messages", []):
            resp = self.on_message_publish({"message": m}) or {}
            val = resp.get("value") or {}
            results.append({"drop": bool(val.get("drop")),
                            "message": val.get("message")})
        return {"results": results}

    def on_message_delivered(self, args):
        return None

    def on_message_acked(self, args):
        return None

    def on_message_dropped(self, args):
        return None


def _snake(rpc: str) -> str:
    out = []
    for ch in rpc:
        if ch.isupper() and out:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


class ProviderServer:
    """TCP server hosting a HookProvider."""

    def __init__(self, provider: HookProvider, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.provider = provider
        prov = provider

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = proto.recv_frame(self.request)
                    except OSError:
                        return
                    if req is None:
                        return
                    try:
                        result = prov.dispatch(req.get("rpc", ""),
                                               req.get("args") or {})
                        resp = {"result": result}
                    except Exception as e:   # noqa: BLE001 — relay
                        log.exception("provider rpc failed")
                        resp = {"error": f"{type(e).__name__}: {e}"}
                    try:
                        proto.send_frame(self.request, resp)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="exhook-provider")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
