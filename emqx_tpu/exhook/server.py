"""Broker-side exhook — parity with
``apps/emqx_exhook/src/emqx_exhook_server.erl`` (+ ``_mgr``/`_handler``).

``ExhookServer`` holds a small connection pool to one external provider
(pool_size connections, emqx_exhook_server.erl:135), calls
``OnProviderLoaded`` to learn which hookpoints the provider wants, and
bridges those hookpoints to RPCs. Per-call timeout with ``failed_action``
deny|ignore semantics (:95-96,433): on timeout/error, ``deny`` stops the
chain (drops the message / denies auth), ``ignore`` continues.

``ExhookMgr`` manages several named providers and owns the hook
registrations (emqx_exhook_handler.erl:228-236 bridges each hookpoint).
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Any, Optional

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.cluster import codec
from emqx_tpu.core.message import Message
from emqx_tpu.exhook import proto
from emqx_tpu.mqtt import packet as P

log = logging.getLogger("emqx_tpu.exhook")


class _Conn:
    def __init__(self, addr: tuple[str, int], timeout: float) -> None:
        self.addr = addr
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self.lock = threading.Lock()

    def _ensure(self) -> socket.socket:
        if self.sock is None:
            self.sock = socket.create_connection(self.addr,
                                                 timeout=self.timeout)
        return self.sock

    def call(self, rpc: str, args: dict) -> Any:
        with self.lock:
            try:
                sock = self._ensure()
                proto.send_frame(sock, {"rpc": rpc, "args": args})
                resp = proto.recv_frame(sock)
            except (OSError, socket.timeout):
                self.close()
                raise
            except ValueError as e:      # malformed frame = provider
                self.close()             # failure, not a broker crash
                raise ConnectionError(f"bad provider frame: {e}") \
                    from None
            if resp is None:
                self.close()
                raise ConnectionError("provider closed connection")
            if resp.get("error"):
                raise ConnectionError(resp["error"])
            return resp.get("result")

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None


class ExhookServer:
    """transport="framed" speaks the length-prefixed JSON protocol
    (exhook/proto.py); transport="grpc" speaks the reference's real
    gRPC HookProvider service (exhook/grpc_transport.py) so stock
    providers connect with no adapter."""

    def __init__(self, name: str, host: str, port: int,
                 pool_size: int = 4, timeout_s: float = 5.0,
                 failed_action: str = "deny",
                 transport: str = "framed") -> None:
        self.name = name
        self.failed_action = failed_action
        self.transport = transport
        if transport in ("grpc", "grpcs"):
            from emqx_tpu.exhook.grpc_transport import (GrpcConn,
                                                        grpc_available)
            if not grpc_available():
                raise ValueError(
                    f"exhook {name}: url scheme {transport}:// needs "
                    "grpcio, which is not importable in this "
                    "environment — use the framed:// transport")
            # HTTP/2 multiplexes, but a single channel serializes onto
            # one TCP connection; honor pool_size with N round-robin
            # channels for parity with the framed transport (the
            # reference's gRPC client pool, emqx_exhook_server.erl:135)
            self._pool = [GrpcConn((host, port), timeout_s,
                                   secure=(transport == "grpcs"))
                          for _ in range(max(1, pool_size))]
        elif transport == "framed":
            self._pool = [_Conn((host, port), timeout_s)
                          for _ in range(max(1, pool_size))]
        else:
            raise ValueError(
                f"exhook {name}: unknown transport {transport!r} "
                "(grpc | grpcs | framed)")
        self._rr = 0
        self.hooks_wanted: list[str] = []
        self.loaded = False

    def load(self, broker_info: Optional[dict] = None) -> list[str]:
        resp = self.call("OnProviderLoaded",
                         {"broker": broker_info or {}})
        self.hooks_wanted = list((resp or {}).get("hooks", []))
        self.loaded = True
        return self.hooks_wanted

    def unload(self) -> None:
        try:
            self.call("OnProviderUnloaded", {})
        except ConnectionError:
            pass
        for c in self._pool:
            c.close()
        self.loaded = False

    def call(self, rpc: str, args: dict) -> Any:
        self._rr = (self._rr + 1) % len(self._pool)
        return self._pool[self._rr].call(rpc, args)


class ExhookMgr:
    """Hook-side bridge for N providers (emqx_exhook_mgr)."""

    def __init__(self, metrics=None) -> None:
        self.servers: dict[str, ExhookServer] = {}
        self.metrics = metrics
        self._hooks: Optional[Hooks] = None
        # fired when the provider set (or a provider's wanted hooks)
        # changes — the native host flushes its publish permits so a
        # provider watching message.* sees already-fast topics at once
        self.on_topology_change: list = []

    def _notify(self) -> None:
        for cb in self.on_topology_change:
            cb()

    def attach(self, hooks: Hooks) -> None:
        self._hooks = hooks
        # exhook outranks the built-in security chain: HP_EXHOOK sits
        # above authn/authz in the reference, so providers decide first
        # and CONTINUE falls through to the local chain
        hooks.add("client.authenticate", self._on_authenticate,
                  priority=1100)
        hooks.add("client.authorize", self._on_authorize, priority=1100)
        hooks.add("message.publish", self._on_message_publish,
                  priority=1100)
        for hookpoint in proto.HOOK_RPCS:
            if hookpoint in ("client.authenticate", "client.authorize",
                             "message.publish"):
                continue
            hooks.add(hookpoint, self._make_notify(hookpoint),
                      priority=900)

    def enable(self, server: ExhookServer) -> list[str]:
        wanted = server.load()
        self.servers[server.name] = server
        self._notify()
        return wanted

    def enable_async(self, server: ExhookServer,
                     retry_interval_s: Optional[float] = 5.0) -> bool:
        """Register the provider and try to load it; on failure keep it
        registered unloaded and let tick() retry — the reference's
        auto_reconnect (emqx_exhook_mgr). ``retry_interval_s=None`` =
        auto_reconnect disabled: one attempt, never retried. Returns
        whether the first load succeeded. Until loaded, the provider's
        hooks are not consulted (same fail-open window as the
        reference's waiting-for-reconnect state)."""
        self.servers[server.name] = server
        server.retry_interval_s = retry_interval_s
        server.next_retry_at = 0.0
        # boot must not stall timeout_s per blackholed provider: cap the
        # FIRST attempt at 2s; retries use the configured timeout
        saved = [c.timeout for c in server._pool]
        for c in server._pool:
            c.timeout = min(c.timeout, 2.0)
        try:
            server.load()
            self._notify()     # hooks_wanted now known — flush permits
            return True
        except (ConnectionError, OSError, ValueError) as e:
            import time as _t
            if retry_interval_s is None:
                server.next_retry_at = float("inf")
                log.warning("exhook provider %s unreachable (%s); "
                            "auto_reconnect disabled", server.name, e)
            else:
                server.next_retry_at = _t.monotonic() + retry_interval_s
                log.warning("exhook provider %s unreachable (%s); will "
                            "retry every %.0fs", server.name, e,
                            retry_interval_s)
            return False
        finally:
            for c, t in zip(server._pool, saved):
                c.timeout = t

    def tick(self) -> None:
        """Housekeeping: retry unloaded providers (auto_reconnect)."""
        import time as _t
        now = _t.monotonic()
        for server in self.servers.values():
            if server.loaded or now < getattr(server, "next_retry_at",
                                              float("inf")):
                continue
            try:
                server.load()
                log.info("exhook provider %s reconnected (hooks: %s)",
                         server.name, server.hooks_wanted)
                self._notify()     # hooks_wanted may have changed
            except (ConnectionError, OSError, ValueError):
                # ValueError included: a garbage LoadedResponse must not
                # escape app.tick and kill broker housekeeping
                server.next_retry_at = now + (getattr(
                    server, "retry_interval_s", None) or 5.0)

    def disable(self, name: str) -> bool:
        server = self.servers.pop(name, None)
        if server is None:
            return False
        server.unload()
        self._notify()
        return True

    def _servers_for(self, hookpoint: str) -> list[ExhookServer]:
        return [s for s in self.servers.values()
                if s.loaded and hookpoint in s.hooks_wanted]

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(f"exhook.{name}")

    # -- fold hooks (may deny / rewrite) ------------------------------------

    def _on_authenticate(self, cred: dict, acc: dict):
        for server in self._servers_for("client.authenticate"):
            try:
                resp = server.call("OnClientAuthenticate",
                                   {"clientinfo": _public_cred(cred)})
                self._inc("authenticate")
            except (ConnectionError, OSError):
                self._inc("failed")
                if server.failed_action == "deny":
                    return (Hooks.STOP,
                            {"result": "error", "reason": "exhook_down",
                             "rc": P.RC_NOT_AUTHORIZED})
                continue
            rtype = (resp or {}).get("type", proto.IGNORE)
            if rtype == proto.STOP_AND_RETURN:
                ok = bool((resp.get("value") or {}).get("result"))
                if ok:
                    return (Hooks.OK, {"result": "ok"})
                return (Hooks.STOP,
                        {"result": "error", "reason": "exhook_denied",
                         "rc": P.RC_NOT_AUTHORIZED})
        return None

    def _on_authorize(self, ci: dict, action: str, topic: str, acc: str):
        for server in self._servers_for("client.authorize"):
            try:
                resp = server.call("OnClientAuthorize", {
                    "clientinfo": _public_cred(ci),
                    "type": action, "topic": topic})
                self._inc("authorize")
            except (ConnectionError, OSError):
                self._inc("failed")
                if server.failed_action == "deny":
                    return (Hooks.STOP, "deny")
                continue
            rtype = (resp or {}).get("type", proto.IGNORE)
            if rtype == proto.STOP_AND_RETURN:
                ok = bool((resp.get("value") or {}).get("result"))
                return (Hooks.STOP, "allow" if ok else "deny")
        return None

    def _on_message_publish(self, msg: Message, *rest):
        if msg.topic.startswith("$SYS/"):
            return None
        cur = msg
        for server in self._servers_for("message.publish"):
            try:
                resp = server.call("OnMessagePublish",
                                   {"message": codec.msg_to_dict(cur)})
                self._inc("message_publish")
            except (ConnectionError, OSError):
                self._inc("failed")
                if server.failed_action == "deny":
                    return cur.set_header("allow_publish", False)
                continue
            rtype = (resp or {}).get("type", proto.IGNORE)
            if rtype == proto.STOP_AND_RETURN:
                val = resp.get("value") or {}
                if val.get("drop"):
                    return cur.set_header("allow_publish", False)
                if val.get("message"):
                    new = codec.msg_from_dict(val["message"])
                    # identity + qos are broker-owned; providers rewrite
                    # topic/payload/headers (exhook ValuedResponse scope)
                    cur = Message(
                        topic=new.topic, payload=new.payload, qos=cur.qos,
                        from_=cur.from_, id=cur.id,
                        flags=cur.flags,
                        headers={**cur.headers, **new.headers},
                        timestamp=cur.timestamp)
        return cur if cur is not msg else None

    # -- batch publish (the TPU sidecar seam) -------------------------------

    def on_message_publish_batch(
            self, msgs: list[Message]) -> list[Optional[Message]]:
        """Batched OnMessagePublish — the exhook-gRPC-style sidecar lane
        the north star prescribes (SURVEY.md §3.5): one RPC carries the
        whole publish batch; verdicts apply per message. Falls back to
        passing messages through on provider failure with
        failed_action=ignore, drops the batch with deny."""
        out: list[Optional[Message]] = list(msgs)
        for server in self._servers_for("message.publish"):
            live = [(i, m) for i, m in enumerate(out) if m is not None]
            if not live:
                break
            try:
                resp = server.call("OnMessagePublishBatch", {
                    "messages": [codec.msg_to_dict(m) for _, m in live]})
                self._inc("message_publish_batch")
            except (ConnectionError, OSError):
                self._inc("failed")
                if server.failed_action == "deny":
                    return [None] * len(msgs)
                continue
            verdicts = (resp or {}).get("results", [])
            for (i, m), v in zip(live, verdicts):
                if v.get("drop"):
                    out[i] = None
                elif v.get("message"):
                    new = codec.msg_from_dict(v["message"])
                    out[i] = Message(
                        topic=new.topic, payload=new.payload, qos=m.qos,
                        from_=m.from_, id=m.id, flags=m.flags,
                        headers={**m.headers, **new.headers},
                        timestamp=m.timestamp)
        return out

    # -- notify-only hooks --------------------------------------------------

    def _make_notify(self, hookpoint: str):
        rpc = proto.HOOK_RPCS[hookpoint]

        def cb(*args):
            for server in self._servers_for(hookpoint):
                try:
                    server.call(rpc, _notify_args(hookpoint, args))
                    self._inc(hookpoint.replace(".", "_"))
                except (ConnectionError, OSError):
                    self._inc("failed")
            return None
        return cb


def _public_cred(cred: dict) -> dict:
    out = dict(cred)
    pw = out.get("password")
    if isinstance(pw, bytes):
        out["password"] = pw.decode(errors="replace")
    return out


def _notify_args(hookpoint: str, args: tuple) -> dict:
    def plain(x):
        if isinstance(x, Message):
            return codec.msg_to_dict(x)
        if hasattr(x, "__dict__"):
            return {k: v for k, v in x.__dict__.items()
                    if isinstance(v, (str, int, float, bool, type(None)))}
        if isinstance(x, (str, int, float, bool, type(None), dict, list)):
            return x
        return str(x)

    return {"args": [plain(a) for a in args]}
