"""gRPC transport for exhook — the real ``HookProvider`` wire
(apps/emqx_exhook/src/emqx_exhook_server.erl over grpc-erl).

``GrpcConn`` implements the same ``call(rpc, args) -> result`` surface
as the framed transport's ``_Conn`` (exhook/server.py), so
``ExhookServer``/``ExhookMgr`` logic is transport-agnostic: requests
are encoded with the hand-written proto codec (exhook/pbwire.py) and
shipped over a grpcio channel as raw bytes (no codegen — grpcio's
generic unary stubs with identity serializers).

``GrpcHookProvider`` is the in-repo provider-side server — the
``emqx_exhook_demo_svr.erl`` analogue: a grpcio server exposing the
21-RPC ``emqx.exhook.v2.HookProvider`` service from a plain handler
object, decoding requests into dicts and encoding ValuedResponse /
LoadedResponse replies. Because both sides speak the real wire format,
a stock gRPC HookProvider (any language) can replace it directly.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Optional

from emqx_tpu.exhook import pbwire


def grpc_available() -> bool:
    try:
        import grpc  # noqa: F401
        return True
    except ImportError:
        return False


_IDENT = lambda b: b      # noqa: E731 — bytes in/out (no codegen)


def make_grpc_server(service: str, rpc_names, dispatch, *,
                     streaming: bool = False, host: str = "127.0.0.1",
                     port: int = 0, workers: int = 4):
    """Generic bytes-in/bytes-out grpcio server for one service.

    ``dispatch(rpc, request)`` gets raw request bytes (or, with
    ``streaming=True``, the request iterator) and returns raw response
    bytes. Shared by the exhook provider host and both exproto sides —
    one place for the method-prefix/handler plumbing. Returns
    (server, bound_port)."""
    import concurrent.futures

    import grpc

    class _Svc(grpc.GenericRpcHandler):
        def service(self, details):
            prefix = f"/{service}/"
            if not details.method.startswith(prefix):
                return None
            rpc = details.method[len(prefix):]
            if rpc not in rpc_names:
                return None
            make = (grpc.stream_unary_rpc_method_handler if streaming
                    else grpc.unary_unary_rpc_method_handler)
            return make(
                lambda req, ctx, rpc=rpc: dispatch(rpc, req),
                request_deserializer=_IDENT,
                response_serializer=_IDENT)

    server = grpc.server(
        concurrent.futures.ThreadPoolExecutor(max_workers=workers))
    server.add_generic_rpc_handlers((_Svc(),))
    bound = server.add_insecure_port(f"{host}:{port}")
    return server, bound


_conn_seq = itertools.count()


class GrpcConn:
    """One gRPC channel (= one HTTP/2 connection). ExhookServer opens
    pool_size of these round-robin — the reference's per-scheduler
    client pool (emqx_exhook_server.erl:135). The unique channel arg
    defeats grpc-core's global subchannel dedup, which would otherwise
    silently collapse N same-target channels onto one TCP connection."""

    def __init__(self, addr: tuple, timeout: float,
                 secure: bool = False) -> None:
        import grpc

        self.timeout = timeout
        target = f"{addr[0]}:{addr[1]}"
        opts = [("emqx_tpu.pool_index", next(_conn_seq))]
        if secure:        # grpcs:// / https:// — system root CAs
            self._channel = grpc.secure_channel(
                target, grpc.ssl_channel_credentials(), options=opts)
        else:
            self._channel = grpc.insecure_channel(target, options=opts)
        self._stubs: dict[str, Any] = {}
        self._lock = threading.Lock()

    def _stub(self, rpc: str):
        with self._lock:
            stub = self._stubs.get(rpc)
            if stub is None:
                stub = self._channel.unary_unary(
                    pbwire.method_path(rpc),
                    request_serializer=_IDENT,
                    response_deserializer=_IDENT)
                self._stubs[rpc] = stub
            return stub

    def call(self, rpc: str, args: dict) -> Any:
        import grpc

        if rpc == "OnMessagePublishBatch":
            # the TPU batch lane is an extension RPC; stock providers
            # don't implement it — per-message calls preserve semantics
            results = []
            for m in args.get("messages", []):
                r = self.call("OnMessagePublish", {"message": m}) or {}
                v = (r.get("value") or {}
                     if r.get("type") == "STOP_AND_RETURN" else {})
                results.append(v)
            return {"results": results}
        req = pbwire.build_request(rpc, args)
        try:
            resp = self._stub(rpc)(req, timeout=self.timeout)
        except grpc.RpcError as e:
            raise ConnectionError(
                f"grpc {rpc}: {e.code().name}") from None
        try:
            return pbwire.parse_response(rpc, resp)
        except ValueError as e:
            # malformed reply bytes must surface as a PROVIDER failure
            # (failed_action applies) — a raw ValueError would escape
            # the hook handlers' (ConnectionError, OSError) guards and
            # crash the auth/publish path
            raise ConnectionError(f"grpc {rpc}: bad response: {e}") \
                from None

    def close(self) -> None:
        self._channel.close()


# ---------------------------------------------------------------------------
# provider-side server (test/demo backend + SDK for real providers)


class GrpcHookProvider:
    """Serve ``emqx.exhook.v2.HookProvider`` from a handler object.

    handler contract (all optional):
      - ``hooks``: list of hookpoint names to register (LoadedResponse)
      - ``on_client_authenticate(clientinfo) -> bool | None``
      - ``on_client_authorize(clientinfo, type, topic) -> bool | None``
      - ``on_message_publish(message) -> dict (rewritten) | False (drop)
        | None (continue)``
      - ``on_notify(rpc, request_dict)``: every other RPC
    None → CONTINUE (chain proceeds), a value → STOP_AND_RETURN.
    """

    def __init__(self, handler: Any, host: str = "127.0.0.1",
                 port: int = 0, workers: int = 4) -> None:
        self.handler = handler
        self.calls: list[str] = []           # observed RPC order (tests)
        self._server, self.port = make_grpc_server(
            pbwire.SERVICE, pbwire.REQUEST_SCHEMAS, self._dispatch,
            host=host, port=port, workers=workers)

    # -- dispatch -----------------------------------------------------------

    def _dispatch(self, rpc: str, req: bytes) -> bytes:
        self.calls.append(rpc)
        request = pbwire.decode(pbwire.REQUEST_SCHEMAS[rpc], req)
        if rpc == "OnProviderLoaded":
            hooks = list(getattr(self.handler, "hooks", []))
            return pbwire.encode(pbwire.LOADED_RESPONSE, {
                "hooks": [{"name": h} for h in hooks]})
        if rpc == "OnClientAuthenticate":
            fn = getattr(self.handler, "on_client_authenticate", None)
            verdict = fn(request.get("clientinfo") or {}) if fn else None
            return self._valued_bool(verdict)
        if rpc == "OnClientAuthorize":
            fn = getattr(self.handler, "on_client_authorize", None)
            verdict = fn(request.get("clientinfo") or {},
                         "publish" if request.get("type") == 0
                         else "subscribe",
                         request.get("topic", "")) if fn else None
            return self._valued_bool(verdict)
        if rpc == "OnMessagePublish":
            fn = getattr(self.handler, "on_message_publish", None)
            msg = request.get("message") or {}
            verdict = fn(msg) if fn else None
            if verdict is None:
                return pbwire.encode(pbwire.VALUED_RESPONSE, {"type": 0})
            if verdict is False:                 # drop
                dropped = {**msg,
                           "headers": {**(msg.get("headers") or {}),
                                       "allow_publish": "false"}}
                return pbwire.encode(pbwire.VALUED_RESPONSE, {
                    "type": 2, "message": dropped})
            return pbwire.encode(pbwire.VALUED_RESPONSE, {
                "type": 2, "message": verdict})
        fn = getattr(self.handler, "on_notify", None)
        if fn:
            fn(rpc, request)
        return b""                               # EmptySuccess

    @staticmethod
    def _valued_bool(verdict: Optional[bool]) -> bytes:
        if verdict is None:
            return pbwire.encode(pbwire.VALUED_RESPONSE, {"type": 0})
        return pbwire.encode(pbwire.VALUED_RESPONSE, {
            "type": 2, "bool_result": bool(verdict)})

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "GrpcHookProvider":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=0.2)
