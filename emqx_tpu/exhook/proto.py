"""Exhook wire protocol — the ``exhook.proto`` surface
(apps/emqx_exhook/priv/protos/exhook.proto:29-72) over length-prefixed
codec frames.

RPCs (same names/cardinality as the reference's 21-RPC HookProvider
service, plus the TPU-era batch publish):

    OnProviderLoaded(broker)             → {hooks: [hookpoint...]}
    OnProviderUnloaded()
    OnClientConnect/Connack/Connected/Disconnected(...)
    OnClientAuthenticate(clientinfo)     → valued bool
    OnClientAuthorize(clientinfo, action, topic) → valued bool
    OnClientSubscribe/Unsubscribe(...)
    OnSessionCreated/Subscribed/Unsubscribed/Resumed/Discarded/
      Takenover/Terminated(...)
    OnMessagePublish(message)            → valued message (rewrite/drop)
    OnMessagePublishBatch(messages)      → per-message verdicts  [TPU]
    OnMessageDelivered/Acked/Dropped(...)

Responses carry {"type": "CONTINUE" | "STOP_AND_RETURN" | "IGNORE",
"value": ...} — the ValuedResponse of the reference.
"""

from __future__ import annotations

import socket
import struct
from typing import Any, Optional

from emqx_tpu.cluster import codec

# hookpoint name (broker side) → RPC name
HOOK_RPCS = {
    "client.connect": "OnClientConnect",
    "client.connack": "OnClientConnack",
    "client.connected": "OnClientConnected",
    "client.disconnected": "OnClientDisconnected",
    "client.authenticate": "OnClientAuthenticate",
    "client.authorize": "OnClientAuthorize",
    "client.subscribe": "OnClientSubscribe",
    "client.unsubscribe": "OnClientUnsubscribe",
    "session.created": "OnSessionCreated",
    "session.subscribed": "OnSessionSubscribed",
    "session.unsubscribed": "OnSessionUnsubscribed",
    "session.resumed": "OnSessionResumed",
    "session.discarded": "OnSessionDiscarded",
    "session.takenover": "OnSessionTakenover",
    "session.terminated": "OnSessionTerminated",
    "message.publish": "OnMessagePublish",
    "message.delivered": "OnMessageDelivered",
    "message.acked": "OnMessageAcked",
    "message.dropped": "OnMessageDropped",
}
RPC_HOOKS = {v: k for k, v in HOOK_RPCS.items()}

CONTINUE = "CONTINUE"
STOP_AND_RETURN = "STOP_AND_RETURN"
IGNORE = "IGNORE"


def send_frame(sock: socket.socket, obj: Any) -> None:
    body = codec.encode(obj)
    sock.sendall(struct.pack(">I", len(body)) + body)


def recv_frame(sock: socket.socket) -> Optional[Any]:
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (ln,) = struct.unpack(">I", head)
    body = _recv_exact(sock, ln)
    if body is None:
        return None
    return codec.decode(body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf
