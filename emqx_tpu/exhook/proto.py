"""Exhook wire protocol — the ``exhook.proto`` surface
(apps/emqx_exhook/priv/protos/exhook.proto:29-72) over length-prefixed
codec frames.

RPCs (same names/cardinality as the reference's 21-RPC HookProvider
service, plus the TPU-era batch publish):

    OnProviderLoaded(broker)             → {hooks: [hookpoint...]}
    OnProviderUnloaded()
    OnClientConnect/Connack/Connected/Disconnected(...)
    OnClientAuthenticate(clientinfo)     → valued bool
    OnClientAuthorize(clientinfo, action, topic) → valued bool
    OnClientSubscribe/Unsubscribe(...)
    OnSessionCreated/Subscribed/Unsubscribed/Resumed/Discarded/
      Takenover/Terminated(...)
    OnMessagePublish(message)            → valued message (rewrite/drop)
    OnMessagePublishBatch(messages)      → per-message verdicts  [TPU]
    OnMessageDelivered/Acked/Dropped(...)

Responses carry {"type": "CONTINUE" | "STOP_AND_RETURN" | "IGNORE",
"value": ...} — the ValuedResponse of the reference.

WIRE FORMAT (normative — what an external provider must speak)
==============================================================

Transport: one TCP connection per pool slot, provider is the listener.
Framing: every message is::

    +----------------+----------------------------------+
    | uint32 big-end | body: UTF-8 JSON, that many bytes|
    +----------------+----------------------------------+

No TLS at this layer (front it with a TLS proxy if needed). Requests
and responses alternate strictly on one connection (synchronous RPC;
concurrency comes from the pool, one in-flight call per connection —
the same discipline as the reference's per-conn gRPC streams).

Request body::

    {"rpc": "<RpcName>", "args": {...}}

Response body::

    {"type": "CONTINUE" | "STOP_AND_RETURN" | "IGNORE", "value": ...}

`args` payloads mirror exhook.proto messages field-for-field in JSON:
clientinfo {clientid, username, peername, proto_ver}, message {id,
topic, payload, qos, retain, from, timestamp, headers}. Binary fields
(payload) use the codec's tagged encoding: {"$b": "<base64>"}
(cluster/codec.py) — providers must decode/encode that tag.

DESIGN NOTE — two transports: ``ExhookServer(transport="grpc")``
speaks the reference's REAL gRPC ``emqx.exhook.v2.HookProvider``
service (exhook/grpc_transport.py + the hand-written proto codec in
exhook/pbwire.py), so stock providers connect with no adapter. This
framed JSON protocol remains as the dependency-free second transport
(providers in constrained environments; also what exproto gateways
reuse). RPC names, request fields, ValuedResponse semantics, timeout
and failed_action behaviour are identical across both.
"""

from __future__ import annotations

import socket
import struct
from typing import Any, Optional

from emqx_tpu.cluster import codec

# hookpoint name (broker side) → RPC name
HOOK_RPCS = {
    "client.connect": "OnClientConnect",
    "client.connack": "OnClientConnack",
    "client.connected": "OnClientConnected",
    "client.disconnected": "OnClientDisconnected",
    "client.authenticate": "OnClientAuthenticate",
    "client.authorize": "OnClientAuthorize",
    "client.subscribe": "OnClientSubscribe",
    "client.unsubscribe": "OnClientUnsubscribe",
    "session.created": "OnSessionCreated",
    "session.subscribed": "OnSessionSubscribed",
    "session.unsubscribed": "OnSessionUnsubscribed",
    "session.resumed": "OnSessionResumed",
    "session.discarded": "OnSessionDiscarded",
    "session.takenover": "OnSessionTakenover",
    "session.terminated": "OnSessionTerminated",
    "message.publish": "OnMessagePublish",
    "message.delivered": "OnMessageDelivered",
    "message.acked": "OnMessageAcked",
    "message.dropped": "OnMessageDropped",
}
RPC_HOOKS = {v: k for k, v in HOOK_RPCS.items()}

CONTINUE = "CONTINUE"
STOP_AND_RETURN = "STOP_AND_RETURN"
IGNORE = "IGNORE"


def send_frame(sock: socket.socket, obj: Any) -> None:
    body = codec.encode(obj)
    sock.sendall(struct.pack(">I", len(body)) + body)


def recv_frame(sock: socket.socket) -> Optional[Any]:
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (ln,) = struct.unpack(">I", head)
    body = _recv_exact(sock, ln)
    if body is None:
        return None
    return codec.decode(body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf
