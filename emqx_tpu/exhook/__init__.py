"""Out-of-process hooks (SURVEY.md §1 L6) — parity with
``apps/emqx_exhook``: external HookProvider services receive broker
hook events over RPC and may rewrite/deny. The wire is the cluster
codec's length-prefixed framing (the grpc-erl slot; this image carries
no gRPC runtime, the service surface mirrors exhook.proto 1:1)."""
