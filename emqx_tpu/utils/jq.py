"""A jq-subset interpreter — backing for the rule engine's ``jq/2``
(emqx_rule_funcs.erl:806-828, which calls the optional libjq NIF; this
build ships its own evaluator instead of gating the function away).

jq programs are stream transformers: every expression maps one input
value to a *stream* of outputs; ``a | b`` feeds each output of ``a``
through ``b``; ``a, b`` concatenates streams; operators distribute over
the cartesian product of their operand streams. ``jq(prog, json)``
returns the list of all outputs, like the reference's
``jq:process_json/3``.

Supported subset (the jq-manual core):
  identity ``.``   paths ``.a.b``, ``.["k"]``, ``.[0]``, slices
  ``.[1:3]``   iteration ``.[]``   optional ``?``   pipe ``|``
  comma   ``//`` alternative   arithmetic ``+ - * / %``   comparisons
  and/or/not   ``if .. then .. elif .. else .. end``   ``select``
  array/object construction ``[...]`` ``{a: .b, "c", d}``   literals
  builtins: length keys values has type empty not add any all min max
  sort sort_by unique reverse join split map range first last floor
  ceil sqrt abs tostring tonumber tojson fromjson ascii_downcase
  ascii_upcase startswith endswith contains ltrimstr rtrimstr
  to_entries from_entries error

Not supported (raises JqError at parse time): ``def``, ``$vars``/``as``,
``reduce``/``foreach``, ``..``, regex builtins, string interpolation,
``try``/``catch`` (use ``?``), ``label``/``break``.
"""

from __future__ import annotations

import functools
import json
import math
import re
from typing import Any, Callable, Iterator, Optional

Stream = Iterator[Any]
Fn = Callable[[Any], Stream]


class JqError(Exception):
    pass


# ---------------------------------------------------------------------------
# tokenizer

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<num>\d+(\.\d+)?([eE][+-]?\d+)?)
  | (?P<str>"(\\.|[^"\\])*")
  | (?P<op>\.\.|\|=|==|!=|<=|>=|//|[.\[\]{}()|,:;?<>=+\-*/%])
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<var>\$[A-Za-z_][A-Za-z0-9_]*)
""", re.VERBOSE)

_KEYWORDS = {"if", "then", "elif", "else", "end", "and", "or", "not",
             "true", "false", "null", "def", "as", "reduce", "foreach",
             "try", "catch", "label", "import", "include"}


def _tokenize(src: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise JqError(f"jq: bad character {src[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "name" and text in _KEYWORDS:
            kind = "kw"
        out.append((kind, text))
    out.append(("eof", ""))
    return out


# ---------------------------------------------------------------------------
# helpers: jq value semantics


def _truthy(v: Any) -> bool:
    return v is not None and v is not False


def _type(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, list):
        return "array"
    if isinstance(v, dict):
        return "object"
    raise JqError(f"jq: unsupported value {v!r}")


_ORD = {"null": 0, "false": 1, "true": 2, "number": 3, "string": 4,
        "array": 5, "object": 6}


def _sort_key(v: Any):
    """jq total order: null < false < true < numbers < strings < arrays
    < objects."""
    t = _type(v)
    if t == "boolean":
        t = "true" if v else "false"
    rank = _ORD[t]
    if t in ("null", "false", "true"):
        return (rank, 0)
    if t == "array":
        return (rank, [_sort_key(x) for x in v])
    if t == "object":
        return (rank, sorted((k, _sort_key(x)) for k, x in v.items()))
    return (rank, v)


def _cmp(a: Any, b: Any) -> int:
    ka, kb = _sort_key(a), _sort_key(b)
    return -1 if ka < kb else (1 if ka > kb else 0)


def _add(a: Any, b: Any) -> Any:
    if a is None:
        return b
    if b is None:
        return a
    if isinstance(a, bool) or isinstance(b, bool):
        raise JqError("jq: booleans cannot be added")
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a + b
    if isinstance(a, str) and isinstance(b, str):
        return a + b
    if isinstance(a, list) and isinstance(b, list):
        return a + b
    if isinstance(a, dict) and isinstance(b, dict):
        return {**a, **b}
    raise JqError(f"jq: {_type(a)} and {_type(b)} cannot be added")


def _arith(op: str, a: Any, b: Any) -> Any:
    if op == "+":
        return _add(a, b)
    if op == "-":
        if isinstance(a, list) and isinstance(b, list):
            return [x for x in a if x not in b]
        if _num2(a, b):
            return a - b
    if op == "*":
        if _num2(a, b):
            return a * b
        if isinstance(a, dict) and isinstance(b, dict):
            return _deep_merge(a, b)
    if op == "/":
        if _num2(a, b):
            if b == 0:
                raise JqError("jq: division by zero")
            # exact integer quotients stay integers (jq prints 6/2 as 3)
            if isinstance(a, int) and isinstance(b, int) and a % b == 0:
                return a // b
            return a / b
        if isinstance(a, str) and isinstance(b, str):
            if not b:
                raise JqError("jq: cannot split by empty string")
            return a.split(b)
    if op == "%":
        if _num2(a, b):
            if int(b) == 0:
                raise JqError("jq: division by zero")
            return int(math.fmod(int(a), int(b)))
    raise JqError(f"jq: {_type(a)} {op} {_type(b)} is not defined")


def _num2(a, b) -> bool:
    return (isinstance(a, (int, float)) and not isinstance(a, bool) and
            isinstance(b, (int, float)) and not isinstance(b, bool))


def _deep_merge(a: dict, b: dict) -> dict:
    out = dict(a)
    for k, v in b.items():
        if isinstance(out.get(k), dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _index(v: Any, key: Any, opt: bool) -> Stream:
    try:
        if v is None:
            yield None
        elif isinstance(v, dict):
            if not isinstance(key, str):
                raise JqError(f"jq: cannot index object with {_type(key)}")
            yield v.get(key)
        elif isinstance(v, list):
            if isinstance(key, bool) or not isinstance(key, (int, float)):
                raise JqError(f"jq: cannot index array with {_type(key)}")
            i = int(key)
            n = len(v)
            if i < 0:
                i += n
            yield v[i] if 0 <= i < n else None
        else:
            raise JqError(f"jq: cannot index {_type(v)}")
    except JqError:
        if not opt:
            raise


def _iterate(v: Any, opt: bool) -> Stream:
    if isinstance(v, list):
        yield from v
    elif isinstance(v, dict):
        yield from v.values()
    elif not opt:
        raise JqError(f"jq: cannot iterate over {_type(v)}")


# ---------------------------------------------------------------------------
# builtins: name -> (n_args, fn(input, *compiled_args) -> stream)


def _b_simple(fn):
    return lambda v: iter([fn(v)])


def _length(v):
    if v is None:
        return 0
    if isinstance(v, bool):
        raise JqError("jq: boolean has no length")
    if isinstance(v, (int, float)):
        return abs(v)
    return len(v)


def _keys(v):
    if isinstance(v, dict):
        return sorted(v.keys())
    if isinstance(v, list):
        return list(range(len(v)))
    raise JqError(f"jq: {_type(v)} has no keys")


def _tonumber(v):
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v
    if isinstance(v, str):
        try:
            return int(v)
        except ValueError:
            try:
                return float(v)
            except ValueError:
                raise JqError(f"jq: cannot parse {v!r} as number") from None
    raise JqError(f"jq: cannot parse {_type(v)} as number")


def _tostring(v):
    return v if isinstance(v, str) else json.dumps(v)


def _expect(v, t, what: str):
    if isinstance(v, bool) or not isinstance(v, t):
        names = (t.__name__ if isinstance(t, type)
                 else "/".join(x.__name__ for x in t))
        raise JqError(f"jq: {what} requires {names}, got {_type(v)}")
    return v


_BUILTINS_0: dict[str, Callable[[Any], Stream]] = {
    "length": _b_simple(_length),
    "keys": _b_simple(_keys),
    "values": lambda v: iter(() if v is None else (v,)),   # select(.!=null)
    "type": _b_simple(_type),
    "not": _b_simple(lambda v: not _truthy(v)),
    "empty": lambda v: iter(()),
    "add": _b_simple(lambda v: _fold_add(v)),
    "floor": _b_simple(lambda v: math.floor(_expect(v, (int, float),
                                                    "floor"))),
    "ceil": _b_simple(lambda v: math.ceil(_expect(v, (int, float),
                                                  "ceil"))),
    "sqrt": _b_simple(lambda v: math.sqrt(_expect(v, (int, float),
                                                  "sqrt"))),
    "abs": _b_simple(lambda v: abs(_expect(v, (int, float), "abs"))),
    "sort": _b_simple(lambda v: sorted(_expect(v, list, "sort"),
                                       key=_sort_key)),
    "unique": _b_simple(lambda v: _unique(_expect(v, list, "unique"))),
    "reverse": _b_simple(lambda v: list(reversed(
        _expect(v, list, "reverse")))),
    "min": _b_simple(lambda v: min(_expect(v, list, "min"),
                                   key=_sort_key, default=None)),
    "max": _b_simple(lambda v: max(_expect(v, list, "max"),
                                   key=_sort_key, default=None)),
    "tostring": _b_simple(_tostring),
    "tonumber": _b_simple(_tonumber),
    "tojson": _b_simple(lambda v: json.dumps(v)),
    "fromjson": _b_simple(lambda v: json.loads(
        _expect(v, str, "fromjson"))),
    "ascii_downcase": _b_simple(lambda v: _expect(v, str,
                                                  "ascii_downcase").lower()),
    "ascii_upcase": _b_simple(lambda v: _expect(v, str,
                                                "ascii_upcase").upper()),
    "to_entries": _b_simple(lambda v: [
        {"key": k, "value": x}
        for k, x in _expect(v, dict, "to_entries").items()]),
    "from_entries": _b_simple(lambda v: {
        str(e.get("key", e.get("k", e.get("name")))):
            e.get("value", e.get("v"))
        for e in _expect(v, list, "from_entries")}),
    # first = .[0], last = .[-1] (jq defs): empty array yields null
    "first": lambda v: iter([_expect(v, list, "first")[0] if v else None]),
    "last": lambda v: iter([_expect(v, list, "last")[-1] if v else None]),
}


def _fold_add(v):
    if not isinstance(v, list):
        raise JqError("jq: add requires array")
    out = None
    for x in v:
        out = _add(out, x)
    return out


def _unique(v: list) -> list:
    out: list = []
    for x in sorted(v, key=_sort_key):
        if not out or _cmp(out[-1], x) != 0:
            out.append(x)
    return out


def _b1_value(name: str, fn):
    """Builtin whose single argument is evaluated against the SAME
    input, distributing over its stream."""
    def run(v, arg: Fn) -> Stream:
        for a in arg(v):
            yield fn(v, a)
    return run


_BUILTINS_1: dict[str, Callable[[Any, Fn], Stream]] = {
    "has": _b1_value("has", lambda v, k:
                     (k in v) if isinstance(v, dict)
                     else (isinstance(k, int) and 0 <= k < len(v))
                     if isinstance(v, list)
                     else _raise(f"jq: {_type(v)} has no keys")),
    "join": _b1_value("join", lambda v, s: _expect(s, str, "join").join(
        "" if x is None else (x if isinstance(x, str) else json.dumps(x))
        for x in _expect(v, list, "join"))),
    "split": _b1_value("split", lambda v, s:
                       _expect(v, str, "split").split(
                           _expect(s, str, "split"))),
    "startswith": _b1_value("startswith", lambda v, p:
                            _expect(v, str, "startswith").startswith(
                                _expect(p, str, "startswith"))),
    "endswith": _b1_value("endswith", lambda v, p:
                          _expect(v, str, "endswith").endswith(
                              _expect(p, str, "endswith"))),
    "ltrimstr": _b1_value("ltrimstr", lambda v, p:
                          v[len(p):] if isinstance(v, str)
                          and isinstance(p, str) and v.startswith(p) else v),
    "rtrimstr": _b1_value("rtrimstr", lambda v, p:
                          v[:-len(p)] if isinstance(v, str)
                          and isinstance(p, str) and p and v.endswith(p)
                          else v),
    "contains": _b1_value("contains", lambda v, x: _contains(v, x)),
    "error": _b1_value("error", lambda v, m: _raise(f"jq: error: {m}")),
}


def _raise(msg: str):
    raise JqError(msg)


def _contains(v, x) -> bool:
    if isinstance(v, str) and isinstance(x, str):
        return x in v
    if isinstance(v, list) and isinstance(x, list):
        return all(any(_contains(a, b) for a in v) for b in x)
    if isinstance(v, dict) and isinstance(x, dict):
        return all(k in v and _contains(v[k], b) for k, b in x.items())
    return _cmp(v, x) == 0


# filter-argument builtins (argument runs per element / as predicate)

def _b_select(v, f: Fn) -> Stream:
    for t in f(v):
        if _truthy(t):
            yield v


def _b_map(v, f: Fn) -> Stream:
    out = []
    for x in _expect(v, list, "map"):
        out.extend(f(x))
    yield out


def _b_sort_by(v, f: Fn) -> Stream:
    yield sorted(_expect(v, list, "sort_by"),
                 key=lambda x: _sort_key(next(f(x), None)))


def _b_any(v, f: Fn) -> Stream:
    yield any(_truthy(t) for x in _expect(v, list, "any") for t in f(x))


def _b_all(v, f: Fn) -> Stream:
    yield all(_truthy(t) for x in _expect(v, list, "all") for t in f(x))


def _b_range(v, f: Fn) -> Stream:
    for n in f(v):
        yield from range(int(n))


_BUILTINS_F: dict[str, Callable[[Any, Fn], Stream]] = {
    "select": _b_select, "map": _b_map, "sort_by": _b_sort_by,
    "any": _b_any, "all": _b_all, "range": _b_range,
}


def _guard(fn: Fn) -> Fn:
    """Builtins must fail with JqError only — a ValueError out of
    fromjson/sqrt/split would escape `?` and `//` error suppression."""
    def run(v, fn=fn):
        try:
            yield from fn(v)
        except JqError:
            raise
        except (ValueError, TypeError, AttributeError, KeyError,
                ArithmeticError) as e:
            raise JqError(f"jq: {e}") from e
    return run


# ---------------------------------------------------------------------------
# parser → compiled closures (each: Fn = input -> stream)


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]) -> None:
        self.toks = tokens
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, text: str) -> bool:
        if self.toks[self.i][1] == text and self.toks[self.i][0] != "str":
            self.i += 1
            return True
        return False

    def expect(self, text: str) -> None:
        if not self.accept(text):
            k, t = self.peek()
            raise JqError(f"jq: expected {text!r}, got {t!r}")

    # pipe (lowest precedence)
    def parse_pipe(self) -> Fn:
        left = self.parse_comma()
        if self.accept("|"):
            right = self.parse_pipe()

            def run(v, left=left, right=right):
                for a in left(v):
                    yield from right(a)
            return run
        return left

    def parse_comma(self) -> Fn:
        parts = [self.parse_alt()]
        while self.accept(","):
            parts.append(self.parse_alt())
        if len(parts) == 1:
            return parts[0]

        def run(v, parts=parts):
            for p in parts:
                yield from p(v)
        return run

    def parse_alt(self) -> Fn:
        left = self.parse_or()
        if self.accept("//"):
            right = self.parse_alt()

            def run(v, left=left, right=right):
                got = False
                try:
                    for a in left(v):
                        if _truthy(a):
                            got = True
                            yield a
                except JqError:
                    pass
                if not got:
                    yield from right(v)
            return run
        return left

    def _binop(self, sub, ops: tuple, apply) -> Fn:
        left = sub()
        while self.peek()[1] in ops and self.peek()[0] in ("op", "kw"):
            op = self.next()[1]
            right = sub()

            def run(v, left=left, right=right, op=op):
                for b in right(v):       # jq evaluates rhs first
                    for a in left(v):
                        yield apply(op, a, b)
            left = run
        return left

    def _shortcircuit(self, sub, op_name: str, stop_on: bool) -> Fn:
        """jq and/or: left first, rhs only evaluated when needed —
        `false and error` is false, not an error."""
        left = sub()
        while self.peek() == ("kw", op_name):
            self.next()
            right = sub()

            def run(v, left=left, right=right, stop_on=stop_on):
                for a in left(v):
                    if _truthy(a) is stop_on:
                        yield stop_on
                    else:
                        for b in right(v):
                            yield _truthy(b)
            left = run
        return left

    def parse_or(self) -> Fn:
        return self._shortcircuit(self.parse_and, "or", stop_on=True)

    def parse_and(self) -> Fn:
        return self._shortcircuit(self.parse_cmp, "and", stop_on=False)

    _CMP = {"==": lambda c: c == 0, "!=": lambda c: c != 0,
            "<": lambda c: c < 0, "<=": lambda c: c <= 0,
            ">": lambda c: c > 0, ">=": lambda c: c >= 0}

    def parse_cmp(self) -> Fn:
        return self._binop(
            self.parse_add, tuple(self._CMP),
            lambda op, a, b: self._CMP[op](_cmp(a, b)))

    def parse_add(self) -> Fn:
        return self._binop(self.parse_mul, ("+", "-"), _arith)

    def parse_mul(self) -> Fn:
        return self._binop(self.parse_unary, ("*", "/", "%"), _arith)

    def parse_unary(self) -> Fn:
        if self.accept("-"):
            inner = self.parse_postfix()

            def run(v, inner=inner):
                for a in inner(v):
                    if isinstance(a, bool) or not isinstance(a, (int, float)):
                        raise JqError(f"jq: {_type(a)} cannot be negated")
                    yield -a
            return run
        return self.parse_postfix()

    # postfix: primary followed by .foo  [..]  []  ?
    def parse_postfix(self) -> Fn:
        fn = self.parse_primary()
        while True:
            if self.peek()[1] == "." and self.toks[self.i + 1][0] == "name":
                self.next()
                name = self.next()[1]
                # default-arg binding: a loop-captured `name` would make
                # every segment of .a.b.c index with the LAST name
                fn = self._chain_index(fn, lambda v, s=name: iter([s]))
            elif self.accept("["):
                fn = self._bracket(fn)
            elif self.accept("?"):
                fn = self._optional(fn)
            else:
                return fn

    @staticmethod
    def _optional(fn: Fn) -> Fn:
        def run(v, fn=fn):
            try:
                yield from fn(v)
            except JqError:
                return
        return run

    @staticmethod
    def _chain_index(fn: Fn, keyf: Fn) -> Fn:
        def run(v, fn=fn, keyf=keyf):
            for a in fn(v):
                for k in keyf(v):
                    yield from _index(a, k, opt=False)
        return run

    def _bracket(self, fn: Fn) -> Fn:
        """``[...]`` after an expression: iterate, index, or slice."""
        if self.accept("]"):
            def run(v, fn=fn):
                for a in fn(v):
                    yield from _iterate(a, opt=False)
            return run
        lo: Optional[Fn] = None
        hi: Optional[Fn] = None
        if not self.peek()[1] == ":":
            lo = self.parse_pipe()
        if self.accept(":"):
            if self.peek()[1] != "]":
                hi = self.parse_pipe()
            self.expect("]")

            def run(v, fn=fn, lo=lo, hi=hi):
                for a in fn(v):
                    los = lo(v) if lo else iter([None])
                    for lov in los:
                        his = hi(v) if hi else iter([None])
                        for hiv in his:
                            if a is None:        # .x[0:2] on null → null
                                yield None
                                continue
                            if not isinstance(a, (list, str)):
                                raise JqError(
                                    f"jq: cannot slice {_type(a)}")
                            s = slice(
                                None if lov is None else int(lov),
                                None if hiv is None else int(hiv))
                            yield a[s]
            return run
        self.expect("]")

        def run(v, fn=fn, lo=lo):
            for a in fn(v):
                for k in lo(v):
                    yield from _index(a, k, opt=False)
        return run

    def parse_primary(self) -> Fn:
        kind, text = self.peek()
        if text == "(":
            self.next()
            inner = self.parse_pipe()
            self.expect(")")
            return inner
        if text == ".":
            self.next()
            # .name / ."k" here; .[...] postfix picks up from identity
            if self.peek()[0] == "name":
                name = self.next()[1]
                return self._chain_index(lambda v: iter([v]),
                                         lambda v, s=name: iter([s]))
            if self.peek()[0] == "str":
                s = json.loads(self.next()[1])
                return self._chain_index(lambda v: iter([v]),
                                         lambda v, s=s: iter([s]))
            return lambda v: iter([v])
        if text == "..":
            raise JqError("jq: recursive descent (..) not supported")
        if kind == "num":
            self.next()
            n = float(text) if ("." in text or "e" in text.lower()) \
                else int(text)
            return lambda v, n=n: iter([n])
        if kind == "str":
            if "\\(" in text:
                raise JqError("jq: string interpolation not supported")
            try:
                s = json.loads(text)
            except ValueError as e:
                raise JqError(f"jq: bad string literal {text}") from e
            self.next()
            return lambda v, s=s: iter([s])
        if kind == "var":
            raise JqError("jq: variables ($x) not supported")
        if kind == "kw":
            return self._keyword()
        if text == "[":
            self.next()
            if self.accept("]"):
                return lambda v: iter([[]])
            inner = self.parse_pipe()
            self.expect("]")
            return lambda v, inner=inner: iter([list(inner(v))])
        if text == "{":
            return self._object()
        if kind == "name":
            return self._call()
        raise JqError(f"jq: unexpected token {text!r}")

    def _keyword(self) -> Fn:
        _kind, text = self.next()
        if text in ("true", "false", "null"):
            lit = {"true": True, "false": False, "null": None}[text]
            return lambda v, lit=lit: iter([lit])
        if text == "not":
            return _BUILTINS_0["not"]
        if text == "if":
            cond = self.parse_pipe()
            self.expect("then")
            then = self.parse_pipe()
            branches = [(cond, then)]
            while self.accept("elif"):
                c = self.parse_pipe()
                self.expect("then")
                branches.append((c, self.parse_pipe()))
            els = self.parse_pipe() if self.accept("else") \
                else (lambda v: iter([v]))
            self.expect("end")

            def run(v, branches=branches, els=els):
                def descend(k: int) -> Stream:
                    if k == len(branches):
                        yield from els(v)
                        return
                    cond, then = branches[k]
                    for t in cond(v):
                        if _truthy(t):
                            yield from then(v)
                        else:
                            yield from descend(k + 1)
                yield from descend(0)
            return run
        raise JqError(f"jq: {text!r} not supported")

    def _object(self) -> Fn:
        self.expect("{")
        fields: list[tuple[Fn, Optional[Fn]]] = []
        if not self.accept("}"):
            while True:
                kind, text = self.peek()
                if kind in ("name", "kw"):
                    self.next()
                    keyf: Fn = (lambda v, s=text: iter([s]))
                elif kind == "str":
                    self.next()
                    keyf = (lambda v, s=json.loads(text): iter([s]))
                elif self.accept("("):
                    keyf = self.parse_pipe()
                    self.expect(")")
                else:
                    raise JqError(f"jq: bad object key {text!r}")
                valf = self.parse_alt() if self.accept(":") else None
                fields.append((keyf, valf))
                if not self.accept(","):
                    break
            self.expect("}")

        def run(v, fields=fields):
            def descend(k: int, acc: dict) -> Stream:
                if k == len(fields):
                    yield dict(acc)
                    return
                keyf, valf = fields[k]
                for key in keyf(v):
                    if not isinstance(key, str):
                        raise JqError("jq: object key must be string")
                    vals = (valf(v) if valf is not None
                            else _index(v, key, opt=False))
                    had, old = key in acc, acc.get(key)
                    for val in vals:
                        acc[key] = val
                        yield from descend(k + 1, acc)
                    if had:          # backtrack: {("a","b"): 1} must not
                        acc[key] = old       # leak "a" into the "b" object
                    else:
                        acc.pop(key, None)
            yield from descend(0, {})
        return run

    def _call(self) -> Fn:
        name = self.next()[1]
        args: list[Fn] = []
        if self.accept("("):
            args.append(self.parse_pipe())
            while self.accept(";"):
                args.append(self.parse_pipe())
            self.expect(")")
        if not args and name in _BUILTINS_0:
            return _guard(_BUILTINS_0[name])
        if len(args) == 1 and name in _BUILTINS_F:
            f = _BUILTINS_F[name]
            return _guard(lambda v, f=f, a=args[0]: f(v, a))
        if len(args) == 1 and name in _BUILTINS_1:
            f = _BUILTINS_1[name]
            return _guard(lambda v, f=f, a=args[0]: f(v, a))
        raise JqError(f"jq: unknown function {name}/{len(args)}")


@functools.lru_cache(maxsize=256)
def compile_program(src: str) -> Fn:
    """Compiled programs are stateless closures — cached so jq/2 on the
    per-message rule hot path compiles each program once."""
    p = _Parser(_tokenize(src))
    fn = p.parse_pipe()
    if p.peek()[0] != "eof":
        raise JqError(f"jq: trailing input at token {p.peek()[1]!r}")
    return fn


def jq(program: str, value: Any) -> list:
    """Run a jq program; returns the list of ALL outputs.

    ``value`` is an already-decoded term, with one exception: bytes are
    a JSON document (invalid JSON errors). A ``str`` is ALWAYS a plain
    string term — never sniffed as JSON text, so ``jq(".", "0")`` is
    ``["0"]``, not ``[0]``. The reference-semantics seam (SQL values
    are binaries holding JSON text, emqx_rule_funcs.erl:806-828) lives
    in rules/funcs.py:_jq, which decodes str/bytes before calling here."""
    if isinstance(value, (bytes, bytearray)):
        try:
            value = json.loads(value.decode("utf-8"))
        except ValueError as e:
            raise JqError(f"jq: invalid JSON input: {e}") from None
    return list(compile_program(program)(value))
