"""Disk-backed FIFO queue with at-least-once ack — the ``replayq`` dep.

The reference buffers bridge traffic (emqx_resource_worker.erl:17-18,164)
and MQTT-bridge egress through replayq: a segmented on-disk log with an
ack pointer, so queued items survive restarts and are replayed after a
crash. Same contract here:

- ``append(items)``      durably appends binary items
- ``pop(n)``             returns ``(ack_ref, items)`` without consuming
- ``ack(ack_ref)``       commits consumption up to that point
- reopening a dir resumes from the last committed ack

Layout: ``<dir>/<segno>.seg`` files of length-prefixed records, plus
``<dir>/ack`` holding "segno itemidx" of the committed read position.
Segments roll at ``seg_bytes``; fully-acked segments are deleted.
Per-segment item counts are tracked in memory so an ack is pure
arithmetic + at most a few unlinks (no re-reading of segment files).
``mem_only=True`` keeps everything in RAM (the reference's
``mem_only`` mode) for tests and low-durability buffers.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Optional

_LEN = struct.Struct("<I")


class ReplayQ:
    def __init__(self, dir: Optional[str] = None, *, mem_only: bool = False,
                 seg_bytes: int = 4 * 1024 * 1024,
                 max_total_bytes: int = 0) -> None:
        self.mem_only = mem_only or dir is None
        self.seg_bytes = seg_bytes
        self.max_total_bytes = max_total_bytes     # 0 = unlimited
        self._lock = threading.RLock()
        self._items: list[bytes] = []     # unacked tail, in order
        self._bytes = 0
        self.dropped = 0
        if self.mem_only:
            self.dir = None
            return
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        # surviving segments in order: [segno, full_item_count]; the ack
        # index counts consumed items within the FIRST one
        self._segments: list[list[int]] = []
        self._ack_idx = 0
        self._load()

    # -- persistence ---------------------------------------------------------

    def _seg_path(self, segno: int) -> str:
        return os.path.join(self.dir, f"{segno:010d}.seg")

    def _load(self) -> None:
        ack_seg, ack_idx = 0, 0
        ack_path = os.path.join(self.dir, "ack")
        if os.path.exists(ack_path):
            with open(ack_path) as f:
                parts = f.read().split()
                if len(parts) == 2:
                    ack_seg, ack_idx = int(parts[0]), int(parts[1])
        segs = sorted(
            int(f[:-4]) for f in os.listdir(self.dir) if f.endswith(".seg")
        )
        self._write_seg = max(segs[-1] if segs else 0, ack_seg)
        for segno in segs:
            if segno < ack_seg:
                os.unlink(self._seg_path(segno))    # fully consumed
                continue
            items = self._read_seg(segno)
            skip = ack_idx if segno == ack_seg else 0
            self._segments.append([segno, len(items)])
            for item in items[skip:]:
                self._items.append(item)
                self._bytes += len(item)
        self._ack_idx = ack_idx if self._segments and \
            self._segments[0][0] == ack_seg else 0

    def _read_seg(self, segno: int) -> list[bytes]:
        out = []
        try:
            with open(self._seg_path(segno), "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return out
        off = 0
        while off + 4 <= len(data):
            (n,) = _LEN.unpack_from(data, off)
            if off + 4 + n > len(data):
                break                              # torn tail write — drop
            out.append(data[off + 4:off + 4 + n])
            off += 4 + n
        return out

    def _append_disk(self, items: list[bytes]) -> None:
        path = self._seg_path(self._write_seg)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size >= self.seg_bytes:
            self._write_seg += 1
            path = self._seg_path(self._write_seg)
        with open(path, "ab") as f:
            for item in items:
                f.write(_LEN.pack(len(item)) + item)
            f.flush()
            os.fsync(f.fileno())
        if self._segments and self._segments[-1][0] == self._write_seg:
            self._segments[-1][1] += len(items)
        else:
            self._segments.append([self._write_seg, len(items)])

    def _commit_ack(self) -> None:
        """Advance the persisted read position; unlink drained segments."""
        consumed = self._ack_idx
        while self._segments:
            segno, count = self._segments[0]
            if consumed >= count:
                consumed -= count
                try:
                    os.unlink(self._seg_path(segno))
                except OSError:
                    pass
                self._segments.pop(0)
            else:
                break
        self._ack_idx = consumed
        if self._segments:
            ack_seg = self._segments[0][0]
        else:
            # queue fully drained: future appends must start at/after the
            # ack point or reopen would discard them as consumed
            ack_seg = self._write_seg = self._write_seg + 1
        with open(os.path.join(self.dir, "ack"), "w") as f:
            f.write(f"{ack_seg} {self._ack_idx}")

    # -- queue API -----------------------------------------------------------

    def append(self, items: list[bytes]) -> int:
        """Append items; returns how many were accepted (overflow drops
        the *new* items, matching replayq's max_total_bytes policy)."""
        with self._lock:
            accepted = []
            for item in items:
                if (self.max_total_bytes
                        and self._bytes + len(item) > self.max_total_bytes):
                    self.dropped += 1
                    continue
                accepted.append(item)
                self._bytes += len(item)
            self._items.extend(accepted)
            if accepted and not self.mem_only:
                self._append_disk(accepted)
            return len(accepted)

    def pop(self, n: int = 1) -> tuple[int, list[bytes]]:
        """Peek the first n items. The ack_ref is the count to pass to
        ``ack`` once the items are safely handled."""
        with self._lock:
            items = self._items[:n]
            return len(items), list(items)

    def ack(self, ack_ref: int) -> None:
        with self._lock:
            done = self._items[:ack_ref]
            self._items = self._items[ack_ref:]
            self._bytes -= sum(len(i) for i in done)
            if not self.mem_only and ack_ref:
                self._ack_idx += ack_ref
                self._commit_ack()

    def count(self) -> int:
        with self._lock:
            return len(self._items)

    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def is_empty(self) -> bool:
        return self.count() == 0

    def close(self) -> None:
        pass
