"""Snappy block-format codec — the ``snappyer`` NIF analogue
(SURVEY.md §2.4: C NIFs via wolff→kafka_protocol for Kafka batch
compression).

Two implementations of the same wire format
(google/snappy format_description.txt):

- the C++ one in ``native/src/snappy.cc`` (preferred — built into
  libemqx_native.so on demand, sanitizer-covered with the host);
- a pure-Python greedy matcher/decoder here, used when no compiler is
  available, and as the differential oracle in tests.

Both produce valid streams (they need not be byte-identical — snappy
is a format, not a canonical encoding); decompress accepts any
spec-conformant stream.
"""

from __future__ import annotations

import ctypes
import struct


class SnappyError(Exception):
    pass


# ---------------------------------------------------------------------------
# pure-Python implementation


def _varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    n = shift = 0
    while True:
        if pos >= len(data) or shift > 32:
            raise SnappyError("bad length varint")
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not (b & 0x80):
            return n, pos
        shift += 7


def _emit_literal(out: bytearray, chunk: bytes) -> None:
    n = len(chunk) - 1
    if n < 60:
        out.append(n << 2)
    else:
        nb = (n.bit_length() + 7) // 8
        out.append((59 + nb) << 2)
        out += n.to_bytes(nb, "little")
    out += chunk


def _emit_copy(out: bytearray, offset: int, length: int) -> None:
    while length > 64:
        _emit_copy(out, offset, 60)      # keep every chunk >= 4
        length -= 60
    if length <= 11 and offset < 2048:
        out.append(0x01 | ((length - 4) << 2) | ((offset >> 8) << 5))
        out.append(offset & 0xFF)
    elif offset < (1 << 16):
        out.append(0x02 | ((length - 1) << 2))
        out += struct.pack("<H", offset)
    else:
        out.append(0x03 | ((length - 1) << 2))
        out += struct.pack("<I", offset)


def py_compress(data: bytes) -> bytes:
    n = len(data)
    out = bytearray(_varint(n))
    if n == 0:
        return bytes(out)
    table: dict[bytes, int] = {}
    i = lit = 0
    while i + 4 <= n:
        four = data[i:i + 4]
        cand = table.get(four)
        table[four] = i
        if cand is None:
            i += 1
            continue
        length = 4
        while i + length < n and data[cand + length] == data[i + length]:
            length += 1
        # only cost-effective copies (mirrors snappy.cc): a 5-byte copy4
        # tag for a short far match would expand the stream
        if i - cand >= (1 << 16) and length < 8:
            i += 1
            continue
        if lit < i:
            _emit_literal(out, data[lit:i])
        _emit_copy(out, i - cand, length)
        i += length
        lit = i
    if lit < n:
        _emit_literal(out, data[lit:])
    return bytes(out)


def py_decompress(data: bytes) -> bytes:
    total, pos = _read_varint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:                          # literal
            length = (tag >> 2) + 1
            if length > 60:
                nb = length - 60
                if pos + nb > n:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(data[pos:pos + nb], "little") + 1
                pos += nb
            if pos + length > n:
                raise SnappyError("truncated literal")
            out += data[pos:pos + length]
            pos += length
            continue
        if kind == 1:
            if pos + 1 > n:
                raise SnappyError("truncated copy1")
            length = ((tag >> 2) & 0x07) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:
            if pos + 2 > n:
                raise SnappyError("truncated copy2")
            length = (tag >> 2) + 1
            (offset,) = struct.unpack_from("<H", data, pos)
            pos += 2
        else:
            if pos + 4 > n:
                raise SnappyError("truncated copy4")
            length = (tag >> 2) + 1
            (offset,) = struct.unpack_from("<I", data, pos)
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError("copy offset out of range")
        for _ in range(length):                # overlap-replicating copy
            out.append(out[-offset])
    if len(out) != total:
        raise SnappyError(
            f"length mismatch: header {total}, decoded {len(out)}")
    return bytes(out)


# ---------------------------------------------------------------------------
# native dispatch


def _native():
    from emqx_tpu import native
    return native.load()


def compress(data: bytes) -> bytes:
    lib = _native()
    if lib is None:
        return py_compress(data)
    cap = lib.emqx_snappy_max_compressed(len(data))
    dst = ctypes.create_string_buffer(cap)
    written = lib.emqx_snappy_compress(data, len(data), dst, cap)
    if written < 0:       # capacity bound hit (pathological input):
        return py_compress(data)     # the Python emitter can't overflow
    return dst.raw[:written]


# a snappy stream cannot expand more than ~21x (best op: a 64-byte copy
# from a 3-byte tag) — cap the attacker-controlled header length before
# allocating the output buffer (64x leaves generous slack)
_MAX_EXPANSION = 64


def decompress(data: bytes) -> bytes:
    lib = _native()
    if lib is None:
        return py_decompress(data)
    total = lib.emqx_snappy_uncompressed_length(data, len(data))
    if total < 0:
        raise SnappyError("bad length varint")
    if total > max(len(data), 16) * _MAX_EXPANSION:
        raise SnappyError(
            f"implausible uncompressed length {total} "
            f"for {len(data)} input bytes")
    dst = ctypes.create_string_buffer(max(total, 1))
    written = lib.emqx_snappy_decompress(data, len(data), dst, total)
    if written < 0:
        raise SnappyError("malformed snappy stream")
    if written != total:
        raise SnappyError(
            f"length mismatch: header {total}, decoded {written}")
    return dst.raw[:written]
