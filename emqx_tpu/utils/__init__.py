"""Host-side utility kit (the reference's replayq / emqx_misc corner)."""
