"""Batched wildcard-trie match on device — the north-star kernel.

Replaces the reference's per-message trie walk (``emqx_trie:match/1``,
emqx_trie.erl:282-344 — one ETS lookup per topic level, ×2 at '+'/'#'
branches) with one XLA program matching a whole ``[B, L]`` batch of
tokenized topics against the HBM-resident flat trie of
``emqx_tpu.router.index.TrieIndex``.

Algorithm: K-capped frontier walk. The frontier at step *i* holds the trie
nodes whose path matches the first *i* topic words (≤K of them; K bounds
the number of simultaneously-alive wildcard branches, overflow is reported
so the host oracle can take over for that topic). Each scan step does:

1. emit ``hash_fid`` of every frontier node (a ``prefix/#`` filter matches
   any remaining suffix, including the empty one);
2. at end-of-topic, emit ``node_fid`` (filters ending exactly here);
3. advance: exact child via ≤``max_probes`` linear probes of the edge hash
   table + ``+`` child, then pack the ≤2K candidates back into K slots.

Every matching filter id is emitted exactly once per topic (tree-ness of
the trie — see index.py), so the output needs masking but no dedup.

All control flow is static (lax.scan over L+1 steps, unrolled probe loop):
no data-dependent shapes, everything fuses into gathers + elementwise ops —
HBM-bandwidth-bound, which is the right regime for this workload.

Pallas note (evaluated, intentionally not used here): every hot op in this
kernel is a scattered row/element gather from HBM-resident tables indexed
by data-dependent lanes. Pallas-TPU expresses gathers as either per-block
DMAs (grid step per row — B·K·probes steps ≈ 10^6 latency-bound DMAs per
batch) or VMEM-resident tables (the 1M-filter trie is ~25MB+, over VMEM).
XLA's native gather lowering with the optimization-barrier placement below
is the fast path (measured: 0.03ms/batch at 1M filters); the pipeline-level
win instead comes from overlapping dispatch (see bench.py window).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu.router.index import HASH_ID, PAD, TrieIndexArrays

# plain Python ints: module-level jnp scalars are concrete device arrays,
# and closure-captured device arrays inside a scan body hit a catastrophic
# slow path on TPU (measured ~400ms vs 0.03ms for the same probe loop)
_MIX_A = 0x9E3779B1
_MIX_B = 0x85EBCA77

# kernel-plane observability (ISSUE 18): the per-batch counters vector's
# field order, declared ONCE here — observe/device_metrics.py carries a
# literal copy the counters-layout lint (tests/test_kernel_counters_lint
# .py) holds in parity, so the in-kernel packer and the host decoder
# cannot drift. Flat layout packs to [C]; the sharded step packs [S, C]
# (one row per trie shard). All int32, computed alongside the match with
# elementwise reductions only — no extra device sync, no data-dependent
# shapes.
KERNEL_COUNTER_FIELDS = (
    "frontier_peak",   # max per-topic frontier occupancy over all steps (≤K)
    "probe_iters",     # total live edge-hash probe-loop iterations
    "cand_pre",        # valid candidate fids before the M compact
    "cand_post",       # candidate fids surviving the M compact
    "compact_peak",    # max per-topic compact-slot occupancy (M utilization)
    "overflow_rows",   # topics whose K frontier spilled (incomplete match)
    "trunc_rows",      # topics truncated by the M compact
)


def pack_counters(**fields) -> jax.Array:
    """Stack the named counter values in KERNEL_COUNTER_FIELDS order.

    Scalars pack to ``[C]``; per-shard ``[S]`` vectors pack to
    ``[S, C]``.  Keyword-only so a caller can never silently permute
    the layout — order lives in one place.
    """
    if set(fields) != set(KERNEL_COUNTER_FIELDS):
        missing = set(KERNEL_COUNTER_FIELDS) - set(fields)
        extra = set(fields) - set(KERNEL_COUNTER_FIELDS)
        raise TypeError(
            f"pack_counters field mismatch: missing={sorted(missing)} "
            f"extra={sorted(extra)}")
    vals = [jnp.asarray(fields[n], jnp.int32)
            for n in KERNEL_COUNTER_FIELDS]
    return jnp.stack(jnp.broadcast_arrays(*vals), axis=-1)


class DeviceTrie(NamedTuple):
    """TrieIndexArrays uploaded to device (a jit-friendly pytree)."""

    ht_parent: jax.Array   # [H] int32, -1 = empty slot
    ht_word: jax.Array     # [H]
    ht_child: jax.Array    # [H]
    plus_child: jax.Array  # [N]
    hash_fid: jax.Array    # [N]
    node_fid: jax.Array    # [N]


def device_trie(arrays: TrieIndexArrays) -> DeviceTrie:
    return DeviceTrie(
        ht_parent=jnp.asarray(arrays.ht_parent),
        ht_word=jnp.asarray(arrays.ht_word),
        ht_child=jnp.asarray(arrays.ht_child),
        plus_child=jnp.asarray(arrays.plus_child),
        hash_fid=jnp.asarray(arrays.hash_fid),
        node_fid=jnp.asarray(arrays.node_fid),
    )


def _g(x: jax.Array) -> jax.Array:
    """Fusion barrier after a table gather.

    XLA-TPU fuses a gather into its elementwise consumers, and the fused
    loop serializes (~500× slowdown measured on v5e: 11ms → 0.02ms for a
    131k-element probe round). The barrier keeps each gather a standalone
    fast-path gather op.
    """
    return jax.lax.optimization_barrier(x)


def _register_barrier_batching() -> None:
    """optimization_barrier has no vmap batching rule in jax<=0.4.x, but
    it is the identity — batch dims pass straight through.  The sharded
    match vmaps the kernel over the trie's shard axis, so register the
    trivial rule (what newer jax ships upstream) when it's missing."""
    try:
        from jax._src.lax.lax import optimization_barrier_p
        from jax.interpreters import batching
    except ImportError:      # layout moved: newer jax has the rule anyway
        return
    if optimization_barrier_p not in batching.primitive_batchers:
        def _rule(args, dims):
            return optimization_barrier_p.bind(*args), list(dims)
        batching.primitive_batchers[optimization_barrier_p] = _rule


_register_barrier_batching()


def _edge_hash(parent: jax.Array, word: jax.Array, mask: int) -> jax.Array:
    """Must stay bit-identical to index.edge_hash (host builder)."""
    h = (
        parent.astype(jnp.uint32) * jnp.uint32(_MIX_A)
        ^ word.astype(jnp.uint32) * jnp.uint32(_MIX_B)
    )
    h ^= h >> jnp.uint32(15)
    h *= jnp.uint32(0x2C1B3C6D)
    h ^= h >> jnp.uint32(12)
    return (h & jnp.uint32(mask)).astype(jnp.int32)


def _edge_step(parent: jax.Array, word: jax.Array, mask: int) -> jax.Array:
    """Double-hashing stride; must stay bit-identical to index.edge_step
    (odd → coprime with the pow2 table)."""
    h = (
        parent.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
        ^ word.astype(jnp.uint32) * jnp.uint32(0x27D4EB2F)
    )
    h ^= h >> jnp.uint32(13)
    h *= jnp.uint32(0x165667B1)
    h ^= h >> jnp.uint32(16)
    return ((h | jnp.uint32(1)) & jnp.uint32(mask)).astype(jnp.int32)


def _probe_exact(
    trie: DeviceTrie, parent: jax.Array, word: jax.Array, max_probes: int
) -> tuple[jax.Array, jax.Array]:
    """Exact-edge lookup for [B, K] (parent, word) pairs; -1 on miss.

    The probe bound is builder-verified, so the loop unrolls statically.
    Returns ``(child, iters)`` — iters counts live probe rounds per lane
    (the hash-table health signal: mean ≈ 1 on a well-sized table); the
    count is an elementwise add per unrolled round, DCE'd by XLA when
    the counters output goes unused.
    """
    hmask = trie.ht_parent.shape[0] - 1
    # hash the raw parent (-1 included): indices stay in-bounds via the
    # mask, invalid lanes are killed by `done`, and the obvious
    # where-clamp here triggers an XLA-TPU lowering cliff (~5× slower —
    # a select feeding a gather's index chain inside scan de-vectorizes)
    h = _edge_hash(parent, word, hmask)
    step = _edge_step(parent, word, hmask)
    child = jnp.full_like(parent, -1)
    iters = jnp.zeros(parent.shape, jnp.int32)
    done = parent < 0
    for p in range(max_probes):
        iters = iters + (~done).astype(jnp.int32)
        s = (h + p * step) & hmask
        slot_parent = _g(trie.ht_parent[s])
        hit = (slot_parent == parent) & (_g(trie.ht_word[s]) == word) & ~done
        child = jnp.where(hit, _g(trie.ht_child[s]), child)
        done = done | hit | (slot_parent == -1)
    return child, iters


def _pack_frontier(cand: jax.Array, K: int) -> tuple[jax.Array, jax.Array]:
    """Pack valid (≥0) entries of [B, 2K] into [B, K] slots.

    The frontier is a *set* — order is irrelevant — so a descending sort
    (valid node ids ≥ 0 sort ahead of the -1 padding) packs without any
    scatter; TPU scatters serialized this step badly in profiling.

    Returns (packed [B, K], overflowed [B]).
    """
    n_valid = jnp.sum(cand >= 0, axis=1)                   # [B]
    packed = _g(-jnp.sort(-cand, axis=1)[:, :K])
    return packed, n_valid > K


@functools.partial(jax.jit, static_argnames=("K", "max_probes"))
def match_batch(
    trie: DeviceTrie,
    tokens: jax.Array,     # [B, L] int32 word ids (PAD beyond length)
    lengths: jax.Array,    # [B] int32
    sys_flags: jax.Array,  # [B] bool — first level starts with '$'
    *,
    K: int = 32,
    max_probes: int = 8,
) -> tuple[jax.Array, jax.Array, dict]:
    """Match a topic batch against the trie.

    Returns ``(cand_fids [B, (L+1)*2K] int32, overflow [B] bool,
    mstats)``.  ``cand_fids`` holds each matched filter id exactly once,
    -1 elsewhere.  ``overflow[b]`` means topic *b*'s frontier exceeded K
    and the result may be incomplete — route it through the host oracle.
    ``mstats`` is the match half of the kernel counters (scalar int32
    leaves: frontier_peak / probe_iters / cand_pre / overflow_rows —
    see KERNEL_COUNTER_FIELDS); the compact-side fields are the step
    functions' (router_model) to fill.  The reductions are elementwise
    and ride the same program — XLA DCEs them when the caller drops the
    dict.
    """
    B, L = tokens.shape
    tokens_ext = jnp.concatenate(
        [tokens, jnp.full((B, 1), PAD, tokens.dtype)], axis=1
    )

    frontier0 = jnp.full((B, K), -1, jnp.int32).at[:, 0].set(0)  # root
    overflow0 = jnp.zeros((B,), bool)
    peak0 = jnp.zeros((), jnp.int32)
    probes0 = jnp.zeros((), jnp.int32)

    def step(carry, xs):
        frontier, overflow, peak, probes = carry
        i, tok = xs                               # i scalar, tok [B]
        valid = frontier >= 0
        peak = jnp.maximum(
            peak, jnp.max(jnp.sum(valid.astype(jnp.int32), axis=1)))
        node = jnp.where(valid, frontier, 0)
        active = (i <= lengths)[:, None]          # may still emit '#'
        ended = (i == lengths)[:, None]
        advancing = (i < lengths)[:, None]
        sys_block = (sys_flags & (i == 0))[:, None]

        hash_em = jnp.where(
            valid & active & ~sys_block, _g(trie.hash_fid[node]), -1
        )
        end_em = jnp.where(valid & ended, _g(trie.node_fid[node]), -1)

        wordk = jnp.broadcast_to(tok[:, None], (B, K))
        exact, iters = _probe_exact(
            trie, jnp.where(advancing, frontier, -1), wordk, max_probes
        )
        probes = probes + jnp.sum(iters)
        plus = jnp.where(
            valid & advancing & ~sys_block, _g(trie.plus_child[node]), -1
        )
        nxt, over = _pack_frontier(
            jnp.concatenate([exact, plus], axis=1), K
        )
        return (nxt, overflow | over, peak, probes), (hash_em, end_em)

    (_, overflow, peak, probes), (hash_ems, end_ems) = jax.lax.scan(
        step,
        (frontier0, overflow0, peak0, probes0),
        (jnp.arange(L + 1), tokens_ext.T),
    )
    # [L+1, B, K] → [B, (L+1)*K] each → concat
    cand = jnp.concatenate(
        [
            jnp.moveaxis(hash_ems, 0, 1).reshape(B, -1),
            jnp.moveaxis(end_ems, 0, 1).reshape(B, -1),
        ],
        axis=1,
    )
    mstats = {
        "frontier_peak": peak,
        "probe_iters": probes,
        "cand_pre": jnp.sum((cand >= 0).astype(jnp.int32)),
        "overflow_rows": jnp.sum(overflow.astype(jnp.int32)),
    }
    return cand, overflow, mstats


@functools.partial(jax.jit, static_argnames=("K", "max_probes"))
def match_counts(
    trie: DeviceTrie,
    tokens: jax.Array,
    lengths: jax.Array,
    sys_flags: jax.Array,
    *,
    K: int = 32,
    max_probes: int = 8,
) -> tuple[jax.Array, jax.Array]:
    """Matched-filter count per topic (the emqx_broker_bench LookupRps
    analogue — the full match with only the reduction materialized)."""
    cand, overflow, _ = match_batch(
        trie, tokens, lengths, sys_flags, K=K, max_probes=max_probes
    )
    return jnp.sum(cand >= 0, axis=1), overflow


@functools.partial(jax.jit, static_argnames=("M",))
def compact_fids(cand: jax.Array, *, M: int = 128) -> tuple[jax.Array, jax.Array]:
    """Compact sparse candidates [B, S] to the first M matches [B, M].

    Returns (fids [B, M] padded with -1, truncated [B]). Stable order.
    """
    order = _g(jnp.argsort(cand < 0, axis=1, stable=True))
    packed = _g(jnp.take_along_axis(cand, order[:, :M], axis=1))
    n = jnp.sum(cand >= 0, axis=1)
    return packed, n > M


# ---------------------------------------------------------------------------
# sharded trie: S per-shard tries stacked into [S, ...] buffers
# ---------------------------------------------------------------------------


def stacked_device_trie(shard_arrays) -> DeviceTrie:
    """Stack S per-shard TrieIndexArrays into one [S, ...] DeviceTrie.

    The edge hash tables must already share one pow2 size H — the probe
    mask (H-1) is baked per stacked buffer, so ShardedTrieIndex.ensure()
    equalizes them before this runs.  Node arrays just pad to the max N
    with -1: a -1 child/fid lane is already "miss" everywhere in the
    kernel, so padding is semantically invisible.

    Returns host (numpy-backed) arrays — the caller device_puts the
    pytree with the ``trie_sub`` sharding (shard axis 0 over ``tp``).
    """
    sizes = {a.ht_parent.shape[0] for a in shard_arrays}
    if len(sizes) != 1:
        raise ValueError(f"unequal edge-table sizes across shards: {sizes}")
    N = max(a.plus_child.shape[0] for a in shard_arrays)

    def pad_n(x: np.ndarray) -> np.ndarray:
        if x.shape[0] == N:
            return x
        return np.concatenate(
            [x, np.full(N - x.shape[0], -1, x.dtype)])

    return DeviceTrie(
        ht_parent=np.stack([a.ht_parent for a in shard_arrays]),
        ht_word=np.stack([a.ht_word for a in shard_arrays]),
        ht_child=np.stack([a.ht_child for a in shard_arrays]),
        plus_child=np.stack([pad_n(a.plus_child) for a in shard_arrays]),
        hash_fid=np.stack([pad_n(a.hash_fid) for a in shard_arrays]),
        node_fid=np.stack([pad_n(a.node_fid) for a in shard_arrays]),
    )


@functools.partial(jax.jit, static_argnames=("K", "max_probes"))
def match_batch_sharded(
    trie: DeviceTrie,      # fields [S, H] / [S, N]
    tokens: jax.Array,     # [B, L]
    lengths: jax.Array,    # [B]
    sys_flags: jax.Array,  # [B]
    *,
    K: int = 32,
    max_probes: int = 8,
) -> tuple[jax.Array, jax.Array, dict]:
    """match_batch vmapped over the shard axis of a stacked trie.

    Each shard walks the SAME (tp-replicated) topic batch against its
    own subscription slice, so the returned fids are shard-LOCAL.
    Overflow is per-shard: shard s's K-frontier can spill on a topic
    even when the replicated trie's would not (its wildcard branches
    are a subset but the cap is per walk) and vice versa — the [S, B]
    flags are OR-reduced because any spilled shard makes the merged
    result potentially incomplete for that topic.

    Returns ``(cand [S, B, (L+1)*2K], overflow [B], mstats)``; the
    vmap turns every mstats leaf into a PER-SHARD [S] vector — the
    shard-skew signal the host fold wants — including overflow_rows,
    which stays per-shard (pre-OR) by design.
    """
    cand, over, mstats = jax.vmap(
        lambda t: match_batch(
            t, tokens, lengths, sys_flags, K=K, max_probes=max_probes
        )
    )(trie)
    return cand, jnp.any(over, axis=0), mstats


@functools.partial(jax.jit, static_argnames=("M", "n_shards"))
def compact_fids_sharded(
    cand: jax.Array, *, M: int = 128, n_shards: int = 1
) -> tuple[jax.Array, jax.Array]:
    """Per-shard compact + local→global fid translation + merge.

    ``cand`` is the [S, B, C] shard-local candidate tensor from
    ``match_batch_sharded``.  Each shard compacts its own candidates to
    M slots (so the merge tensor is [B, S·M], tiny next to C), local
    fids translate to the interleaved global namespace
    (``global = local * S + shard``), and a second stable compact packs
    the shard-major concatenation down to the first M global matches.

    Returns (fids [B, M] global, truncated [B]).  Truncation is the OR
    of any per-shard spill and the merged spill — either loses matches.
    For S=1 the translation is the identity and the second compact of
    an already-packed row is a no-op, so this degenerates bit-for-bit
    to ``compact_fids``.
    """
    S, B, _ = cand.shape
    per, trunc = jax.vmap(lambda c: compact_fids(c, M=M))(cand)
    shard_ids = jnp.arange(S, dtype=per.dtype)[:, None, None]
    per = jnp.where(per >= 0, per * n_shards + shard_ids, -1)
    merged = jnp.moveaxis(per, 0, 1).reshape(B, S * M)   # [B, S*M]
    fids, trunc2 = compact_fids(merged, M=M)
    return fids, jnp.any(trunc, axis=0) | trunc2
