"""Subscriber fan-out as a bitmap OR-reduce on device.

The reference's fan-out is a per-message Erlang loop over subscriber pids
(emqx_broker.erl:546-579, sharded above 1024 subscribers via
emqx_broker_helper). Here each filter id owns a row of a packed subscriber
bitmap ``[F, W]`` (W uint32 words ⇒ 32·W subscriber slots); fan-out for a
topic batch is an OR over the rows of its matched fids — a pure
gather+reduce that scales with HBM/ICI bandwidth, with W sharded over the
``tp`` mesh axis for large subscriber populations.

For small match sets the compacted fid list itself (M entries) is the
cheaper host-side product; the bitmap path is for the heavy-fan-out regime
(BASELINE configs 2/3, millions of subscribers).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def fanout_bitmaps(bitmaps: jax.Array, fids: jax.Array) -> jax.Array:
    """OR the subscriber bitmaps of matched filters.

    bitmaps: [F, W] uint32 — W may be a tp-shard of the full width.
    fids:    [B, M] int32, -1 padding (from ops.trie_match.compact_fids).
    returns: [B, W] uint32 — subscriber slots to deliver each topic to.

    Sequential lax.scan over M keeps peak memory at [B, W] (a [B, M, W]
    materialized gather would blow HBM at production W); each step is one
    row-gather + OR, which XLA fuses.
    """
    B, M = fids.shape
    W = bitmaps.shape[1]
    valid = fids >= 0
    safe = jnp.where(valid, fids, 0)

    def step(acc, xs):
        f, v = xs                                   # [B], [B]
        # barrier: keep the row-gather un-fused from the OR (see
        # trie_match._g — fused TPU gathers serialize)
        rows = jax.lax.optimization_barrier(bitmaps[f])   # [B, W]
        return acc | jnp.where(v[:, None], rows, jnp.uint32(0)), None

    init = jnp.zeros((B, W), jnp.uint32)
    out, _ = jax.lax.scan(step, init, (safe.T, valid.T))
    return out


@jax.jit
def fanout_pool(rowmap: jax.Array, pool: jax.Array,
                fids: jax.Array) -> jax.Array:
    """Hybrid fan-out: OR the DENSE-POOL rows of matched filters.

    rowmap: [F] int32 — fid → pool row, -1 for low-degree filters (their
            slots decode host-side from the subscription table; storing a
            dense row per filter would cost F·W words — 16 GB at 10M
            filters — where the pool costs P·W for the few high-degree
            broadcast filters that actually need bitmap aggregation).
    pool:   [P, W] uint32 — subscriber-shard bitmaps, W shardable over tp.
    fids:   [B, M] int32, -1 padding.
    returns: [B, W] uint32 — shard slots contributed by dense filters.
    """
    B, M = fids.shape
    W = pool.shape[1]
    valid = fids >= 0
    safe = jnp.where(valid, fids, 0)
    rows = jnp.where(valid, rowmap[safe], -1)          # [B, M]
    has = rows >= 0
    safe_rows = jnp.where(has, rows, 0)

    def step(acc, xs):
        r, v = xs                                       # [B], [B]
        gathered = jax.lax.optimization_barrier(pool[r])    # [B, W]
        return acc | jnp.where(v[:, None], gathered, jnp.uint32(0)), None

    init = jnp.zeros((B, W), jnp.uint32)
    out, _ = jax.lax.scan(step, init, (safe_rows.T, has.T))
    return out


@jax.jit
def bitmap_to_counts(fanout: jax.Array) -> jax.Array:
    """Population count per topic: number of matched subscriber slots."""
    # popcount via uint8 view-free nibble trick (XLA has population_count)
    return jnp.sum(jax.lax.population_count(fanout), axis=1)
