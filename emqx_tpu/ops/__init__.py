from emqx_tpu.ops.trie_match import (
    DeviceTrie,
    device_trie,
    match_batch,
    match_counts,
    compact_fids,
)

__all__ = [
    "DeviceTrie",
    "device_trie",
    "match_batch",
    "match_counts",
    "compact_fids",
]
