"""Access-control front — parity with
``apps/emqx/src/emqx_access_control.erl``.

Binds the security services onto the channel's hookpoints:

- ``client.connect``       → banned check (emqx_channel checks
                             emqx_banned before authn)
- ``client.authenticate``  → authn chain; stashes extras
                             (is_superuser / acl claim) per clientid
- ``client.authorize``     → cache → authz source chain
- ``client.disconnected``  → flapping bookkeeping + state cleanup

The channel's hook folds (emqx_tpu/broker/channel.py) carry plain dicts;
this module owns per-client authn extras so the authorize path sees
``is_superuser``/``acl`` even though the channel rebuilds its clientinfo
dict per call.
"""

from __future__ import annotations

import time
from typing import Optional

from emqx_tpu.access.authn import AuthnChain
from emqx_tpu.access.authz import Authz, AuthzCache, ClientAclSource
from emqx_tpu.access.banned import Banned
from emqx_tpu.access.flapping import Flapping
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.mqtt import packet as P


class AccessControl:
    def __init__(self, authn: Optional[AuthnChain] = None,
                 authz: Optional[Authz] = None,
                 banned: Optional[Banned] = None,
                 flapping_enable: bool = False,
                 cache_enable: bool = True,
                 cache_max: int = 32, cache_ttl_ms: int = 60_000,
                 **flapping_opts) -> None:
        self.authn = authn or AuthnChain()
        self.authz = authz or Authz()
        # client_info source is always first: JWT-supplied ACLs take
        # precedence (the reference registers it at highest priority)
        if not any(s.type == "client_info" for s in self.authz.sources):
            self.authz.add_source(ClientAclSource(), front=True)
        self.banned = banned or Banned()
        self.flapping = (Flapping(self.banned, **flapping_opts)
                         if flapping_enable else None)
        self.cache_enable = cache_enable
        self.cache_max = cache_max
        self.cache_ttl_ms = cache_ttl_ms
        self._extras: dict[str, dict] = {}       # clientid → authn extras
        self._caches: dict[str, AuthzCache] = {}

    # -- hook wiring --------------------------------------------------------

    def attach(self, hooks: Hooks) -> None:
        hooks.put("client.connect", self._on_connect, priority=1000)
        hooks.put("client.authenticate", self._on_authenticate,
                  priority=1000)
        hooks.put("client.authorize", self._on_authorize, priority=1000)
        hooks.put("client.disconnected", self._on_disconnected,
                  priority=1000)

    # -- hook callbacks -----------------------------------------------------

    def _on_connect(self, conninfo: dict, acc=None):
        if self.banned.check(conninfo):
            return (Hooks.STOP, P.RC_BANNED)
        return None

    def _on_authenticate(self, cred: dict, acc: dict):
        ret = self.authn.authenticate(cred)
        if ret[0] == "ok":
            extras = ret[1]
            cid = cred.get("clientid")
            if cid:
                self._extras[cid] = extras
            return (Hooks.OK, {"result": "ok", **extras})
        reason = ret[1]
        rc = (P.RC_BAD_USER_NAME_OR_PASSWORD
              if reason == "bad_username_or_password"
              else P.RC_NOT_AUTHORIZED)
        return (Hooks.STOP, {"result": "error", "reason": reason, "rc": rc})

    def _on_authorize(self, ci: dict, action: str, topic: str, acc: str):
        cid = ci.get("clientid") or ""
        extras = self._extras.get(cid)
        if extras:
            expire_at = extras.get("expire_at")
            if expire_at is not None and time.time() >= expire_at:
                # JWT expired mid-session → deny until re-auth
                return (Hooks.STOP, "deny")
            ci = {**ci, **extras}
        cache = self._cache_for(cid) if self.cache_enable else None
        if cache is not None:
            hit = cache.get(action, topic)
            if hit is not None:
                return (Hooks.STOP, hit)
        verdict = self.authz.authorize(ci, action, topic)
        if cache is not None:
            cache.put(action, topic, verdict)
        return (Hooks.STOP, verdict)

    def _on_disconnected(self, conninfo, reason: str):
        cid = getattr(conninfo, "clientid", None) or (
            conninfo.get("clientid") if isinstance(conninfo, dict) else None)
        if not cid:
            return
        if self.flapping is not None and reason != "normal":
            self.flapping.on_disconnect(cid)
        self._extras.pop(cid, None)
        self._caches.pop(cid, None)

    # -- helpers ------------------------------------------------------------

    def _cache_for(self, clientid: str) -> AuthzCache:
        cache = self._caches.get(clientid)
        if cache is None:
            cache = self._caches[clientid] = AuthzCache(
                self.cache_max, self.cache_ttl_ms)
        return cache

    def clean_authz_cache(self, clientid: str) -> None:
        self._caches.pop(clientid, None)
