"""Authorization — parity with ``apps/emqx_authz``.

A source chain folded allow/deny/ignore per request
(emqx_authz.erl:106-115,297+): each source inspects
(clientinfo, action, topic) and answers

- ``"allow"`` / ``"deny"`` → final verdict, stop
- ``"ignore"``             → next source

falling through to the configurable ``no_match`` default. Verdicts are
memoised per connection in an LRU+TTL cache (emqx_authz_cache.erl).

Rule model (the acl.conf shape, apps/emqx_authz/src/emqx_authz_file.erl):
    Rule = (permission, who, action, topics)
      permission : allow | deny
      who        : all | ("user", name) | ("clientid", id)
                 | ("ipaddr", "10.0.0.0/8") | ("and"|"or", [who...])
      action     : publish | subscribe | all
      topics     : list of filters; "eq topic/1" pins a literal (no
                   wildcard expansion); ${clientid}/${username}
                   (and %c/%u) placeholders are substituted.
"""

from __future__ import annotations

import ipaddress
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Iterable, Optional

from emqx_tpu.core import topic as T

ClientInfo = dict


# -- rules ----------------------------------------------------------------


@dataclass(frozen=True)
class Rule:
    permission: str                    # allow | deny
    who: object = "all"
    action: str = "all"                # publish | subscribe | all
    topics: tuple = ("#",)


def _who_match(who, ci: ClientInfo) -> bool:
    if who == "all":
        return True
    if isinstance(who, tuple):
        tag = who[0]
        if tag == "user":
            return ci.get("username") == who[1]
        if tag == "clientid":
            return ci.get("clientid") == who[1]
        if tag == "ipaddr":
            peer = (ci.get("peername") or "").rsplit(":", 1)[0]
            try:
                return ipaddress.ip_address(peer) in ipaddress.ip_network(
                    who[1], strict=False)
            except ValueError:
                return False
        if tag == "and":
            return all(_who_match(w, ci) for w in who[1])
        if tag == "or":
            return any(_who_match(w, ci) for w in who[1])
    return False


def _feed(topic_spec: str, ci: ClientInfo) -> str:
    return (topic_spec
            .replace("${clientid}", ci.get("clientid") or "")
            .replace("${username}", ci.get("username") or "")
            .replace("%c", ci.get("clientid") or "")
            .replace("%u", ci.get("username") or ""))


def _topic_match(spec: str, topic: str, ci: ClientInfo) -> bool:
    if spec.startswith("eq "):
        return topic == _feed(spec[3:], ci)
    return T.match(topic, _feed(spec, ci))


def match_rule(rule: Rule, ci: ClientInfo, action: str,
               topic: str) -> Optional[str]:
    if rule.action not in ("all", action):
        return None
    if not _who_match(rule.who, ci):
        return None
    if any(_topic_match(spec, topic, ci) for spec in rule.topics):
        return rule.permission
    return None


# -- sources --------------------------------------------------------------


class Source:
    """Source behaviour: authorize → allow | deny | ignore."""

    type: str = "source"
    enable: bool = True

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        raise NotImplementedError


class FileSource(Source):
    """Static rule list = acl.conf (emqx_authz_file.erl)."""

    type = "file"

    def __init__(self, rules: Iterable[Rule]) -> None:
        self.rules = list(rules)

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        for rule in self.rules:
            verdict = match_rule(rule, ci, action, topic)
            if verdict is not None:
                return verdict
        return "ignore"

    @classmethod
    def parse(cls, text: str) -> "FileSource":
        """Parse the acl file DSL, one rule per line:
        ``allow|deny  all|user=U|clientid=C|ipaddr=CIDR
        publish|subscribe|all  topic[,topic...]``; '#' comments."""
        rules = []
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                continue
            parts = ln.split(None, 3)
            if len(parts) != 4:
                raise ValueError(f"bad acl line: {ln!r}")
            perm, who_s, action, topics_s = parts
            if perm not in ("allow", "deny"):
                raise ValueError(f"bad permission in: {ln!r}")
            if who_s == "all":
                who = "all"
            elif "=" in who_s:
                tag, val = who_s.split("=", 1)
                if tag not in ("user", "clientid", "ipaddr"):
                    raise ValueError(f"bad who in: {ln!r}")
                who = (tag, val)
            else:
                raise ValueError(f"bad who in: {ln!r}")
            topics = tuple(t.strip() for t in topics_s.split(",") if t.strip())
            rules.append(Rule(perm, who, action, topics))
        return cls(rules)


class BuiltinSource(Source):
    """Per-client / per-user / all rule store
    (emqx_authz_mnesia.erl)."""

    type = "built_in_database"

    def __init__(self) -> None:
        self._by_clientid: dict[str, list[Rule]] = {}
        self._by_username: dict[str, list[Rule]] = {}
        self._all: list[Rule] = []

    def set_rules(self, who: object, rules: list[Rule]) -> None:
        if who == "all":
            self._all = list(rules)
        elif isinstance(who, tuple) and who[0] == "clientid":
            self._by_clientid[who[1]] = list(rules)
        elif isinstance(who, tuple) and who[0] == "user":
            self._by_username[who[1]] = list(rules)
        else:
            raise ValueError(f"bad who {who!r}")

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        chains = (
            self._by_clientid.get(ci.get("clientid") or "", ()),
            self._by_username.get(ci.get("username") or "", ()),
            self._all,
        )
        for rules in chains:
            for rule in rules:
                verdict = match_rule(rule, ci, action, topic)
                if verdict is not None:
                    return verdict
        return "ignore"


class ClientAclSource(Source):
    """Rules attached to the client at authentication time (the JWT
    ``acl`` claim path, emqx_authz_client_info.erl): reads
    ``ci["acl"] = {"pub": [...], "sub": [...], "all": [...]}``."""

    type = "client_info"

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        acl = ci.get("acl")
        if not acl:
            return "ignore"
        key = {"publish": "pub", "subscribe": "sub"}[action]
        specs = list(acl.get(key, ())) + list(acl.get("all", ()))
        if not specs:
            return "ignore"
        for spec in specs:
            if _topic_match(spec, topic, ci):
                return "allow"
        return "deny"                           # acl present but no grant


class HttpAclSource(Source):
    """External HTTP authorizer (emqx_authz_http.erl), transport
    injected like ``HttpProvider``."""

    type = "http"

    def __init__(self, request_fn) -> None:
        self.request_fn = request_fn

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        try:
            resp = self.request_fn({
                "clientid": ci.get("clientid"),
                "username": ci.get("username"),
                "action": action, "topic": topic,
            })
        except Exception:
            return "ignore"
        if resp is None:
            return "ignore"
        return {"allow": "allow", "deny": "deny"}.get(
            resp.get("result"), "ignore")


# -- cache ----------------------------------------------------------------


class AuthzCache:
    """Per-connection verdict cache: LRU with TTL
    (emqx_authz_cache.erl; reference defaults 32 entries / 1 min)."""

    def __init__(self, max_size: int = 32, ttl_ms: int = 60_000) -> None:
        self.max_size = max_size
        self.ttl_ms = ttl_ms
        self._d: OrderedDict[tuple, tuple[str, float]] = OrderedDict()

    def get(self, action: str, topic: str) -> Optional[str]:
        key = (action, topic)
        hit = self._d.get(key)
        if hit is None:
            return None
        verdict, at = hit
        if (time.time() - at) * 1000 > self.ttl_ms:
            del self._d[key]
            return None
        self._d.move_to_end(key)
        return verdict

    def put(self, action: str, topic: str, verdict: str) -> None:
        self._d[(action, topic)] = (verdict, time.time())
        self._d.move_to_end((action, topic))
        while len(self._d) > self.max_size:
            self._d.popitem(last=False)

    def drain(self) -> None:
        self._d.clear()


# -- the chain ------------------------------------------------------------


class Authz:
    """Source chain + defaults (emqx_authz.erl):
    ``no_match`` = allow|deny, superuser bypass before any source."""

    def __init__(self, sources: Optional[list[Source]] = None,
                 no_match: str = "allow") -> None:
        self.sources: list[Source] = list(sources or [])
        self.no_match = no_match

    def add_source(self, src: Source, front: bool = False) -> None:
        if front:
            self.sources.insert(0, src)
        else:
            self.sources.append(src)

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        if ci.get("is_superuser"):
            return "allow"
        for src in self.sources:
            if not src.enable:
                continue
            verdict = src.authorize(ci, action, topic)
            if verdict in ("allow", "deny"):
                return verdict
        return self.no_match
