"""Authentication chains — parity with
``apps/emqx/src/emqx_authentication.erl`` + ``apps/emqx_authn``.

A chain is an ordered list of providers; each provider's
``authenticate(credential)`` returns:

- ``("ok", extras)``      → accepted, stop the chain (extras may carry
                            ``is_superuser``, ``acl`` …)
- ``"ignore"``            → not my user / backend unsure, try next
- ``("error", rc)``       → rejected, stop the chain

mirroring the provider behaviour `-callback authenticate/2`
(emqx_authentication.erl:161) and the chain fold (:244-283). An empty
chain allows everyone (anonymous), as the reference does with no
authenticators configured.

Providers implemented (apps/emqx_authn/src/simple_authn/):
``BuiltinDbProvider`` (password_based:built_in_database),
``JwtProvider`` (HS256/HS384/HS512 over stdlib hmac),
``HttpProvider`` (password_based:http, pluggable request fn so tests
inject a fake server), ``ScramProvider`` (SCRAM-SHA-256 first/final
message flow used by MQTT5 enhanced auth).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from emqx_tpu.access import hashing
from emqx_tpu.access.hashing import (
    HashSpec, check_password, gen_salt, hash_password,
)

Credential = dict  # clientid/username/password/peername/...


class Provider:
    """Provider behaviour (emqx_authentication.erl:161)."""

    id: str = "provider"

    def authenticate(self, cred: Credential):
        raise NotImplementedError

    def destroy(self) -> None:
        pass


# -- built-in password database ------------------------------------------


@dataclass
class _UserRow:
    key: str
    stored: bytes
    salt: bytes
    is_superuser: bool = False


class BuiltinDbProvider(Provider):
    """In-memory user DB keyed by username or clientid
    (emqx_authn_mnesia.erl)."""

    id = "password_based:built_in_database"

    def __init__(self, user_id_type: str = "username",
                 hash_spec: Optional[HashSpec] = None) -> None:
        self.user_id_type = user_id_type          # username | clientid
        self.hash_spec = hash_spec or HashSpec()
        hashing.warm(self.hash_spec)
        self._users: dict[str, _UserRow] = {}

    def add_user(self, user_id: str, password: str,
                 is_superuser: bool = False) -> None:
        salt = gen_salt(self.hash_spec)
        stored = hash_password(self.hash_spec, salt, password.encode())
        self._users[user_id] = _UserRow(user_id, stored, salt, is_superuser)

    def delete_user(self, user_id: str) -> bool:
        return self._users.pop(user_id, None) is not None

    def lookup_user(self, user_id: str) -> Optional[dict]:
        row = self._users.get(user_id)
        if row is None:
            return None
        return {"user_id": row.key, "is_superuser": row.is_superuser}

    def list_users(self) -> list[dict]:
        return [{"user_id": r.key, "is_superuser": r.is_superuser}
                for r in self._users.values()]

    def authenticate(self, cred: Credential):
        user_id = cred.get(self.user_id_type)
        if not user_id:
            return "ignore"
        row = self._users.get(user_id)
        if row is None:
            return "ignore"                      # not my user → next provider
        password = cred.get("password") or b""
        if isinstance(password, str):
            password = password.encode()
        if check_password(self.hash_spec, row.salt, row.stored, password):
            return ("ok", {"is_superuser": row.is_superuser})
        return ("error", "bad_username_or_password")


# -- JWT ------------------------------------------------------------------


def _b64url(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


def _unb64url(data: str) -> bytes:
    pad = -len(data) % 4
    return base64.urlsafe_b64decode(data + "=" * pad)


def jwt_sign(claims: dict, secret: bytes, alg: str = "HS256") -> str:
    """Test/tooling helper: mint an HS* JWT."""
    digest = {"HS256": "sha256", "HS384": "sha384", "HS512": "sha512"}[alg]
    header = _b64url(json.dumps({"alg": alg, "typ": "JWT"}).encode())
    body = _b64url(json.dumps(claims).encode())
    sig = _b64url(hmac.new(secret, header + b"." + body,
                           getattr(hashlib, digest)).digest())
    return (header + b"." + body + b"." + sig).decode()


class JwtProvider(Provider):
    """JWT verification (emqx_authn_jwt.erl): password carries the
    token; verifies signature + exp/nbf, checks optional pinned claims,
    extracts acl/is_superuser claims.

    Three key sources, as in the reference:
    - ``secret``: HMAC (HS256/384/512)
    - ``public_key_pem``: RSA/ECDSA public key (RS256/384/512, ES256)
    - ``jwks`` / ``jwks_fn``: a JWKS document (or a zero-arg fetcher —
      the endpoint transport is injected like HttpProvider's, so tests
      run socketless); keys select by the token header's ``kid`` and a
      verification miss triggers ONE refresh (key rotation)."""

    id = "jwt"

    def __init__(self, secret: bytes = b"", algorithm: str = "HS256",
                 verify_claims: Optional[dict] = None,
                 from_field: str = "password",
                 public_key_pem: Optional[bytes] = None,
                 jwks: Optional[dict] = None,
                 jwks_fn: Optional[Callable[[], dict]] = None) -> None:
        if algorithm.startswith("HS") and not secret:
            # an empty HMAC secret verifies attacker-minted tokens
            # (HMAC(b"") is computable by anyone) — refuse at config
            # time; key sources don't help, HS* only ever uses `secret`
            raise ValueError(
                "jwt: HS* algorithms require a non-empty secret")
        self.secret = secret
        self.algorithm = algorithm
        self.verify_claims = verify_claims or {}
        self.from_field = from_field             # password | username
        self.public_key_pem = public_key_pem
        self._static_key = None
        if public_key_pem is not None:
            # parse ONCE — a malformed PEM fails at config time, not per
            # CONNECT, and the hot path skips re-parsing
            from cryptography.hazmat.primitives.serialization import (
                load_pem_public_key)
            self._static_key = load_pem_public_key(public_key_pem)
        self.jwks_fn = jwks_fn
        self._jwks = jwks or ({} if jwks_fn is None else None)
        self._jwks_keys = (None if self._jwks is None
                           else self._parse_jwks(self._jwks))
        # refresh throttle: a flood of bad-signature tokens must not
        # amplify into one endpoint fetch each (the reference refreshes
        # on an interval, emqx_authn_jwt ssl/refresh_interval)
        self.jwks_min_refresh_s = 5.0
        self._jwks_fetched_at = 0.0

    # -- asymmetric verification -------------------------------------------

    _RS = {"RS256": "sha256", "RS384": "sha384", "RS512": "sha512"}

    @staticmethod
    def _parse_jwks(doc: dict) -> list:
        """JWKS → [(kid, kty, key_object)], parsed ONCE per fetch — key
        construction is off the per-CONNECT hot path."""
        out = []
        for jwk in (doc or {}).get("keys", []):
            try:
                if jwk.get("kty") == "RSA":
                    from cryptography.hazmat.primitives.asymmetric.rsa \
                        import RSAPublicNumbers
                    n = int.from_bytes(_unb64url(jwk["n"]), "big")
                    e = int.from_bytes(_unb64url(jwk["e"]), "big")
                    out.append((jwk.get("kid"), "RSA",
                                RSAPublicNumbers(e, n).public_key()))
                elif jwk.get("kty") == "EC" and jwk.get("crv") == "P-256":
                    from cryptography.hazmat.primitives.asymmetric.ec \
                        import SECP256R1, EllipticCurvePublicNumbers
                    x = int.from_bytes(_unb64url(jwk["x"]), "big")
                    y = int.from_bytes(_unb64url(jwk["y"]), "big")
                    out.append((jwk.get("kid"), "EC",
                                EllipticCurvePublicNumbers(
                                    x, y, SECP256R1()).public_key()))
            except Exception:            # malformed JWK entry: skip it
                continue
        return out

    def _refresh_jwks(self) -> None:
        try:
            doc = self.jwks_fn() or {}
        except Exception:
            # keep (or establish) a doc even on failure: `_jwks is None`
            # marks "never fetched" and would bypass the refresh
            # throttle, turning a dead endpoint into per-token blocking
            # fetches
            self._jwks = self._jwks or {}
            return
        self._jwks = doc
        self._jwks_keys = self._parse_jwks(doc)

    def _jwks_keys_view(self, refresh: bool = False) -> list:
        if self.jwks_fn is not None:
            now = time.time()
            first = self._jwks is None
            if (first or refresh) and (
                    first or now - self._jwks_fetched_at
                    >= self.jwks_min_refresh_s):
                self._jwks_fetched_at = now
                # the first fetch and a verification-miss refresh (key
                # rotation) must complete before verification proceeds;
                # the throttle bounds loop stalls to one fetch per
                # jwks_min_refresh_s even under a bad-token flood
                self._refresh_jwks()
        if self._jwks_keys is None:
            self._jwks_keys = self._parse_jwks(self._jwks)
        return self._jwks_keys

    def _candidate_keys(self, alg: str, header: dict,
                        refresh: bool = False) -> list:
        """All plausibly matching public keys (kid match if present,
        kty compatible with alg) — a no-kid token against a multi-key
        JWKS tries each."""
        if self._static_key is not None:
            return [self._static_key]
        want_kty = "RSA" if alg in self._RS else "EC"
        kid = header.get("kid")
        return [key for k_kid, k_kty, key
                in self._jwks_keys_view(refresh)
                if k_kty == want_kty
                and (kid is None or k_kid == kid)]

    def _verify_asym(self, alg: str, header: dict, signing: bytes,
                     sig: bytes) -> bool:
        from cryptography.hazmat.primitives import hashes as chashes

        digest = {"sha256": chashes.SHA256, "sha384": chashes.SHA384,
                  "sha512": chashes.SHA512}
        for refresh in (False, True):
            for key in self._candidate_keys(alg, header, refresh=refresh):
                try:
                    if alg in self._RS:
                        from cryptography.hazmat.primitives.asymmetric \
                            .padding import PKCS1v15
                        key.verify(sig, signing, PKCS1v15(),
                                   digest[self._RS[alg]]())
                    else:                # ES256: raw r||s → DER
                        from cryptography.hazmat.primitives.asymmetric \
                            .ec import ECDSA
                        from cryptography.hazmat.primitives.asymmetric \
                            .utils import encode_dss_signature
                        half = len(sig) // 2
                        der = encode_dss_signature(
                            int.from_bytes(sig[:half], "big"),
                            int.from_bytes(sig[half:], "big"))
                        key.verify(der, signing, ECDSA(chashes.SHA256()))
                    return True
                except Exception:        # wrong key type/size included —
                    continue             # any failure = not verified
            if self.jwks_fn is None:
                return False             # static keys can't refresh
        return False

    def authenticate(self, cred: Credential):
        token = cred.get(self.from_field)
        if not token:
            return "ignore"
        if isinstance(token, bytes):
            token = token.decode(errors="replace")
        parts = token.split(".")
        if len(parts) != 3:
            return "ignore"                      # not a JWT → next provider
        try:
            header = json.loads(_unb64url(parts[0]))
            claims = json.loads(_unb64url(parts[1]))
            sig = _unb64url(parts[2])
        except Exception:
            return "ignore"
        if not isinstance(header, dict) or not isinstance(claims, dict):
            return ("error", "bad_token")
        alg = header.get("alg")
        if alg != self.algorithm:
            return ("error", "bad_token_algorithm")
        signing = f"{parts[0]}.{parts[1]}".encode()
        if alg in ("HS256", "HS384", "HS512"):
            digest = {"HS256": "sha256", "HS384": "sha384",
                      "HS512": "sha512"}[alg]
            expect = hmac.new(self.secret, signing,
                              getattr(hashlib, digest)).digest()
            if not hmac.compare_digest(expect, sig):
                return ("error", "bad_token_signature")
        elif alg in ("RS256", "RS384", "RS512", "ES256"):
            if not self._verify_asym(alg, header, signing, sig):
                return ("error", "bad_token_signature")
        else:
            return ("error", "bad_token_algorithm")
        now = time.time()
        try:
            exp = float(claims["exp"]) if "exp" in claims else None
            nbf = float(claims["nbf"]) if "nbf" in claims else None
        except (TypeError, ValueError):
            return ("error", "bad_token_claims")
        if exp is not None and now >= exp:
            return ("error", "token_expired")
        if nbf is not None and now < nbf:
            return ("error", "token_not_yet_valid")
        for k, want in self.verify_claims.items():
            # placeholder ${clientid}/${username} as in the reference
            if want == "${clientid}":
                want = cred.get("clientid")
            elif want == "${username}":
                want = cred.get("username")
            if claims.get(k) != want:
                return ("error", "claim_mismatch")
        extras: dict[str, Any] = {
            "is_superuser": bool(claims.get("is_superuser", False))
        }
        if "acl" in claims:
            extras["acl"] = claims["acl"]
        if exp is not None:
            extras["expire_at"] = exp
        return ("ok", extras)


# -- HTTP -----------------------------------------------------------------


class HttpProvider(Provider):
    """External HTTP authenticator (emqx_authn_http.erl): POSTs the
    credential, maps {result: allow|deny|ignore, is_superuser} replies.
    The transport is injected (``request_fn(body_dict) -> dict | None``)
    so unit tests run without sockets; production wires an http client."""

    id = "password_based:http"

    def __init__(self, request_fn: Callable[[dict], Optional[dict]]) -> None:
        self.request_fn = request_fn

    def authenticate(self, cred: Credential):
        body = {
            "clientid": cred.get("clientid"),
            "username": cred.get("username"),
            "password": (
                cred.get("password").decode(errors="replace")
                if isinstance(cred.get("password"), bytes)
                else cred.get("password")
            ),
            "peername": cred.get("peername"),
        }
        try:
            resp = self.request_fn(body)
        except Exception:
            return "ignore"                      # backend down → next provider
        if resp is None:
            return "ignore"
        result = resp.get("result", "ignore")
        if result == "allow":
            return ("ok", {"is_superuser": bool(resp.get("is_superuser"))})
        if result == "deny":
            return ("error", "http_denied")
        return "ignore"


# -- SCRAM-SHA-256 (enhanced auth) ----------------------------------------


class ScramProvider(Provider):
    """SCRAM-SHA-256 for MQTT5 enhanced authentication
    (emqx_enhanced_authn_scram_mnesia.erl). Holds per-user
    StoredKey/ServerKey/salt; speaks the client-first → server-first →
    client-final → server-final exchange via ``step``."""

    id = "scram:built_in_database"
    _ALG = "sha256"

    PENDING_TTL_S = 60.0          # abandoned-exchange expiry

    def __init__(self, iterations: int = 4096) -> None:
        self.iterations = iterations
        self._users: dict[str, dict] = {}
        self._pending: dict[str, dict] = {}      # clientid → exchange state

    def add_user(self, username: str, password: str,
                 is_superuser: bool = False) -> None:
        salt = os.urandom(16)
        salted = hashlib.pbkdf2_hmac(
            self._ALG, password.encode(), salt, self.iterations)
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        self._users[username] = {
            "salt": salt,
            "stored_key": hashlib.sha256(client_key).digest(),
            "server_key": hmac.new(salted, b"Server Key",
                                   hashlib.sha256).digest(),
            "is_superuser": is_superuser,
        }

    def step(self, clientid: str, data: bytes):
        """Drive one exchange step; returns ("continue", out) |
        ("ok", extras) | ("error", reason)."""
        st = self._pending.get(clientid)
        if st is not None and time.time() - st["at"] > self.PENDING_TTL_S:
            del self._pending[clientid]
            st = None                            # stale → restart exchange
        if st is None:
            return self._client_first(clientid, data)
        return self._client_final(clientid, st, data)

    def gc(self, now=None) -> None:
        """Sweep abandoned exchanges (housekeeping tick)."""
        now = time.time() if now is None else now
        dead = [cid for cid, st in self._pending.items()
                if now - st["at"] > self.PENDING_TTL_S]
        for cid in dead:
            del self._pending[cid]

    def _client_first(self, clientid: str, data: bytes):
        try:
            fields = dict(
                kv.split(b"=", 1) for kv in data.split(b",") if b"=" in kv)
            username = fields[b"n"].decode()
            cnonce = fields[b"r"]
        except Exception:
            return ("error", "bad_client_first")
        row = self._users.get(username)
        if row is None:
            return ("error", "not_authorized")
        snonce = cnonce + _b64url(os.urandom(12))
        bare = b"n=" + username.encode() + b",r=" + cnonce
        server_first = (b"r=" + snonce + b",s="
                        + base64.b64encode(row["salt"])
                        + b",i=" + str(self.iterations).encode())
        self._pending[clientid] = {
            "row": row, "nonce": snonce, "at": time.time(),
            "auth_message_prefix": bare + b"," + server_first + b",",
        }
        return ("continue", server_first)

    def _client_final(self, clientid: str, st: dict, data: bytes):
        try:
            fields = dict(
                kv.split(b"=", 1) for kv in data.split(b",") if b"=" in kv)
            nonce, proof = fields[b"r"], base64.b64decode(fields[b"p"])
        except Exception:
            return ("error", "bad_client_final")
        if nonce != st["nonce"]:
            return ("error", "nonce_mismatch")
        row = st["row"]
        without_proof = data.rsplit(b",p=", 1)[0]
        auth_message = st["auth_message_prefix"] + without_proof
        # ClientSignature = HMAC(StoredKey, AuthMessage);
        # ClientKey = Proof XOR Sig; verify H(ClientKey) == StoredKey
        sig = hmac.new(row["stored_key"], auth_message,
                       hashlib.sha256).digest()
        client_key = bytes(a ^ b for a, b in zip(proof, sig))
        del self._pending[clientid]
        if hashlib.sha256(client_key).digest() != row["stored_key"]:
            return ("error", "bad_proof")
        server_sig = hmac.new(row["server_key"], auth_message,
                              hashlib.sha256).digest()
        return ("ok", {"is_superuser": row["is_superuser"],
                       "server_final": b"v=" + base64.b64encode(server_sig)})

    def authenticate(self, cred: Credential):
        return "ignore"                          # only via enhanced auth


# -- the chain ------------------------------------------------------------


class AuthnChain:
    """Ordered provider chain (one per listener in the reference;
    emqx_authentication.erl:228-283)."""

    def __init__(self, providers: Optional[list[Provider]] = None) -> None:
        self.providers: list[Provider] = list(providers or [])

    def add(self, provider: Provider, front: bool = False) -> None:
        if front:
            self.providers.insert(0, provider)
        else:
            self.providers.append(provider)

    def remove(self, provider_id: str) -> None:
        self.providers = [p for p in self.providers if p.id != provider_id]

    def authenticate(self, cred: Credential):
        """→ ("ok", extras) | ("error", reason). Empty chain → anonymous ok."""
        if not self.providers:
            return ("ok", {})
        for p in self.providers:
            ret = p.authenticate(cred)
            if ret == "ignore":
                continue
            return ret
        return ("error", "not_authorized")       # all ignored → deny
