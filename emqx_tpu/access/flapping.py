"""Flapping detection — parity with ``apps/emqx/src/emqx_flapping.erl``.

Counts disconnects per clientid in a sliding window; crossing
``max_count`` within ``window_s`` bans the client for ``ban_duration_s``
via the shared ``Banned`` table (the reference bans by clientid with
by="flapping detector").
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from emqx_tpu.access.banned import Banned


class Flapping:
    def __init__(self, banned: Banned, *, max_count: int = 15,
                 window_s: float = 60.0,
                 ban_duration_s: float = 300.0) -> None:
        self.banned = banned
        self.max_count = max_count
        self.window_s = window_s
        self.ban_duration_s = ban_duration_s
        self._events: dict[str, deque[float]] = {}

    def on_disconnect(self, clientid: str,
                      now: Optional[float] = None) -> bool:
        """Record one disconnect; returns True if this tripped a ban."""
        now = time.time() if now is None else now
        dq = self._events.setdefault(clientid, deque())
        dq.append(now)
        while dq and now - dq[0] > self.window_s:
            dq.popleft()
        if len(dq) >= self.max_count:
            self.banned.create(
                "clientid", clientid, by="flapping detector",
                reason=f"flapping: {len(dq)} disconnects in "
                       f"{self.window_s:.0f}s",
                duration_s=self.ban_duration_s)
            dq.clear()
            return True
        return False

    def gc(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        dead = [cid for cid, dq in self._events.items()
                if not dq or now - dq[-1] > self.window_s]
        for cid in dead:
            del self._events[cid]
