"""SQL (MySQL/PostgreSQL) and MongoDB authn providers + authz sources —
the ``emqx_authn_mysql/pgsql/mongodb.erl`` and
``emqx_authz_mysql/pgsql/mongodb.erl`` analogues over the in-repo wire
clients (connector/mysql.py, connector/pgsql.py, connector/mongodb.py).

Authn (SQL): the reference's default query shape
``SELECT password_hash, salt, is_superuser FROM mqtt_user WHERE
username = ${username} LIMIT 1`` — columns are positional by NAME from
the resultset; the password check shares the built-in DB's HashSpec.

Authz (SQL): ``SELECT permission, action, topic FROM mqtt_acl WHERE
username = ${username}`` rows fold allow/deny per action with
placeholder-expanding topic match, exactly the source semantics of
emqx_authz.erl:106-115.

Mongo: same data model over collections (``mqtt_user`` docs with
password_hash/salt/is_superuser; ``mqtt_acl`` docs with
permission/action/topics[]).

Backend-down behaviour is uniformly "ignore" — the chain moves on, the
fold's no_match applies (reference: resource unavailable ⇒ ignore).
"""

from __future__ import annotations

from typing import Any, Optional

from emqx_tpu.access.authn import Credential, Provider
from emqx_tpu.access.authz import ClientInfo, Source, _topic_match
from emqx_tpu.access.hashing import HashSpec, check_password

_TRUE = (True, "true", "1", "True", 1)


def _binds(cred: dict) -> dict:
    out = {}
    for key in ("username", "clientid"):
        v = cred.get(key)
        if isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        out[key] = v or ""
    peer = cred.get("peerhost") or str(cred.get("peername") or "")
    out["peerhost"] = peer.rsplit(":", 1)[0]
    return out


class SqlAuthnProvider(Provider):
    """One provider for both SQL backends — they differ only in client.
    ``client`` needs ``query(sql) -> (cols, rows)``."""

    def __init__(self, client, query: Optional[str] = None,
                 hash_spec: Optional[HashSpec] = None,
                 backend: str = "mysql") -> None:
        self.id = f"password_based:{backend}"
        self.client = client
        self.query = query or (
            "SELECT password_hash, salt, is_superuser FROM mqtt_user "
            "WHERE username = ${username} LIMIT 1")
        self.hash_spec = hash_spec or HashSpec(name="plain")

    def authenticate(self, cred: Credential):
        from emqx_tpu.connector.pgsql import render_sql

        try:
            cols, rows = self.client.query(
                render_sql(self.query, _binds(cred)))
        except Exception:     # noqa: BLE001 — backend down ⇒ ignore
            return "ignore"
        if not rows:
            return "ignore"
        row = dict(zip(cols, rows[0]))
        if "password_hash" not in row or row["password_hash"] is None:
            return "ignore"
        password = cred.get("password") or b""
        if isinstance(password, str):
            password = password.encode()
        salt = (row.get("salt") or "").encode()
        if check_password(self.hash_spec, salt,
                          str(row["password_hash"]).encode(), password):
            return ("ok", {
                "is_superuser": row.get("is_superuser") in _TRUE})
        return ("error", "bad_username_or_password")


class SqlAclSource(Source):
    def __init__(self, client, query: Optional[str] = None,
                 backend: str = "mysql") -> None:
        self.type = backend
        self.client = client
        self.query = query or (
            "SELECT permission, action, topic FROM mqtt_acl "
            "WHERE username = ${username}")

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        from emqx_tpu.connector.pgsql import render_sql

        try:
            cols, rows = self.client.query(
                render_sql(self.query, _binds(ci)))
        except Exception:     # noqa: BLE001
            return "ignore"
        for r in rows:
            row = dict(zip(cols, r))
            act = str(row.get("action", "all"))
            if act not in (action, "all"):
                continue
            if _topic_match(str(row.get("topic", "")), topic, ci):
                return ("allow"
                        if str(row.get("permission")) == "allow"
                        else "deny")
        return "ignore"


class MongoAuthnProvider(Provider):
    id = "password_based:mongodb"

    def __init__(self, client, collection: str = "mqtt_user",
                 filter_: Optional[dict] = None,
                 hash_spec: Optional[HashSpec] = None) -> None:
        self.client = client
        self.collection = collection
        self.filter = filter_ or {"username": "${username}"}
        self.hash_spec = hash_spec or HashSpec(name="plain")

    def _render_filter(self, cred: dict) -> dict:
        binds = _binds(cred)

        def sub(v: Any) -> Any:
            if isinstance(v, str) and v.startswith("${") and v.endswith("}"):
                return binds.get(v[2:-1], "")
            return v
        return {k: sub(v) for k, v in self.filter.items()}

    def authenticate(self, cred: Credential):
        try:
            docs = self.client.find(self.collection,
                                    self._render_filter(cred))
        except Exception:     # noqa: BLE001
            return "ignore"
        if not docs or "password_hash" not in docs[0]:
            return "ignore"
        doc = docs[0]
        password = cred.get("password") or b""
        if isinstance(password, str):
            password = password.encode()
        salt = str(doc.get("salt") or "").encode()
        if check_password(self.hash_spec, salt,
                          str(doc["password_hash"]).encode(), password):
            return ("ok", {"is_superuser": doc.get("is_superuser") in _TRUE})
        return ("error", "bad_username_or_password")


class MongoAclSource(Source):
    type = "mongodb"

    def __init__(self, client, collection: str = "mqtt_acl",
                 filter_: Optional[dict] = None) -> None:
        self.client = client
        self.collection = collection
        self.filter = filter_ or {"username": "${username}"}

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        try:
            docs = self.client.find(
                self.collection,
                MongoAuthnProvider._render_filter(self, ci))
        except Exception:     # noqa: BLE001
            return "ignore"
        for doc in docs:
            act = str(doc.get("action", "all"))
            if act not in (action, "all"):
                continue
            topics = doc.get("topics") or (
                [doc["topic"]] if doc.get("topic") else [])
            for filt in topics:
                if _topic_match(str(filt), topic, ci):
                    return ("allow"
                            if str(doc.get("permission")) == "allow"
                            else "deny")
        return "ignore"
