"""Ban table — parity with ``apps/emqx/src/emqx_banned.erl``.

Bans keyed by ``(kind, value)`` where kind ∈ clientid | username |
peerhost, each with an ``until`` deadline (None = forever). ``check``
runs at CONNECT (emqx_channel calls emqx_banned:check/1 before authn);
expired entries lazily removed (the reference also sweeps on a timer —
``expire()`` is that sweep, driven by the app housekeeping tick).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

KINDS = ("clientid", "username", "peerhost")


@dataclass
class BanEntry:
    kind: str
    value: str
    by: str = "admin"
    reason: str = ""
    at: float = field(default_factory=time.time)
    until: Optional[float] = None          # unix seconds; None = forever


class Banned:
    def __init__(self) -> None:
        self._t: dict[tuple[str, str], BanEntry] = {}

    def create(self, kind: str, value: str, *, by: str = "admin",
               reason: str = "", duration_s: Optional[float] = None,
               until: Optional[float] = None) -> BanEntry:
        if kind not in KINDS:
            raise ValueError(f"bad ban kind {kind!r}")
        if duration_s is not None:
            until = time.time() + duration_s
        entry = BanEntry(kind, value, by=by, reason=reason, until=until)
        self._t[(kind, value)] = entry
        return entry

    def delete(self, kind: str, value: str) -> bool:
        return self._t.pop((kind, value), None) is not None

    def look_up(self, kind: str, value: str) -> Optional[BanEntry]:
        e = self._t.get((kind, value))
        if e is not None and e.until is not None and time.time() >= e.until:
            del self._t[(kind, value)]
            return None
        return e

    def all(self) -> list[BanEntry]:
        self.expire()
        return list(self._t.values())

    def check(self, clientinfo: dict) -> bool:
        """True if the client is banned (emqx_banned:check/1)."""
        peer = (clientinfo.get("peername") or "").rsplit(":", 1)[0]
        return any((
            self.look_up("clientid", clientinfo.get("clientid") or ""),
            self.look_up("username", clientinfo.get("username") or ""),
            self.look_up("peerhost", peer),
        ))

    def expire(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        dead = [k for k, e in self._t.items()
                if e.until is not None and now >= e.until]
        for k in dead:
            del self._t[k]
