"""TLS-PSK identity table — ``apps/emqx_psk/`` analogue.

identity → pre-shared key (hex on disk, raw bytes in memory), with the
reference's bootstrap-file import format (``identity:psk-hex`` per line,
emqx_psk.erl). The lookup surface is the SSL server callback shape: a
TLS listener asks for the PSK bytes of an offered identity.
"""

from __future__ import annotations

import threading
from typing import Optional


class PskStore:
    def __init__(self, enable: bool = True,
                 init_file: Optional[str] = None,
                 separator: str = ":") -> None:
        self.enable = enable
        self.separator = separator
        self._table: dict[str, bytes] = {}
        self._lock = threading.RLock()
        if init_file:
            self.import_file(init_file)

    def import_file(self, path: str) -> int:
        """``identity:hex`` per line; blank lines/comments skipped.
        Returns the number of imported identities."""
        n = 0
        with open(path, "r", encoding="utf-8") as fh:
            for raw in fh:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                ident, sep, hexkey = line.partition(self.separator)
                if not sep:
                    continue
                try:
                    self.insert(ident, bytes.fromhex(hexkey.strip()))
                    n += 1
                except ValueError:
                    continue
        return n

    def insert(self, identity: str, psk: bytes) -> None:
        with self._lock:
            self._table[identity] = psk

    def lookup(self, identity: str) -> Optional[bytes]:
        """The ssl psk_lookup callback: None → handshake rejected."""
        if not self.enable:
            return None
        return self._table.get(identity)

    def delete(self, identity: str) -> bool:
        with self._lock:
            return self._table.pop(identity, None) is not None

    def all(self) -> list[str]:
        return list(self._table)

    def __len__(self) -> int:
        return len(self._table)
