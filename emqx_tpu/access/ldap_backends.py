"""LDAP authn provider + authz source over the in-repo LDAPv3 client
(connector/ldap.py).

The reference ships LDAP as a pooled connector
(emqx_connector_ldap.erl:102-118 `{search, Base, Filter, Attributes}`);
the auth data model here follows its classic LDAP auth scheme
(emqx_auth_ldap's mqttUser objectClass): look the user's entry up by
filter, verify the password by **re-binding as the entry's DN** (never
reading the hash), and read ACL rules from `mqttPublishTopic` /
`mqttSubscriptionTopic` / `mqttPubSubTopic` attributes.

Backend-down behaviour is uniformly "ignore", matching the other DB
backends (db_backends.py).
"""

from __future__ import annotations

from typing import Optional

from emqx_tpu.access.authn import Credential, Provider
from emqx_tpu.access.authz import ClientInfo, Source, _topic_match

_TRUE = ("true", "1", "TRUE", "True")


def _render(template: str, cred: dict) -> str:
    from emqx_tpu.connector.ldap import ldap_escape

    out = template
    for key in ("username", "clientid"):
        v = cred.get(key) or ""
        if isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        # RFC 4515-escape: a username like "bo*" must match the literal
        # entry, not act as a wildcard over the directory
        out = out.replace("${" + key + "}", ldap_escape(v))
    return out


class LdapAuthnProvider(Provider):
    id = "password_based:ldap"

    def __init__(self, client, base_dn: str = "dc=emqx,dc=io",
                 filter_: Optional[str] = None) -> None:
        self.client = client
        self.base_dn = base_dn
        self.filter = filter_ or "(&(objectClass=mqttUser)(uid=${username}))"

    def authenticate(self, cred: Credential):
        try:
            entries = self.client.search(
                self.base_dn, _render(self.filter, cred),
                ("isSuperuser",))
        except Exception:     # noqa: BLE001 — backend down ⇒ ignore
            return "ignore"
        if not entries:
            return "ignore"
        dn, attrs = entries[0]
        password = cred.get("password") or b""
        if isinstance(password, bytes):
            password = password.decode("utf-8", "replace")
        # RFC 4513 §5.1.2: simple bind with a name but empty password is
        # an *unauthenticated* bind — many directories accept it, which
        # would turn "no password" into a login as any known user
        if not password:
            return ("error", "bad_username_or_password")
        try:
            ok = self.client.check_bind(dn, password)
        except Exception:     # noqa: BLE001
            return "ignore"
        if ok:
            supers = attrs.get("isSuperuser") or attrs.get("issuperuser") or []
            return ("ok", {"is_superuser": any(s in _TRUE for s in supers)})
        return ("error", "bad_username_or_password")


class LdapAclSource(Source):
    type = "ldap"

    _ATTRS = {"publish": ("mqttPublishTopic", "mqttPubSubTopic"),
              "subscribe": ("mqttSubscriptionTopic", "mqttPubSubTopic")}

    def __init__(self, client, base_dn: str = "dc=emqx,dc=io",
                 filter_: Optional[str] = None) -> None:
        self.client = client
        self.base_dn = base_dn
        self.filter = filter_ or "(&(objectClass=mqttUser)(uid=${username}))"

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        try:
            entries = self.client.search(
                self.base_dn, _render(self.filter, ci),
                ("mqttPublishTopic", "mqttSubscriptionTopic",
                 "mqttPubSubTopic"))
        except Exception:     # noqa: BLE001
            return "ignore"
        for _dn, attrs in entries:
            low = {k.lower(): v for k, v in attrs.items()}
            for name in self._ATTRS.get(action, ()):
                for filt in low.get(name.lower(), []):
                    if _topic_match(filt, topic, ci):
                        return "allow"
        # an entry existed but granted nothing ⇒ this source denies
        return "deny" if entries else "ignore"
