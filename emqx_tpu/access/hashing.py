"""Password hashing — parity with
``apps/emqx_authn/src/emqx_authn_password_hashing.erl``.

Simple algorithms (plain/md5/sha/sha256/sha512 with salt position
prefix|suffix|disable) plus pbkdf2. bcrypt is delegated to the optional
``bcrypt`` wheel when present (the reference uses a C NIF); absent that,
creating bcrypt credentials raises — verification of foreign hashes is
then unavailable, mirroring how the reference gates the NIF.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass

try:  # optional accelerator, like the reference's bcrypt NIF
    import bcrypt as _bcrypt  # type: ignore
except Exception:  # pragma: no cover
    _bcrypt = None

_SIMPLE = {"plain", "md5", "sha", "sha256", "sha512"}
_DIGEST = {"md5": "md5", "sha": "sha1", "sha256": "sha256",
           "sha512": "sha512"}


@dataclass(frozen=True)
class HashSpec:
    name: str = "sha256"             # plain|md5|sha|sha256|sha512|pbkdf2|bcrypt
    salt_position: str = "prefix"    # prefix|suffix|disable (simple algos)
    mac_fun: str = "sha256"          # pbkdf2 PRF
    iterations: int = 4096           # pbkdf2
    dk_length: int = 32              # pbkdf2 derived-key bytes
    salt_rounds: int = 10            # bcrypt cost


def gen_salt(spec: HashSpec) -> bytes:
    if spec.name == "bcrypt":
        if _bcrypt is None:
            raise RuntimeError("bcrypt not available in this build")
        return _bcrypt.gensalt(rounds=spec.salt_rounds)
    if spec.name == "plain" or spec.salt_position == "disable":
        return b""
    return os.urandom(16).hex().encode()


def hash_password(spec: HashSpec, salt: bytes, password: bytes) -> bytes:
    if spec.name == "plain":
        return password
    if spec.name == "pbkdf2":
        return hashlib.pbkdf2_hmac(
            spec.mac_fun, password, salt, spec.iterations, spec.dk_length
        ).hex().encode()
    if spec.name == "bcrypt":
        if _bcrypt is None:
            raise RuntimeError("bcrypt not available in this build")
        return _bcrypt.hashpw(password, salt)
    if spec.name in _SIMPLE:
        if spec.salt_position == "prefix":
            data = salt + password
        elif spec.salt_position == "suffix":
            data = password + salt
        else:
            data = password
        return hashlib.new(_DIGEST[spec.name], data).hexdigest().encode()
    raise ValueError(f"unknown hash algorithm {spec.name!r}")


def check_password(
    spec: HashSpec, salt: bytes, stored: bytes, password: bytes
) -> bool:
    if spec.name == "bcrypt":
        if _bcrypt is None:
            return False
        try:
            return _bcrypt.checkpw(password, stored)
        except ValueError:
            return False
    return hmac.compare_digest(hash_password(spec, salt, password), stored)
