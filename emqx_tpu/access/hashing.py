"""Password hashing — parity with
``apps/emqx_authn/src/emqx_authn_password_hashing.erl``.

Simple algorithms (plain/md5/sha/sha256/sha512 with salt position
prefix|suffix|disable) plus pbkdf2 and bcrypt. bcrypt runs on the
in-repo C++ primitive (native/src/bcrypt.cc — the analogue of the
reference's bcrypt NIF, mix.exs:635), vector-tested against the
published OpenBSD/John-the-Ripper hashes; a bcrypt wheel, if present,
is preferred only as an independent cross-check surface for tests.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass

try:  # optional wheel — used as a differential oracle when present
    import bcrypt as _bcrypt  # type: ignore
except Exception:  # pragma: no cover
    _bcrypt = None


def _native_bcrypt():
    from emqx_tpu import native
    return native.load() if native.available() else None


def warm(spec: "HashSpec") -> None:
    """Pre-build the native library for bcrypt specs at provider
    construction time — the lazy path would otherwise run a multi-second
    g++ compile inside the first client's CONNECT handshake."""
    if spec.name == "bcrypt":
        _native_bcrypt()

_SIMPLE = {"plain", "md5", "sha", "sha256", "sha512"}
_DIGEST = {"md5": "md5", "sha": "sha1", "sha256": "sha256",
           "sha512": "sha512"}


@dataclass(frozen=True)
class HashSpec:
    name: str = "sha256"             # plain|md5|sha|sha256|sha512|pbkdf2|bcrypt
    salt_position: str = "prefix"    # prefix|suffix|disable (simple algos)
    mac_fun: str = "sha256"          # pbkdf2 PRF
    iterations: int = 4096           # pbkdf2
    dk_length: int = 32              # pbkdf2 derived-key bytes
    salt_rounds: int = 10            # bcrypt cost


def gen_salt(spec: HashSpec) -> bytes:
    if spec.name == "bcrypt":
        lib = _native_bcrypt()
        if lib is not None:
            import ctypes
            out = ctypes.create_string_buffer(32)
            rc = lib.emqx_bcrypt_gensalt(spec.salt_rounds,
                                         os.urandom(16), out)
            if rc != 0:
                raise ValueError(f"bad bcrypt cost {spec.salt_rounds}")
            return out.value
        if _bcrypt is None:
            raise RuntimeError("bcrypt not available in this build")
        return _bcrypt.gensalt(rounds=spec.salt_rounds)
    if spec.name == "plain" or spec.salt_position == "disable":
        return b""
    return os.urandom(16).hex().encode()


def hash_password(spec: HashSpec, salt: bytes, password: bytes) -> bytes:
    if spec.name == "plain":
        return password
    if spec.name == "pbkdf2":
        return hashlib.pbkdf2_hmac(
            spec.mac_fun, password, salt, spec.iterations, spec.dk_length
        ).hex().encode()
    if spec.name == "bcrypt":
        lib = _native_bcrypt()
        if lib is not None:
            import ctypes
            # the salt/settings prefix is the first 29 chars of a hash
            # or a gensalt() output ("$2b$NN$" + 22-char salt)
            setting = salt[:29]
            out = ctypes.create_string_buffer(64)
            rc = lib.emqx_bcrypt_hash(password, len(password),
                                      setting, out)
            if rc != 0:
                raise ValueError(f"bad bcrypt settings {setting!r}")
            return out.value
        if _bcrypt is None:
            raise RuntimeError("bcrypt not available in this build")
        return _bcrypt.hashpw(password, salt)
    if spec.name in _SIMPLE:
        if spec.salt_position == "prefix":
            data = salt + password
        elif spec.salt_position == "suffix":
            data = password + salt
        else:
            data = password
        return hashlib.new(_DIGEST[spec.name], data).hexdigest().encode()
    raise ValueError(f"unknown hash algorithm {spec.name!r}")


def check_password(
    spec: HashSpec, salt: bytes, stored: bytes, password: bytes
) -> bool:
    if spec.name == "bcrypt":
        lib = _native_bcrypt()
        if lib is not None:
            try:
                return hmac.compare_digest(
                    hash_password(spec, stored, password), stored)
            except ValueError:
                return False
        if _bcrypt is None:
            return False
        try:
            return _bcrypt.checkpw(password, stored)
        except ValueError:
            return False
    return hmac.compare_digest(hash_password(spec, salt, password), stored)
