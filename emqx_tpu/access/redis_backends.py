"""Redis-backed authn provider + authz source — the
``emqx_authn_redis.erl`` / ``emqx_authz_redis.erl`` analogues, over the
in-repo RESP client (emqx_tpu/connector/redis.py).

Authn: a command template (reference default
``HGETALL mqtt_user:${username}``) yields fields
``password_hash`` / ``salt`` / ``is_superuser``; the password check uses
the same HashSpec machinery as the built-in DB.

Authz: ``HGETALL mqtt_acl:${username}`` yields {topic-filter: action}
rows, folded as allow-on-match / ignore otherwise (redis ACL sources in
the reference can only *allow*; deny comes from the chain's no_match).
"""

from __future__ import annotations

from typing import Optional

from emqx_tpu.access.authn import Credential, Provider
from emqx_tpu.access.authz import ClientInfo, Source, _topic_match
from emqx_tpu.access.hashing import HashSpec, check_password
from emqx_tpu.connector.redis import RedisClient, RedisError


def render_cmd(template: list[str], cred: dict) -> list[str]:
    """``${username}``/``${clientid}``/... placeholder substitution."""
    binds = {}
    for key in ("username", "clientid", "password"):
        v = cred.get(key)
        if isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        binds[key] = v or ""
    # peerhost derives from the credential's peername "ip:port"
    peer = cred.get("peerhost") or str(cred.get("peername") or "")
    binds["peerhost"] = peer.rsplit(":", 1)[0]   # IPv6-safe
    out = []
    for part in template:
        for key, val in binds.items():
            part = part.replace("${" + key + "}", val)
        out.append(part)
    return out


def _pairs_to_dict(flat: Optional[list]) -> dict[str, str]:
    d: dict[str, str] = {}
    if flat:
        for i in range(0, len(flat) - 1, 2):
            k = flat[i].decode() if isinstance(flat[i], bytes) else flat[i]
            v = (flat[i + 1].decode()
                 if isinstance(flat[i + 1], bytes) else flat[i + 1])
            d[k] = v
    return d


class RedisAuthnProvider(Provider):
    id = "password_based:redis"

    def __init__(self, client: RedisClient,
                 cmd: Optional[list[str]] = None,
                 hash_spec: Optional[HashSpec] = None) -> None:
        self.client = client
        self.cmd = cmd or ["HGETALL", "mqtt_user:${username}"]
        self.hash_spec = hash_spec or HashSpec(name="plain")

    def authenticate(self, cred: Credential):
        try:
            flat = self.client.command(render_cmd(self.cmd, cred))
        except (OSError, ConnectionError, RedisError):
            return "ignore"       # backend down → next provider in chain
        row = _pairs_to_dict(flat)
        if not row or "password_hash" not in row:
            return "ignore"
        password = cred.get("password") or b""
        if isinstance(password, str):
            password = password.encode()
        salt = row.get("salt", "").encode()
        if check_password(self.hash_spec, salt,
                          row["password_hash"].encode(), password):
            return ("ok", {
                "is_superuser": row.get("is_superuser") in
                ("true", "1", "True")})
        return ("error", "bad_username_or_password")


class RedisAclSource(Source):
    type = "redis"

    def __init__(self, client: RedisClient,
                 cmd: Optional[list[str]] = None) -> None:
        self.client = client
        self.cmd = cmd or ["HGETALL", "mqtt_acl:${username}"]

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        try:
            flat = self.client.command(render_cmd(self.cmd, ci))
        except (OSError, ConnectionError, RedisError):
            return "ignore"
        rules = _pairs_to_dict(flat)
        for filt, allowed in rules.items():
            # placeholder-expanding match (devices/${clientid}/# rows),
            # same _feed substitution as the built-in ACL source
            if allowed in (action, "all") and _topic_match(filt, topic, ci):
                return "allow"
        return "ignore"
