"""Security layer (SURVEY.md §1 L7): authn chains, authz sources,
banned table, flapping detector, per-connection authz cache."""
