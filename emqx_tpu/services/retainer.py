"""Retained messages — parity with ``apps/emqx_retainer``.

Store: retained message per exact topic; empty payload deletes
(MQTT spec). Lookup is the *inverse* trie problem (SURVEY.md §7-6):
given a subscription filter, find all retained topic *names* matching
it. The reference builds word-position indices for this
(emqx_retainer_mnesia.erl / emqx_retainer_index.erl); this store goes
vectorized instead (VERDICT r3 #5 — the recursive Python name-trie
measured 2.9k lookups/sec at 100K retained):

- every retained topic is a row in a token matrix ``tok[N, L]`` (word
  ids via an interning vocab) with depth/$-flags in parallel arrays;
- a filter match is a handful of numpy comparisons over the candidate
  rows — ``+`` constrains nothing (depth covers it), a word constrains
  one column, a trailing ``#`` relaxes the depth equality;
- candidates come from a (level0, level1) prefix bucket when the
  filter's first two levels are literal (the common
  ``vendor/device/...`` shape — buckets cut 100K rows to the ~200
  sharing the prefix), else the whole matrix is scanned;
- each bucket IS a compact submatrix (token rows, depth, deadline,
  message/topic lists) maintained INCREMENTALLY on store/delete/expire
  — append and swap-with-last writes, amortized-doubling growth. The
  round-6 design rebuilt a per-bucket cache on the first lookup after
  any churn, which made exactly the lookup the reference's
  word-position index serves fast (first wildcard match after a churn
  burst) pay a ~10x rebuild cliff (BENCH_r05
  retained_lookups_per_sec_cold=11.7k vs 108k warm);
- topics deeper than ``MAX_LEVELS`` go to a tiny fallback dict walked
  with ``T.match`` (they are rare; correctness is preserved).

Broker wiring (same hookpoints as the reference):
- ``message.publish``      retain flag ⇒ store/delete (and deliver a copy)
- ``session.subscribed``   dispatch matching retained msgs per the
                           retain-handling (rh) subopt
TTL: per-message Message-Expiry-Interval plus a store-wide default;
expired entries are dropped lazily on read + via ``sweep()``.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message, now_ms

MAX_LEVELS = 16


class _Bucket:
    """One (level0-id, level1-id) prefix bucket: a compact, always-live
    submatrix of the retained-topic token matrix, position-aligned with
    its message/topic lists. Updated in place on every store/delete —
    append at ``n`` (amortized-doubling growth) and swap-with-last
    removal — so a lookup right after churn reads ready arrays instead
    of rebuilding a cache."""

    __slots__ = ("n", "tok", "depth", "deadline", "stored", "msgs",
                 "topics", "rows", "finite")

    def __init__(self, cap: int = 8):
        self.n = 0
        self.tok = np.zeros((cap, MAX_LEVELS), dtype=np.int32)
        self.depth = np.zeros(cap, dtype=np.int32)
        self.deadline = np.full(cap, np.inf)
        self.stored = np.zeros(cap, dtype=np.int64)
        self.msgs: list = []
        self.topics: list[str] = []
        self.rows: list[int] = []    # global row ids, position-aligned
        # sticky "a finite per-message deadline was ever seen": False
        # keeps the hit-dense one-extend fast path; deletes never clear
        # it (conservative)
        self.finite = False

    def append(self, row: int, tok_row, depth: int, deadline: float,
               stored: int, msg, topic: str) -> int:
        if self.n == self.tok.shape[0]:
            cap = self.n * 2
            for name in ("tok", "depth", "deadline", "stored"):
                old = getattr(self, name)
                new = np.full((cap,) + old.shape[1:],
                              np.inf if name == "deadline" else 0,
                              dtype=old.dtype)
                new[: self.n] = old
                setattr(self, name, new)
        pos = self.n
        self.tok[pos] = tok_row
        self.depth[pos] = depth
        self.deadline[pos] = deadline
        self.stored[pos] = stored
        self.msgs.append(msg)
        self.topics.append(topic)
        self.rows.append(row)
        if deadline != np.inf:
            self.finite = True
        self.n = pos + 1
        return pos

    def remove(self, pos: int) -> "int | None":
        """Swap-with-last removal; returns the global row id that moved
        INTO ``pos`` (the caller re-points its position map), or None."""
        last = self.n - 1
        moved = None
        if pos != last:
            self.tok[pos] = self.tok[last]
            self.depth[pos] = self.depth[last]
            self.deadline[pos] = self.deadline[last]
            self.stored[pos] = self.stored[last]
            self.msgs[pos] = self.msgs[last]
            self.topics[pos] = self.topics[last]
            self.rows[pos] = moved = self.rows[last]
        self.msgs.pop()
        self.topics.pop()
        self.rows.pop()
        self.n = last
        return moved


class Retainer:
    def __init__(self, max_retained: int = 0, default_expiry_ms: int = 0):
        self.max_retained = max_retained          # 0 = unlimited
        self.default_expiry_ms = default_expiry_ms
        self._lock = threading.RLock()
        self.dropped = 0
        # mirror observers (round 11): fired under the store lock as
        # ("set", topic, msg, effective_deadline_ms) on store/update and
        # ("del", topic, None, 0) on delete/expire — the native server
        # replicates the store into the host-side retained snapshot so
        # SUBSCRIBE-triggered delivery resolves below the GIL. Callbacks
        # must be non-blocking (they enqueue ops); this store remains
        # the oracle and the authority.
        self.observers: list = []
        self._count = 0               # live retained messages (incl. deep)
        # row-aligned store
        self._row_of: dict[str, int] = {}
        self._topics: list[str] = []
        self._msgs: list[Optional[Message]] = []
        self._stored: list[int] = []
        # per-row absolute expiry deadline (ms; inf = no msg expiry),
        # precomputed at store so match() can mask expiry vectorized
        # instead of calling msg.is_expired() per hit
        self._deadline = np.full(1024, np.inf)
        self._stored_np = np.zeros(1024, dtype=np.int64)
        self._vocab: dict[str, int] = {}          # word -> id >= 1
        cap = 1024
        self._tok = np.zeros((cap, MAX_LEVELS), dtype=np.int32)
        self._depth = np.zeros(cap, dtype=np.int32)
        self._dollar = np.zeros(cap, dtype=bool)
        self._alive = np.zeros(cap, dtype=bool)
        self._n = 0                   # rows used (live + tombstoned)
        self._dead = 0
        # (id0, id1) -> always-live compact submatrix, maintained
        # incrementally on store/delete/expire (no rebuild-on-miss);
        # _bpos maps a global row to its position inside its bucket
        self._bucket: dict[tuple[int, int], _Bucket] = {}
        self._bpos: dict[int, int] = {}
        # topics deeper than MAX_LEVELS: topic -> (msg, stored_at)
        self._deep: dict[str, tuple[Message, int]] = {}

    def __len__(self) -> int:
        return self._count

    # -- store -------------------------------------------------------------

    def on_publish(self, msg: Message) -> None:
        if not msg.retain:
            return
        if msg.payload:
            self.store(msg)
        else:
            self.delete(msg.topic)     # empty retained payload = clear

    def _eff_deadline_ms(self, msg: Message, stored_ms: int) -> int:
        """Fold the per-message expiry and the store default into ONE
        absolute wall-clock deadline (0 = never) — the number the
        native snapshot checks with a single compare."""
        dl = self._msg_deadline(msg)
        if self.default_expiry_ms:
            dl = min(dl, stored_ms + self.default_expiry_ms)
        return 0 if dl == float("inf") else int(dl)

    def _notify(self, op: str, topic: str, msg, deadline_ms: int) -> None:
        for fn in self.observers:
            try:
                fn(op, topic, msg, deadline_ms)
            except Exception:  # noqa: BLE001 — a mirror must never
                pass           # break the authoritative store

    def _wid(self, w: str) -> int:
        wid = self._vocab.get(w)
        if wid is None:
            wid = len(self._vocab) + 1
            self._vocab[w] = wid
        return wid

    def _grow(self) -> None:
        cap = self._tok.shape[0] * 2
        for name in ("_tok", "_depth", "_dollar", "_alive", "_deadline",
                     "_stored_np"):
            old = getattr(self, name)
            shape = (cap,) + old.shape[1:]
            fill = np.inf if name == "_deadline" else 0
            new = np.full(shape, fill, dtype=old.dtype)
            new[: self._n] = old[: self._n]
            setattr(self, name, new)

    def store(self, msg: Message, now: Optional[int] = None) -> bool:
        now = now_ms() if now is None else now
        topic = msg.topic
        kept = msg.set_header("retained", True)
        with self._lock:
            words = T.words(topic)
            if len(words) > MAX_LEVELS:
                if topic not in self._deep:
                    if self.max_retained and self._count >= self.max_retained:
                        self.dropped += 1
                        return False
                    self._count += 1
                self._deep[topic] = (kept, now)
                self._notify("set", topic, kept,
                             self._eff_deadline_ms(kept, now))
                return True
            row = self._row_of.get(topic)
            if row is not None:
                self._msgs[row] = kept
                self._stored[row] = now
                dl = self._msg_deadline(kept)
                self._deadline[row] = dl
                self._stored_np[row] = now
                # in-place bucket refresh at the row's known position
                b = self._bucket[(int(self._tok[row, 0]),
                                  int(self._tok[row, 1]))]
                pos = self._bpos[row]
                b.deadline[pos] = dl
                b.stored[pos] = now
                b.msgs[pos] = kept
                if dl != np.inf:
                    b.finite = True
                self._notify("set", topic, kept,
                             self._eff_deadline_ms(kept, now))
                return True
            if self.max_retained and self._count >= self.max_retained:
                self.dropped += 1
                return False       # table full: new topics rejected
            if self._n >= self._tok.shape[0]:
                self._grow()
            row = self._n
            self._n += 1
            ids = [self._wid(w) for w in words]
            self._tok[row, : len(ids)] = ids
            self._tok[row, len(ids):] = 0
            self._depth[row] = len(ids)
            self._dollar[row] = topic.startswith("$")
            self._alive[row] = True
            self._row_of[topic] = row
            self._topics.append(topic)
            self._msgs.append(kept)
            self._stored.append(now)
            dl = self._msg_deadline(kept)
            self._deadline[row] = dl
            self._stored_np[row] = now
            key = (ids[0], ids[1] if len(ids) > 1 else 0)
            b = self._bucket.get(key)
            if b is None:
                b = self._bucket[key] = _Bucket()
            self._bpos[row] = b.append(
                row, self._tok[row], len(ids), dl, now, kept, topic)
            self._count += 1
            self._notify("set", topic, kept,
                         self._eff_deadline_ms(kept, now))
            return True

    def delete(self, topic: str) -> bool:
        with self._lock:
            if topic in self._deep:
                del self._deep[topic]
                self._count -= 1
                self._notify("del", topic, None, 0)
                return True
            row = self._row_of.pop(topic, None)
            if row is None:
                return False
            self._alive[row] = False
            self._msgs[row] = None
            self._dead += 1
            self._count -= 1
            key = (int(self._tok[row, 0]), int(self._tok[row, 1]))
            b = self._bucket.get(key)
            pos = self._bpos.pop(row, None)
            if b is not None and pos is not None:
                moved = b.remove(pos)    # buckets hold live rows only
                if moved is not None:
                    self._bpos[moved] = pos
                if b.n == 0:
                    del self._bucket[key]
            # tombstones compact when they dominate — O(n) rebuild
            # amortized over >= n/2 deletes
            if self._dead > 1024 and self._dead * 2 > self._n:
                self._compact()
            self._notify("del", topic, None, 0)
            return True

    def _compact(self) -> None:
        live = [r for r in range(self._n) if self._alive[r]]
        topics = [self._topics[r] for r in live]
        msgs = [self._msgs[r] for r in live]
        stored = [self._stored[r] for r in live]
        for name in ("_depth", "_dollar", "_alive", "_deadline",
                     "_stored_np"):
            arr = getattr(self, name)
            arr[: len(live)] = arr[live]
        self._n = len(live)
        self._dead = 0
        self._topics = topics
        self._msgs = msgs
        self._stored = stored
        self._row_of = {t: i for i, t in enumerate(topics)}
        # rebuild the vocab from the survivors: without this, unique
        # topic-name churn (per-UUID topics) grows the intern dict
        # forever (the old trie pruned nodes on delete)
        self._vocab = {}
        self._tok[: self._n] = 0
        for i, t in enumerate(topics):
            ids = [self._wid(w) for w in T.words(t)]
            self._tok[i, : len(ids)] = ids
        self._bucket.clear()
        self._bpos.clear()
        for i, topic_i in enumerate(topics):
            key = (int(self._tok[i, 0]), int(self._tok[i, 1]))
            b = self._bucket.get(key)
            if b is None:
                b = self._bucket[key] = _Bucket()
            self._bpos[i] = b.append(
                i, self._tok[i], int(self._depth[i]),
                float(self._deadline[i]), int(self._stored_np[i]),
                self._msgs[i], topic_i)

    # -- inverse-trie lookup (vectorized) ------------------------------------

    def match(self, filt: str, now: Optional[int] = None) -> list[Message]:
        """All live retained messages whose topic matches ``filt``."""
        now = now_ms() if now is None else now
        fw = T.words(filt)
        out: list[Message] = []
        expired: list[str] = []
        with self._lock:
            self._match_rows(fw, now, out, expired)
            if self._deep:
                guard_dollar = fw[0] in (T.PLUS, T.HASH)
                for topic, (msg, stored_at) in list(self._deep.items()):
                    if guard_dollar and topic.startswith("$"):
                        continue
                    if T.match(topic, filt):
                        if self._msg_expired(msg, stored_at, now):
                            expired.append(topic)
                        else:
                            out.append(msg)
            for topic in expired:       # lazy expiry, same as the walk did
                self.delete(topic)
        return out

    def _match_rows(self, fw: list[str], now: int, out: list[Message],
                    expired: list[str]) -> None:
        n = self._n
        if n == 0:
            return
        has_hash = fw[-1] == T.HASH
        need = len(fw) - 1 if has_hash else len(fw)
        if need > MAX_LEVELS:
            # no array row is that deep (deep topics live in _deep,
            # matched by the caller's fallback walk) — and the literal
            # loops below must never index past the token matrix
            return
        # candidate narrowing: two literal leading levels hit a bucket
        # whose compact arrays are ALWAYS live (no rebuild-on-miss: the
        # round-6 lazy cache made the first lookup after churn pay ~10x)
        if len(fw) >= 2 and fw[0] not in (T.PLUS, T.HASH) \
                and fw[1] not in (T.PLUS, T.HASH):
            id0 = self._vocab.get(fw[0])
            id1 = self._vocab.get(fw[1])
            if id0 is None or id1 is None:
                return                    # no retained topic has the prefix
            b = self._bucket.get((id0, id1))
            if b is None:
                return
            n_b = b.n
            tok = b.tok[:n_b]
            depth = b.depth[:n_b]
            msgs = b.msgs
            mask = (depth >= need) if has_hash else (depth == need)
            # levels 0/1 == the bucket key; need<=MAX_LEVELS bounds i
            for i in range(2, min(len(fw), MAX_LEVELS)):
                w = fw[i]
                if w == T.HASH:
                    break
                if w == T.PLUS:
                    continue
                wid = self._vocab.get(w)
                if wid is None:
                    return                # literal word never stored
                mask &= tok[:, i] == wid
            if not b.finite and not self.default_expiry_ms:
                if mask.all():            # hit-dense fast path: one extend
                    out.extend(msgs)
                else:
                    out.extend([msgs[j] for j in np.nonzero(mask)[0].tolist()])
                return
            fresh = b.deadline[:n_b] > now
            if self.default_expiry_ms:
                fresh &= (now - b.stored[:n_b]) < self.default_expiry_ms
            stale = np.nonzero(mask & ~fresh)[0]
            hitj = np.nonzero(mask & fresh)[0]
            out.extend([msgs[j] for j in hitj.tolist()])
            expired.extend([b.topics[j] for j in stale.tolist()])
            return
        # full scan: wildcard in the first two levels
        tok = self._tok[:n]
        depth = self._depth[:n]
        mask = self._alive[:n] & (
            (depth >= need) if has_hash else (depth == need))
        if fw[0] in (T.PLUS, T.HASH):
            # MQTT 4.7.2: root wildcards never expose '$'-topics
            mask &= ~self._dollar[:n]
        for i, w in enumerate(fw[:MAX_LEVELS]):
            if w == T.HASH:
                break
            if w == T.PLUS:
                continue
            wid = self._vocab.get(w)
            if wid is None:
                return                    # literal word never stored
            mask &= tok[:, i] == wid
        # expiry is part of the mask: no per-hit Python calls on the
        # emission path (the workload is hit-bound — VERDICT r3 #5)
        fresh = self._deadline[:n] > now
        if self.default_expiry_ms:
            fresh &= (now - self._stored_np[:n]) < self.default_expiry_ms
        stale = np.nonzero(mask & ~fresh)[0]
        hits = np.nonzero(mask & fresh)[0]
        msgs = self._msgs
        out.extend([msgs[r] for r in hits.tolist()])
        if stale.size:
            topics = self._topics
            expired.extend([topics[r] for r in stale.tolist()])

    @staticmethod
    def _msg_deadline(msg: Message) -> float:
        interval = (msg.headers.get("properties") or {}).get(
            "Message-Expiry-Interval")
        if interval is None:
            return float("inf")
        return msg.timestamp + interval * 1000

    def _msg_expired(self, msg: Message, stored_at: int, now: int) -> bool:
        if msg.is_expired(now):
            return True
        if self.default_expiry_ms and now - stored_at >= self.default_expiry_ms:
            return True
        return False

    # -- maintenance ---------------------------------------------------------

    def sweep(self, now: Optional[int] = None) -> int:
        """Periodic clear of expired entries (emqx_retainer clear timer)."""
        now = now_ms() if now is None else now
        removed = 0
        with self._lock:
            victims = [
                self._topics[r]
                for r in range(self._n)
                if self._alive[r] and self._msgs[r] is not None
                and self._msg_expired(self._msgs[r], self._stored[r], now)
            ]
            victims.extend(
                t for t, (m, s) in self._deep.items()
                if self._msg_expired(m, s, now))
            for topic in victims:
                if self.delete(topic):
                    removed += 1
        return removed

    def topics(self) -> list[str]:
        with self._lock:
            out = [self._topics[r] for r in range(self._n)
                   if self._alive[r]]
            out.extend(self._deep)
            return out

    def mirror_attach(self, fn) -> None:
        """Atomically boot a mirror: replay the current store through
        ``fn`` as ("set", ...) events, then register it as an observer —
        all under the store lock. A store/delete racing the native
        server's boot mirror therefore either lands in the replay or
        fires the observer after it, in order; it can never fall in a
        gap (missed mutation) or apply out of order (a delete overtaken
        by a stale boot "set" would resurrect the topic)."""
        with self._lock:
            for topic, msg, dl in self.dump():
                fn("set", topic, msg, dl)
            self.observers.append(fn)

    def dump(self) -> list[tuple]:
        """Every live retained message as ``(topic, msg,
        effective_deadline_ms)`` — the native server's boot-time mirror
        snapshot (messages retained before the server started)."""
        with self._lock:
            out = []
            for r in range(self._n):
                if self._alive[r] and self._msgs[r] is not None:
                    out.append((self._topics[r], self._msgs[r],
                                self._eff_deadline_ms(self._msgs[r],
                                                      self._stored[r])))
            for topic, (msg, stored_at) in self._deep.items():
                out.append((topic, msg,
                            self._eff_deadline_ms(msg, stored_at)))
            return out
