"""Retained messages — parity with ``apps/emqx_retainer``.

Store: retained message per exact topic; empty payload deletes
(MQTT spec). Lookup is the *inverse* trie problem (SURVEY.md §7-6): given
a subscription filter, find all retained topic *names* matching it — a
name-trie walked under the filter's ``+``/``#`` branching (the reference
builds word-position indices for this, emqx_retainer_mnesia.erl /
emqx_retainer_index.erl; a name-trie gives the same pruning).

Broker wiring (same hookpoints as the reference):
- ``message.publish``      retain flag ⇒ store/delete (and deliver a copy)
- ``session.subscribed``   dispatch matching retained msgs per the
                           retain-handling (rh) subopt
TTL: per-message Message-Expiry-Interval plus a store-wide default;
expired entries are dropped lazily on read + via ``sweep()``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message, now_ms


@dataclass
class _Node:
    children: dict[str, "_Node"] = field(default_factory=dict)
    msg: Optional[Message] = None       # retained message ending here
    stored_at: int = 0


class Retainer:
    def __init__(self, max_retained: int = 0, default_expiry_ms: int = 0):
        self._root = _Node()
        self._count = 0
        self.max_retained = max_retained          # 0 = unlimited
        self.default_expiry_ms = default_expiry_ms
        self._lock = threading.RLock()
        self.dropped = 0

    def __len__(self) -> int:
        return self._count

    # -- store -------------------------------------------------------------

    def on_publish(self, msg: Message) -> None:
        if not msg.retain:
            return
        if msg.payload:
            self.store(msg)
        else:
            self.delete(msg.topic)     # empty retained payload = clear

    def store(self, msg: Message, now: Optional[int] = None) -> bool:
        now = now_ms() if now is None else now
        with self._lock:
            node = self._root
            path = []
            for w in T.words(msg.topic):
                node = node.children.setdefault(w, _Node())
                path.append(node)
            if node.msg is None:
                if self.max_retained and self._count >= self.max_retained:
                    self.dropped += 1
                    return False       # table full: new topics rejected
                self._count += 1
            # retained copies keep the retain flag when replayed
            node.msg = msg.set_header("retained", True)
            node.stored_at = now
            return True

    def delete(self, topic: str) -> bool:
        with self._lock:
            node = self._root
            path: list[tuple[_Node, str]] = []
            for w in T.words(topic):
                child = node.children.get(w)
                if child is None:
                    return False
                path.append((node, w))
                node = child
            if node.msg is None:
                return False
            node.msg = None
            self._count -= 1
            for parent, w in reversed(path):
                child = parent.children[w]
                if child.msg is None and not child.children:
                    del parent.children[w]
                else:
                    break
            return True

    # -- inverse-trie lookup -------------------------------------------------

    def match(self, filt: str, now: Optional[int] = None) -> list[Message]:
        """All live retained messages whose topic matches ``filt``."""
        now = now_ms() if now is None else now
        fw = T.words(filt)
        out: list[Message] = []
        with self._lock:
            self._expired_paths: list[str] = []
            self._walk(self._root, fw, 0, first_level=True, out=out, now=now)
            # lazily-expired entries prune their empty trie branches too
            # (delete() owns the pruning loop)
            for topic in self._expired_paths:
                self.delete(topic)
        return out

    def _expired(self, node: _Node, now: int) -> bool:
        msg = node.msg
        if msg.is_expired(now):
            return True
        if self.default_expiry_ms and now - node.stored_at >= self.default_expiry_ms:
            return True
        return False

    def _emit(self, node: _Node, out: list[Message], now: int) -> None:
        if node.msg is not None:
            if self._expired(node, now):
                self._expired_paths.append(node.msg.topic)
            else:
                out.append(node.msg)

    def _walk(self, node: _Node, fw: list[str], i: int,
              first_level: bool, out: list[Message], now: int) -> None:
        if i == len(fw):
            self._emit(node, out, now)
            return
        w = fw[i]
        if w == T.HASH:
            # '#' matches the parent level and everything below — but a
            # root wildcard must not expose '$'-topics (MQTT 4.7.2)
            self._emit(node, out, now)
            stack = [
                c for name, c in node.children.items()
                if not (first_level and name.startswith("$"))
            ]
            while stack:
                n = stack.pop()
                self._emit(n, out, now)
                stack.extend(n.children.values())
            return
        if w == T.PLUS:
            for name, child in node.children.items():
                if first_level and name.startswith("$"):
                    continue
                self._walk(child, fw, i + 1, False, out, now)
        else:
            child = node.children.get(w)
            if child is not None:
                self._walk(child, fw, i + 1, False, out, now)

    # -- maintenance ---------------------------------------------------------

    def sweep(self, now: Optional[int] = None) -> int:
        """Periodic clear of expired entries (emqx_retainer clear timer)."""
        now = now_ms() if now is None else now
        removed = 0
        with self._lock:
            victims = []
            walk = [(self._root, [])]
            while walk:
                node, path = walk.pop()
                if node.msg is not None and self._expired(node, now):
                    victims.append(T.join(path))
                for w, c in node.children.items():
                    walk.append((c, path + [w]))
            for topic in victims:
                if self.delete(topic):
                    removed += 1
        return removed

    def topics(self) -> list[str]:
        with self._lock:
            out = []
            walk = [(self._root, [])]
            while walk:
                node, path = walk.pop()
                if node.msg is not None:
                    out.append(T.join(path))
                for w, c in node.children.items():
                    walk.append((c, path + [w]))
            return out
