"""Topic rewrite — ``apps/emqx_modules/src/emqx_rewrite.erl`` analogue.

Rules: ``{action: publish|subscribe|all, source_topic: <filter>,
re: <regex>, dest_topic: <template>}``. A topic that matches the source
filter AND the regex is rewritten to dest with ``$1..$N`` regex captures
plus ``%c``/``%u`` client binds (emqx_rewrite.erl:146-175). First
matching rule wins; no re-chaining.

Hooks: ``client.subscribe`` / ``client.unsubscribe`` folds over the
topic-filter list, ``message.publish`` fold over the message.
"""

from __future__ import annotations

import re as _re
from dataclasses import dataclass
from typing import Optional

from emqx_tpu.core import topic as T


@dataclass
class RewriteRule:
    action: str            # publish | subscribe | all
    source_topic: str      # topic filter gating the rule
    re: str                # regex with capture groups
    dest_topic: str        # template with $1..$N, %c, %u
    _compiled: Optional[_re.Pattern] = None

    def compiled(self) -> _re.Pattern:
        if self._compiled is None:
            self._compiled = _re.compile(self.re)
        return self._compiled


class TopicRewrite:
    def __init__(self, rules: Optional[list[dict]] = None) -> None:
        self.pub_rules: list[RewriteRule] = []
        self.sub_rules: list[RewriteRule] = []
        # fired after add_rule/clear — the native host flushes its
        # publish permits so a new pub rewrite applies to topics that
        # were already fast-pathing (broker/native_server.py)
        self.on_topology_change: list = []
        for spec in rules or []:
            self.add_rule(**spec)

    def add_rule(self, action: str, source_topic: str, re: str,
                 dest_topic: str) -> None:
        rule = RewriteRule(action, source_topic, re, dest_topic)
        rule.compiled()                       # surface bad regexes early
        if action in ("publish", "all"):
            self.pub_rules.append(rule)
            # only pub rewrites affect publish permits; a subscribe-only
            # rule must not flush every publisher broker-wide
            for cb in self.on_topology_change:
                cb()
        if action in ("subscribe", "all"):
            self.sub_rules.append(rule)

    def clear(self) -> None:
        had_pub = bool(self.pub_rules)
        self.pub_rules.clear()
        self.sub_rules.clear()
        if had_pub:
            for cb in self.on_topology_change:
                cb()

    def replace(self, pub_rules: list, sub_rules: list) -> None:
        """Atomic swap-in of a validated rule set (the REST PUT path)
        — fires the topology callbacks the way add_rule/clear do."""
        changed = bool(self.pub_rules) or bool(pub_rules)
        self.pub_rules = pub_rules
        self.sub_rules = sub_rules
        if changed:
            for cb in self.on_topology_change:
                cb()

    # -- core ----------------------------------------------------------------

    @staticmethod
    def _rewrite(topic: str, rules: list[RewriteRule],
                 binds: dict[str, str]) -> str:
        for rule in rules:
            if not T.match(topic, rule.source_topic):
                continue
            m = rule.compiled().search(topic)
            if m is None:
                return topic              # filter hit, regex miss → as-is
            dest = rule.dest_topic
            for key, val in binds.items():
                dest = dest.replace(key, val or "")
            for i, cap in enumerate(m.groups(), start=1):
                dest = dest.replace(f"${i}", cap or "")
            return dest
        return topic

    @staticmethod
    def _binds(clientid: str, username: Optional[str]) -> dict[str, str]:
        return {"%c": clientid or "", "%u": username or ""}

    # -- hook callbacks ------------------------------------------------------

    def attach(self, hooks) -> None:
        hooks.add("message.publish", self._on_publish, priority=1000)
        hooks.add("client.subscribe", self._on_subscribe, priority=1000)
        hooks.add("client.unsubscribe", self._on_unsubscribe, priority=1000)

    def _on_publish(self, msg):
        if msg.sys or not self.pub_rules:
            return None
        binds = self._binds(msg.from_,
                            (msg.headers or {}).get("username"))
        new_topic = self._rewrite(msg.topic, self.pub_rules, binds)
        if new_topic != msg.topic:
            from dataclasses import replace
            return replace(msg, topic=new_topic)
        return None

    def _on_subscribe(self, ci: dict, props: dict, tfs):
        if not self.sub_rules:
            return None
        binds = self._binds(ci.get("clientid", ""), ci.get("username"))
        return [(self._rewrite(t, self.sub_rules, binds), opts)
                for t, opts in tfs]

    def _on_unsubscribe(self, ci: dict, props: dict, tfs):
        if not self.sub_rules:
            return None
        binds = self._binds(ci.get("clientid", ""), ci.get("username"))
        return [self._rewrite(t, self.sub_rules, binds) for t in tfs]

    def list(self) -> list[dict]:
        seen, out = set(), []
        for rule in self.pub_rules + self.sub_rules:
            key = id(rule)
            if key not in seen:
                seen.add(key)
                out.append({"action": rule.action,
                            "source_topic": rule.source_topic,
                            "re": rule.re, "dest_topic": rule.dest_topic})
        return out
