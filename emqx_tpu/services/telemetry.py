"""Telemetry report — ``apps/emqx_modules/src/emqx_telemetry.erl``.

Builds the periodic usage report (uuid, node/OS/version facts, broker
counters, enabled-feature inventory). Phone-home is OFF by default and
the transport is injectable — tests and air-gapped deployments read the
report locally (the reference posts the same JSON to its endpoint).
"""

from __future__ import annotations

import json
import platform
import time
import uuid as _uuid
from typing import Callable, Optional

REPORT_INTERVAL_S = 7 * 24 * 3600        # weekly, like the reference


class Telemetry:
    def __init__(self, app=None, enable: bool = False,
                 send_fn: Optional[Callable[[dict], None]] = None) -> None:
        self.app = app
        self.enable = enable
        self.send_fn = send_fn
        self.uuid = str(_uuid.uuid4())
        self.started_at = time.time()
        self._last_report_at = 0.0
        self.reports_sent = 0

    def build_report(self) -> dict:
        app = self.app
        report = {
            "uuid": self.uuid,
            "emqx_version": "5.0.14-tpu",
            "license": {"edition": "opensource"},
            "os_name": platform.system(),
            "os_version": platform.release(),
            "otp_version": platform.python_version(),   # runtime version
            "up_time": int(time.time() - self.started_at),
            "nodes_uuid": [],
            "active_plugins": [],
            "num_clients": 0,
            "messages_received": 0,
            "messages_sent": 0,
            "build_info": {"arch": platform.machine()},
            "vm_specs": {},
        }
        if app is not None:
            m = app.metrics
            report.update({
                "num_clients": sum(1 for _ in app.cm.all_channels()),
                "messages_received": m.val("messages.received"),
                "messages_sent": m.val("messages.sent"),
                "topic_count": len(app.broker.router.topics()),
                "rule_count": len(getattr(app.rules, "rules", {})),
                "bridge_count": len(getattr(app.bridges, "bridges", {})),
                "gateway_count": len(app.gateway.gateways),
                "retained_count": len(app.retainer),
            })
        return report

    def tick(self, now: Optional[float] = None) -> bool:
        """Send a report when due; returns True if one went out."""
        if not self.enable:
            return False
        now = time.time() if now is None else now
        if now - self._last_report_at < REPORT_INTERVAL_S:
            return False
        self._last_report_at = now
        report = self.build_report()
        if self.send_fn is not None:
            self.send_fn(report)
        self.reports_sent += 1
        return True

    def to_json(self) -> str:
        return json.dumps(self.build_report())
