"""Runtime-installable plugins — ``apps/emqx_plugins/`` analogue.

The reference installs tarballs of BEAM apps described by a
``release.json`` and starts them in configured order
(emqx_plugins.erl:297 package discovery, ensure_installed/started).
Here a plugin is a directory ``<install_dir>/<name>-<vsn>/`` holding:

- ``release.json`` — {"name", "rel_vsn", "description", ...}
- ``plugin.py``    — a module exposing ``on_start(app)`` / ``on_stop(app)``
  (hooks are the extension surface, exactly like reference plugins that
  register emqx_hooks callbacks on app start).

Position-ordered start (``ensure_enabled(name, position)``), per-plugin
enable/disable persisted in the manager's state list.
"""

from __future__ import annotations

import importlib.util
import json
import os
import shutil
import threading
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class Plugin:
    name_vsn: str                 # "<name>-<vsn>"
    dir: str
    info: dict = field(default_factory=dict)
    enabled: bool = False
    running: bool = False
    module: Any = None
    error: Optional[str] = None


class PluginManager:
    def __init__(self, app, install_dir: str) -> None:
        self.app = app
        self.install_dir = install_dir
        self.plugins: dict[str, Plugin] = {}
        self.order: list[str] = []            # start order
        self._lock = threading.RLock()

    # -- discovery / install -------------------------------------------------

    def _state_file(self) -> str:
        return os.path.join(self.install_dir, "plugins_state.json")

    def _save_state(self) -> None:
        """Persist enablement + order (the reference keeps this in the
        cluster config; we keep it beside the packages)."""
        try:
            with open(self._state_file(), "w", encoding="utf-8") as fh:
                json.dump({"states": [
                    {"name_vsn": n, "enabled": self.plugins[n].enabled}
                    for n in self.order if n in self.plugins
                ]}, fh)
        except OSError:
            pass

    def _load_state(self) -> None:
        try:
            with open(self._state_file(), "r", encoding="utf-8") as fh:
                states = json.load(fh).get("states", [])
        except (OSError, json.JSONDecodeError):
            return
        ordered = [s["name_vsn"] for s in states
                   if s["name_vsn"] in self.plugins]
        self.order = ordered + [n for n in self.order if n not in ordered]
        for s in states:
            p = self.plugins.get(s["name_vsn"])
            if p is not None:
                p.enabled = bool(s.get("enabled"))

    def scan(self) -> list[str]:
        """Discover installed packages (release.json probe, the
        emqx_plugins.erl:297 glob) and re-apply persisted enablement."""
        found = []
        if not os.path.isdir(self.install_dir):
            return found
        with self._lock:
            for entry in sorted(os.listdir(self.install_dir)):
                pdir = os.path.join(self.install_dir, entry)
                relf = os.path.join(pdir, "release.json")
                if not os.path.isfile(relf):
                    continue
                try:
                    with open(relf, "r", encoding="utf-8") as fh:
                        info = json.load(fh)
                except (OSError, json.JSONDecodeError):
                    continue
                if entry not in self.plugins:
                    self.plugins[entry] = Plugin(entry, pdir, info)
                    self.order.append(entry)
                found.append(entry)
            self._load_state()
        return found

    def ensure_installed(self, name_vsn: str) -> Plugin:
        self.scan()
        p = self.plugins.get(name_vsn)
        if p is None:
            raise ValueError(f"plugin {name_vsn} not found in "
                             f"{self.install_dir}")
        return p

    # -- enable / start ------------------------------------------------------

    def ensure_enabled(self, name_vsn: str,
                       position: Optional[int] = None) -> None:
        with self._lock:
            p = self.ensure_installed(name_vsn)
            p.enabled = True
            if position is not None:
                self.order.remove(name_vsn)
                self.order.insert(position, name_vsn)
            self._save_state()

    def ensure_disabled(self, name_vsn: str) -> None:
        with self._lock:
            p = self.plugins.get(name_vsn)
            if p is not None:
                p.enabled = False
                self._save_state()

    def _load_module(self, p: Plugin):
        if p.module is not None:
            return p.module
        path = os.path.join(p.dir, "plugin.py")
        spec = importlib.util.spec_from_file_location(
            f"emqx_plugin_{p.name_vsn.replace('-', '_').replace('.', '_')}",
            path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        p.module = mod
        return mod

    def ensure_started(self, name_vsn: Optional[str] = None) -> None:
        """Start one plugin, or every enabled plugin in order."""
        with self._lock:
            self.scan()             # one rescan, then plain lookups
            if name_vsn is not None:
                if name_vsn not in self.plugins:
                    raise ValueError(
                        f"plugin {name_vsn} not found in {self.install_dir}")
                targets = [name_vsn]
            else:
                targets = [n for n in self.order
                           if self.plugins[n].enabled]
            for n in targets:
                p = self.plugins[n]
                if p.running:
                    continue
                try:
                    mod = self._load_module(p)
                    if hasattr(mod, "on_start"):
                        mod.on_start(self.app)
                    p.running, p.error = True, None
                except Exception as e:  # noqa: BLE001 — isolate plugins
                    p.error = f"{type(e).__name__}: {e}"

    def ensure_stopped(self, name_vsn: Optional[str] = None) -> None:
        with self._lock:
            targets = ([name_vsn] if name_vsn
                       else list(reversed(self.order)))
            for n in targets:
                p = self.plugins.get(n)
                if p is None or not p.running:
                    continue
                try:
                    if p.module is not None and hasattr(p.module, "on_stop"):
                        p.module.on_stop(self.app)
                except Exception:
                    pass
                p.running = False

    def restart(self, name_vsn: str) -> None:
        with self._lock:
            self.ensure_stopped(name_vsn)
            self.ensure_started(name_vsn)

    def ensure_uninstalled(self, name_vsn: str, purge: bool = True) -> bool:
        """Stop, forget, and (by default) delete the package directory —
        without the purge a later scan() would re-discover it."""
        with self._lock:
            if name_vsn not in self.plugins:
                return False
            self.ensure_stopped(name_vsn)
            p = self.plugins.pop(name_vsn)
            if name_vsn in self.order:
                self.order.remove(name_vsn)
            self._save_state()
            if purge:
                shutil.rmtree(p.dir, ignore_errors=True)
            return True

    # -- introspection -------------------------------------------------------

    def list(self) -> list[dict]:
        with self._lock:
            return [self.describe(n) for n in self.order
                    if n in self.plugins]

    def describe(self, name_vsn: str) -> dict:
        with self._lock:
            p = self.plugins.get(name_vsn)
            if p is None:
                # concurrent uninstall: surface as not-found, not a crash
                raise ValueError(f"plugin {name_vsn} not installed")
            return {
                "name_vsn": p.name_vsn,
                "description": p.info.get("description", ""),
                "enabled": p.enabled,
                "running": p.running,
                **({"error": p.error} if p.error else {}),
            }
