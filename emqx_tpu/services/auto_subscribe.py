"""Server-side auto-subscribe — ``apps/emqx_auto_subscribe/``.

A configured list of topic templates (placeholders ``%c`` clientid,
``%u`` username, ``%h`` host, ``%p`` port) is subscribed on behalf of
every client at connect, through the channel's normal subscribe pipeline
(the reference messages the channel process with the topic tables so
authz and session bookkeeping all apply).
"""

from __future__ import annotations

from typing import Optional

from emqx_tpu.core import topic as T
from emqx_tpu.core.message import SubOpts

MAX_AUTO_SUBSCRIBE = 20      # reference cap


class AutoSubscribe:
    def __init__(self, app, topics: Optional[list[dict]] = None) -> None:
        self.app = app
        self.topics: list[dict] = []
        for spec in (topics or [])[:MAX_AUTO_SUBSCRIBE]:
            self.add(**spec)

    def add(self, topic: str, qos: int = 0, nl: int = 0, rh: int = 0,
            rap: int = 0) -> None:
        if len(self.topics) >= MAX_AUTO_SUBSCRIBE:
            raise ValueError("too many auto-subscribe topics")
        self.topics.append({"topic": topic, "qos": qos, "nl": nl,
                            "rh": rh, "rap": rap})

    def attach(self, hooks) -> None:
        hooks.add("client.connected", self._on_connected, priority=-500)

    def _on_connected(self, ci) -> None:
        if not self.topics:
            return
        cid = getattr(ci, "clientid", None) or (
            ci.get("clientid") if isinstance(ci, dict) else None)
        if not cid:
            return
        username = getattr(ci, "username", None) or (
            ci.get("username") if isinstance(ci, dict) else None)
        peer = str(getattr(ci, "peername", "") or
                   (ci.get("peername", "") if isinstance(ci, dict) else ""))
        host, _, port = peer.partition(":")
        ch = self.app.cm.lookup_channel(cid)
        binds = {"%c": cid, "%u": username or "", "%h": host, "%p": port}
        for spec in self.topics:
            topic = T.feed_var(spec["topic"], binds)
            if not T.validate_filter(topic):
                continue
            # same pipeline guarantees the channel's SUBSCRIBE has: the
            # client's mountpoint applies and the ACL chain can veto
            # (the reference routes auto-subscribe through the channel's
            # normal subscribe path for exactly this)
            if ch is not None and hasattr(ch, "_mount"):
                topic = ch._mount(topic)
            verdict = self.app.hooks.run_fold(
                "client.authorize",
                ({"clientid": cid, "username": username,
                  "peername": peer}, "subscribe", topic),
                "allow",
            )
            if verdict != "allow":
                continue
            opts = SubOpts(qos=spec["qos"], nl=spec["nl"],
                           rh=spec["rh"], rap=spec["rap"])
            # through the session when there is one (keeps resume state
            # coherent), else straight into the broker tables
            session = getattr(ch, "session", None)
            if session is not None:
                try:
                    session.subscribe(topic, opts)
                except Exception:
                    continue
            self.app.broker.subscribe(cid, topic, opts)
