"""Per-topic metrics — ``apps/emqx_modules/src/emqx_topic_metrics.erl``.

Operators register topic filters (bounded set, reference cap 512);
publishes/deliveries matching a registered filter bump its counters:
messages.in (+qosN.in breakdown), messages.out, messages.dropped.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from emqx_tpu.core import topic as T

MAX_TOPICS = 512


class TopicMetrics:
    def __init__(self, max_topics: int = MAX_TOPICS) -> None:
        self.max_topics = max_topics
        self._metrics: dict[str, dict[str, int]] = {}
        self._created: dict[str, float] = {}
        # fired after register/deregister — the native host flushes its
        # publish permits here so a freshly watched topic's messages
        # come back through Python immediately, not after permit-TTL
        self.on_topology_change: list = []
        self._lock = threading.RLock()

    # -- registry ------------------------------------------------------------

    def register(self, topic_filter: str) -> bool:
        if not T.validate_filter(topic_filter):
            raise ValueError(f"bad topic filter {topic_filter}")
        with self._lock:
            if topic_filter in self._metrics:
                return False
            if len(self._metrics) >= self.max_topics:
                raise ValueError("too many registered topics")
            self._metrics[topic_filter] = {
                "messages.in": 0, "messages.out": 0,
                "messages.qos0.in": 0, "messages.qos1.in": 0,
                "messages.qos2.in": 0, "messages.dropped": 0,
            }
            self._created[topic_filter] = time.time()
        for cb in self.on_topology_change:
            cb()
        return True

    def deregister(self, topic_filter: Optional[str] = None) -> bool:
        with self._lock:
            if topic_filter is None:
                changed = bool(self._metrics)
                self._metrics.clear()
                self._created.clear()
                hit = True
            else:
                self._created.pop(topic_filter, None)
                changed = hit = (
                    self._metrics.pop(topic_filter, None) is not None)
        if changed:               # fire only when something was removed
            for cb in self.on_topology_change:
                cb()
        return hit

    def topics(self) -> list[str]:
        with self._lock:          # snapshot: off-thread readers iterate
            return list(self._metrics)

    def metrics(self, topic_filter: str) -> Optional[dict[str, int]]:
        m = self._metrics.get(topic_filter)
        return dict(m) if m is not None else None

    def all(self) -> list[dict]:
        with self._lock:
            return [{"topic": t, "create_time": self._created.get(t, 0),
                     "metrics": dict(m)}
                    for t, m in self._metrics.items()]

    # -- counting ------------------------------------------------------------

    def _bump(self, topic: str, key: str, extra: Optional[str] = None
              ) -> None:
        with self._lock:
            for filt, m in self._metrics.items():
                if T.match(topic, filt):
                    m[key] += 1
                    if extra:
                        m[extra] += 1

    def attach(self, hooks) -> None:
        hooks.add("message.publish", self._on_publish, priority=-800)
        hooks.add("message.delivered", self._on_delivered, priority=-800)
        hooks.add("message.dropped", self._on_dropped, priority=-800)

    def _on_publish(self, msg):
        if not msg.sys:
            self._bump(msg.topic, "messages.in",
                       f"messages.qos{min(msg.qos, 2)}.in")
        return None

    def _on_delivered(self, clientid: str, topic: str) -> None:
        self._bump(topic, "messages.out")

    def _on_dropped(self, msg, *args) -> None:
        topic = msg if isinstance(msg, str) else msg.topic
        self._bump(topic, "messages.dropped")
