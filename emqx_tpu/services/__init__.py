from emqx_tpu.services.retainer import Retainer
from emqx_tpu.services.delayed import Delayed

__all__ = ["Retainer", "Delayed"]
