"""Delayed publish (``$delayed/<secs>/<topic>``) — parity with
``apps/emqx_modules/src/emqx_delayed.erl``.

A publish to ``$delayed/5/a/b`` is intercepted at the ``message.publish``
hookpoint, stored, and re-published to ``a/b`` after 5 seconds. Pure
scheduler core (heap by due time + explicit clock) so it runs under any
event loop; the server wires ``tick()`` into its housekeeping timer.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import Callable, Optional

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message, now_ms

PREFIX = "$delayed"
MAX_DELAY_S = 4294967  # emqx_delayed: seconds cap (~49.7 days)


def parse_delayed(topic: str) -> Optional[tuple[int, str]]:
    """'$delayed/5/a/b' → (5, 'a/b'); None if not a delayed topic."""
    ws = T.words(topic)
    if len(ws) < 3 or ws[0] != PREFIX:
        return None
    try:
        secs = int(ws[1])
    except ValueError:
        raise ValueError(f"invalid delay in {topic!r}")
    if not 0 <= secs <= MAX_DELAY_S:
        raise ValueError(f"delay out of range in {topic!r}")
    return secs, T.join(ws[2:])


class Delayed:
    def __init__(self, publish_fn: Callable[[Message], None],
                 max_delayed: int = 0):
        self.publish_fn = publish_fn
        self.max_delayed = max_delayed     # 0 = unlimited
        self._heap: list[tuple[int, int, Message]] = []
        self._seq = itertools.count()
        self._lock = threading.RLock()
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._heap)

    def attach(self, hooks: Hooks, priority: int = 100) -> None:
        hooks.add("message.publish", self._on_publish, priority=priority)

    def _on_publish(self, msg: Message):
        try:
            parsed = parse_delayed(msg.topic)
        except ValueError:
            # malformed client-controlled delay ('$delayed/xx/t'): drop the
            # single message, never crash the pipeline (reference behavior)
            self.dropped += 1
            return (Hooks.STOP, msg.set_header("allow_publish", False))
        if parsed is None:
            return None                     # not ours — continue the fold
        secs, real_topic = parsed
        self.store(msg, secs, real_topic)
        # stop the pipeline: the delayed message must not route now
        return (Hooks.STOP, msg.set_header("allow_publish", False))

    def store(self, msg: Message, secs: int, real_topic: str,
              now: Optional[int] = None) -> bool:
        now = now_ms() if now is None else now
        with self._lock:
            if self.max_delayed and len(self._heap) >= self.max_delayed:
                self.dropped += 1
                return False
            due = now + secs * 1000
            from dataclasses import replace
            heapq.heappush(
                self._heap,
                (due, next(self._seq), replace(msg, topic=real_topic)),
            )
            return True

    def tick(self, now: Optional[int] = None) -> int:
        """Publish everything due; returns the count."""
        now = now_ms() if now is None else now
        fired = 0
        while True:
            with self._lock:
                if not self._heap or self._heap[0][0] > now:
                    break
                _, _, msg = heapq.heappop(self._heap)
            self.publish_fn(msg)
            fired += 1
        return fired

    def next_due(self) -> Optional[int]:
        with self._lock:
            return self._heap[0][0] if self._heap else None

    def peek_topics(self) -> list[str]:
        with self._lock:
            return [m.topic for _, _, m in sorted(self._heap)]
