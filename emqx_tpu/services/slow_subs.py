"""Slow-subscriber tracking — ``apps/emqx_slow_subs/`` analogue.

Per-delivery latency (publish timestamp → delivery, the
``mark_begin_deliver`` stamp emqx_session.erl:908) feeds a bounded
top-K table of the slowest (clientid, topic) pairs; entries expire after
``expire_interval_s`` so the table reflects the recent window, exactly
the reference's moving top-K (emqx_slow_subs.erl).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional


@dataclass
class SlowEntry:
    clientid: str
    topic: str
    latency_ms: int
    last_update: float
    # which delivery plane observed the latency: "python" (the
    # delivery.completed hook, publish-ts -> delivery) or "native" (a
    # sampled C++ fast-path ack RTT, delivery write -> PUBACK/PUBCOMP —
    # kind-8 slow-ack records via broker/native_server.py). Before the
    # telemetry plane the native fast path was invisible here: a slow
    # native subscriber never ranked.
    plane: str = "python"


class SlowSubs:
    def __init__(self, threshold_ms: int = 500, top_k: int = 10,
                 expire_interval_s: float = 300.0,
                 enable: bool = True) -> None:
        self.enable = enable
        self.threshold_ms = threshold_ms
        self.top_k = top_k
        self.expire_interval_s = expire_interval_s
        self._table: dict[tuple[str, str], SlowEntry] = {}
        self._lock = threading.RLock()

    def attach(self, hooks) -> None:
        hooks.add("delivery.completed", self._on_delivery, priority=-900)

    def _on_delivery(self, clientid: str, topic: str,
                     latency_ms: int) -> None:
        self.record(clientid, topic, latency_ms)

    def record(self, clientid: str, topic: str, latency_ms: int,
               now: Optional[float] = None,
               plane: str = "python") -> None:
        if not self.enable or latency_ms < self.threshold_ms:
            return
        now = time.time() if now is None else now
        with self._lock:
            key = (clientid, topic)
            cur = self._table.get(key)
            if cur is None or latency_ms > cur.latency_ms:
                self._table[key] = SlowEntry(clientid, topic,
                                             latency_ms, now, plane)
            else:
                cur.last_update = now
            if len(self._table) > self.top_k:
                # evict the fastest of the slow (bounded top-K)
                worst = min(self._table.values(),
                            key=lambda e: e.latency_ms)
                del self._table[(worst.clientid, worst.topic)]

    def top(self) -> list[SlowEntry]:
        with self._lock:
            return sorted(self._table.values(),
                          key=lambda e: -e.latency_ms)

    def gc(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        with self._lock:
            dead = [k for k, e in self._table.items()
                    if now - e.last_update >= self.expire_interval_s]
            for k in dead:
                del self._table[k]
            return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._table.clear()

    def __len__(self) -> int:
        return len(self._table)
