// Durable-session message store: a segmented append-only log the C++
// host writes BELOW the GIL (host.cc FlushDurables) so a persistent
// session's subscription no longer collapses matching traffic onto the
// asyncio plane — the reference persists every matching publish +
// per-session unconsumed markers (emqx_persistent_session.erl:93-109,
// optionally RocksDB-backed) and replays them on clean_start=false
// resume (:275-310). SURVEY §5's discipline holds: "the HBM trie is a
// pure cache; persistence stays host-side" — this file IS that
// host-side disc slot (session/persistent.py names it), kept off the
// device and off the Python plane.
//
// On-disk format (little-endian), one file per segment
// ("<dir>/NNNNNNNN.seg", fixed-size, mmap-backed, zero-filled tail):
//
//   frame   = [u32 crc32][u32 len][payload: u8 type + body]
//             crc32 (IEEE, reflected) covers the whole payload; len is
//             the payload length. A zeroed/garbled frame header or a
//             crc mismatch ends the segment scan — exactly the torn-
//             tail-drop recovery a kill -9 mid-write needs, since the
//             mmap'd tail past the last full msync is undefined.
//   type 1  = MSG BATCH   [u64 base_guid][u64 ts_ms][u32 n] + n x entry
//             entry = [u64 origin][u8 flags][u16 ntok][u64 tok x ntok]
//                     [u16 tlen][topic]
//                     + (flags bit4 ? [u64 trace_id])
//                     + (flags bit5 ? [u8 cidlen][origin clientid])
//                     + (flags bit0 ? [u32 plen][payload]
//                                   : payload of the PREVIOUS entry)
//             guid of entry i = base_guid + i. flags: bit0 = payload
//             inline (the kind-6 dedup discipline), bits1-2 = qos,
//             bit3 = publisher DUP, bit4 = a sampled trace id follows
//             the topic (round 13: the native tracing plane persists
//             the id so a resume replay can re-join the trace), bit5 =
//             the publisher's clientid follows (round 18: no-local and
//             from_ attribution survive a restart — origin conn ids
//             are meaningless in the next life). The SAME bytes ride
//             up to Python as the kind-10 event payload — one buffer,
//             two sinks.
//   type 2  = CONSUME     [u32 n] + n x ([u64 token][u64 guid])
//   type 3  = REGISTER    [u64 token][u16 len][sid utf-8]
//   type 4  = REWRITE     like MSG BATCH but every entry is prefixed
//             [u64 guid] (explicit ids: GC compaction re-homes LIVE
//             messages from mostly-dead sealed segments, then unlinks
//             them; [u64 ts_ms] header, no base_guid)
//   type 5  = SESSION     [u64 token][u32 blen][body] — the session
//             catalog record (round 18): subscriptions + expiry
//             metadata the Python JSON DiskStore used to hold, keyed
//             by the sid's REGISTER token. blen 0 deletes the entry.
//             Newest record per token wins at recovery.
//   type 6  = UNREGISTER  [u64 token] — retires a REGISTER (session
//             expiry GC): the sid→token mapping, its SESSION record
//             and any leftover markers die with it, so a dead
//             session's records stop pinning segments.
//   type 7  = TRUNK       [u16 nlen][peer name][u64 seq][u8 tflags]
//             [record bytes] — one flushed-but-unacked trunk qos1
//             replay record (round 18: the per-peer unacked ring,
//             store-backed so kill -9 no longer loses it). Keyed by
//             the PEER NODE NAME (peer ids are minted per-process).
//             tflags bit0 = the record carries >= 1 trace id.
//   type 8  = TRUNK ACK   [u16 nlen][peer name][u64 seq] — the peer
//             acked that batch; seq UINT64_MAX drops the whole ring
//             (peer forgotten).
//
// REGISTER / SESSION / TRUNK records are LIVE state, not a log tail:
// they count toward their segment's live total, and GC re-journals the
// survivors forward (meta_rewrites) before unlinking a segment — a
// sid→token mapping must never die with an all-consumed segment.
//
// Recovery replays segments in id order; within a segment it stops at
// the first bad frame (no resync marker — by construction only the
// tail of the NEWEST segment can be torn, and the fuzz test pins that
// a corrupted record drops only itself and what follows it in that
// segment). Consume records for unknown guids are no-ops, which makes
// the segment-unlink GC safe: a message's consumes always live in
// segments >= its own.
//
// Threading: ONE mutex over everything. The host's poll thread appends
// one batch per flush; Python threads fetch/consume/gc concurrently
// (resume replay, ack-driven marker consumption, housekeep GC) — the
// ASan/TSan DRIVER_DURABLE hammers exactly this interleaving.
//
// fsync policy: 0 = never (page cache only), 1 = per append/consume
// (msync MS_SYNC — the PUBACK-after-store ordering in host.cc then
// gives real qos1 durability), 2 = interval (~100ms cadence).
#pragma once

#include <dirent.h>
#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "fault.h"

namespace emqx_native {
namespace store {

constexpr uint8_t kRecMsgBatch = 1;
constexpr uint8_t kRecConsume = 2;
constexpr uint8_t kRecRegister = 3;
constexpr uint8_t kRecRewrite = 4;
constexpr uint8_t kRecSession = 5;
constexpr uint8_t kRecUnregister = 6;
constexpr uint8_t kRecTrunk = 7;
constexpr uint8_t kRecTrunkAck = 8;

// TRUNK ACK seq sentinel: drop the named peer's whole ring.
constexpr uint64_t kTrunkDropAll = ~0ull;

constexpr int kFsyncNever = 0;
constexpr int kFsyncBatch = 1;
constexpr int kFsyncInterval = 2;
constexpr uint64_t kFsyncIntervalMs = 100;

// stat slots (emqx_store_stat; see native/__init__.py STORE_STAT_NAMES)
enum StoreStat {
  kSsAppends = 0,   // message entries appended
  kSsConsumed,      // (token, guid) markers consumed
  kSsPending,       // live markers right now (gauge)
  kSsMessages,      // live messages right now (gauge)
  kSsSegments,      // segment files right now (gauge)
  kSsGcSegments,    // segments unlinked by GC
  kSsRewrites,      // messages re-homed by GC compaction
  kSsTornDrops,     // records dropped at recovery (bad crc / torn tail)
  kSsBytes,         // payload bytes framed into the log
  kSsDegraded,      // mid-run segment-open/mmap failures: the store
                    // fell back to anonymous (non-durable) segments —
                    // Python warns, since PUBACK-after-store keeps
                    // asserting a durability this segment cannot give
  kSsReplayBytes,   // bytes handed back for replay (Fetch + TrunkFetch)
  kSsSessions,      // live SESSION catalog records (gauge)
  kSsTrunkPending,  // live trunk replay-ring records (gauge)
  kSsMetaRewrites,  // REGISTER/SESSION/TRUNK records re-homed by GC
  kSsStatCount
};

inline uint32_t Crc32(const char* data, size_t len) {
  // IEEE reflected CRC-32, nibble-table variant: small, no zlib dep
  static const uint32_t tbl[16] = {
      0x00000000, 0x1db71064, 0x3b6e20c8, 0x26d930ac,
      0x76dc4190, 0x6b6b51f4, 0x4db26158, 0x5005713c,
      0xedb88320, 0xf00f9344, 0xd6d6a3e8, 0xcb61b38c,
      0x9b64c2b0, 0x86d3d2d4, 0xa00ae278, 0xbdbdf21c};
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) {
    crc ^= static_cast<uint8_t>(data[i]);
    crc = tbl[crc & 0xF] ^ (crc >> 4);
    crc = tbl[crc & 0xF] ^ (crc >> 4);
  }
  return crc ^ 0xFFFFFFFFu;
}

inline uint64_t WallMs() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

struct Segment {
  uint32_t id = 0;
  int fd = -1;          // -1 for anonymous (in-memory) segments
  char* base = nullptr;
  size_t cap = 0;
  size_t end = 0;       // append offset
  uint32_t live = 0;    // live message records homed here
  // when this segment stopped being the active append target (0 =
  // still active / unknown): the age-based compaction trigger's clock
  uint64_t sealed_ms = 0;
};

struct StoredMsg {
  std::string topic;
  std::string payload;
  std::string cid;              // origin clientid ("" = unknown): the
                                // no-local / from_ attribution that
                                // must survive a restart (flags bit5)
  uint64_t origin = 0;
  uint64_t ts_ms = 0;
  uint64_t trace = 0;           // sampled trace id (0 = not sampled)
  uint8_t flags = 0;            // bits1-2 qos, bit3 dup (bit0 meaningless)
  uint32_t seg = 0;             // homing segment (GC bookkeeping)
  std::vector<uint64_t> toks;   // tokens still holding a marker
};

// One persisted trunk replay-ring entry (kRecTrunk).
struct TrunkRec {
  std::string bytes;            // the pre-framed qos1 wire record
  uint8_t flags = 0;            // bit0 = carries >= 1 trace id
  uint32_t seg = 0;             // homing segment (GC bookkeeping)
};

class DurableStore {
 public:
  // dir == "" runs on anonymous mmaps: the full durable PLANE (fast
  // path preserved, kind-10 delivery, replay within the process) minus
  // restart survival — the default when no store_dir is configured.
  // @locked(mu_) — construction precedes any concurrent caller
  DurableStore(std::string dir, size_t seg_bytes, int fsync_policy)
      : dir_(std::move(dir)),
        seg_bytes_(seg_bytes < 64 * 1024 ? 64 * 1024 : seg_bytes),
        fsync_(fsync_policy) {
    if (!dir_.empty()) {
      ::mkdir(dir_.c_str(), 0777);
      Recover();
    }
    if (segs_.empty()) Roll(seg_bytes_);
  }

  // @locked(mu_) — destruction outlives every concurrent caller
  ~DurableStore() {
    for (auto& [id, s] : segs_) {
      if (s.base) {
        if (s.fd >= 0 && fsync_ != kFsyncNever) SyncSeg(s);
        munmap(s.base, s.cap);
      }
      if (s.fd >= 0) close(s.fd);
    }
  }

  DurableStore(const DurableStore&) = delete;
  DurableStore& operator=(const DurableStore&) = delete;

  // Mid-run degradation flag (Roll flips it on the poll thread while
  // Python threads ask): locked like every other mu_-guarded read —
  // the unguarded return nativecheck surfaced was a real data race.
  bool ok() {
    std::lock_guard<std::mutex> lk(mu_);
    return ok_;
  }

  // The store's own faultline injector (fault.h): msync and
  // segment-open sites fire under mu_ like the real failures they
  // model; the host forwards store-site arms here. Thread-safe.
  fault::Injector* injector() { return &fault_; }

  // Age-based compaction trigger (round 15): sealed segments whose
  // live tail has sat past `ms` get re-homed regardless of the
  // thin-tail byte bound. 0 disables the trigger.
  void SetCompactAge(uint64_t ms) {
    std::lock_guard<std::mutex> lk(mu_);
    compact_age_ms_ = ms;
  }

  // sid -> stable token: returns the recovered token when the sid was
  // seen in a previous life (markers key on it), else registers a new
  // one durably. Thread-safe.
  // sid -> token WITHOUT creating one (0 = never registered): the
  // discard/drain paths must not mint-and-journal tokens for sessions
  // that never had durable state.
  uint64_t Lookup(const std::string& sid) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = token_of_.find(sid);
    return it == token_of_.end() ? 0 : it->second;
  }

  uint64_t Register(const std::string& sid) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = token_of_.find(sid);
    if (it != token_of_.end()) return it->second;
    uint64_t tok = next_token_++;
    JournalRegister(tok, sid);
    MaybeSync();
    return tok;
  }

  // Retire a REGISTER token (session-expiry GC): the sid→token
  // mapping, the SESSION catalog record, and any leftover markers die
  // with it — a dead session must not pin segments. Thread-safe.
  void Unregister(uint64_t token) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!sid_of_.count(token)) return;
    std::string body;
    AppendU64(&body, token);
    AppendFrame(kRecUnregister, body.data(), body.size());
    ApplyUnregister(token);
    MaybeSync();
  }

  // -- session catalog (round 18) -----------------------------------------
  // The subscription/expiry metadata the Python JSON DiskStore used to
  // hold: one SESSION record per token, newest wins, deleted with
  // blen 0. Thread-safe.

  void PutSession(uint64_t token, const char* body, uint32_t blen) {
    std::lock_guard<std::mutex> lk(mu_);
    JournalSession(token, body, blen);
    ApplySession(token, body, blen, active_ ? active_->id : 0);
    MaybeSync();
  }

  // All live SESSION records as a malloc'd blob of
  // [u64 token][u16 sidlen][sid][u32 blen][body] entries (the boot
  // walk). Returns the count.
  long FetchSessions(uint8_t** out, size_t* out_len) {
    std::lock_guard<std::mutex> lk(mu_);
    std::string blob;
    long n = 0;
    for (auto& [tok, rec] : sess_) {
      auto sit = sid_of_.find(tok);
      if (sit == sid_of_.end()) continue;
      AppendU64(&blob, tok);
      AppendU16(&blob, static_cast<uint16_t>(sit->second.size()));
      blob += sit->second;
      AppendU32(&blob, static_cast<uint32_t>(rec.body.size()));
      blob += rec.body;
      n++;
    }
    uint8_t* buf =
        static_cast<uint8_t*>(malloc(blob.size() ? blob.size() : 1));
    memcpy(buf, blob.data(), blob.size());
    *out = buf;
    *out_len = blob.size();
    return n;
  }

  // -- trunk replay ring (round 18) ---------------------------------------
  // The per-peer unacked qos1 ring, store-backed: kill -9 of a node no
  // longer loses it. Keyed by peer NODE NAME (peer ids are per-process).
  // Thread-safe (the host's poll thread is the only product caller,
  // but raw tests drive these from Python threads).

  void TrunkPut(const std::string& name, uint64_t seq, uint8_t tflags,
                const char* data, size_t len) {
    std::lock_guard<std::mutex> lk(mu_);
    JournalTrunk(name, seq, tflags, data, len);
    ApplyTrunk(name, seq, tflags, data, len,
               active_ ? active_->id : 0);
    MaybeSync();
  }

  void TrunkAck(const std::string& name, uint64_t seq) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = trunk_.find(name);
    if (it == trunk_.end()) return;
    if (seq != kTrunkDropAll && !it->second.count(seq)) return;
    std::string body;
    body.reserve(10 + name.size());
    AppendU16(&body, static_cast<uint16_t>(name.size()));
    body += name;
    AppendU64(&body, seq);
    AppendFrame(kRecTrunkAck, body.data(), body.size());
    ApplyTrunkAck(name, seq);
    MaybeSync();
  }

  // The named peer's persisted ring in seq order, as a malloc'd blob
  // of [u64 seq][u8 tflags][u32 len][record bytes] entries. Returns
  // the count — the host rebuilds its in-memory ring from this at
  // reconnect after a restart.
  long TrunkFetch(const std::string& name, uint8_t** out,
                  size_t* out_len) {
    std::lock_guard<std::mutex> lk(mu_);
    std::string blob;
    long n = 0;
    auto it = trunk_.find(name);
    if (it != trunk_.end()) {
      for (auto& [seq, rec] : it->second) {
        AppendU64(&blob, seq);
        blob.push_back(static_cast<char>(rec.flags));
        AppendU32(&blob, static_cast<uint32_t>(rec.bytes.size()));
        blob += rec.bytes;
        n++;
      }
    }
    stats_[kSsReplayBytes] += blob.size();
    uint8_t* buf =
        static_cast<uint8_t*>(malloc(blob.size() ? blob.size() : 1));
    memcpy(buf, blob.data(), blob.size());
    *out = buf;
    *out_len = blob.size();
    return n;
  }

  // Forget a peer's whole persisted ring (node left the cluster).
  void TrunkDrop(const std::string& name) { TrunkAck(name, kTrunkDropAll); }

  long TrunkPending(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = trunk_.find(name);
    return it == trunk_.end() ? 0 : static_cast<long>(it->second.size());
  }

  // Reserve n contiguous guids for the batch about to be appended (the
  // host stamps them into the kind-10 event header BEFORE AppendBatch).
  uint64_t AllocGuids(uint32_t n) {
    std::lock_guard<std::mutex> lk(mu_);
    uint64_t base = next_guid_;
    next_guid_ += n;
    return base;
  }

  // Append one MSG BATCH payload ([base_guid][ts][n] + entries, the
  // exact kind-10 event payload) and index its entries. Returns false
  // on a malformed payload (nothing written).
  bool AppendBatch(const char* payload, size_t len) {
    std::lock_guard<std::mutex> lk(mu_);
    if (len < 20) return false;
    uint64_t base_guid = RdU64(payload);
    uint64_t ts = RdU64(payload + 8);
    uint32_t n = RdU32(payload + 16);
    // index first (validates the layout), then frame the bytes
    std::vector<StoredMsg> parsed;
    parsed.reserve(n);
    if (!ParseEntries(payload + 20, len - 20, n, ts,
                      /*explicit_guids=*/false, nullptr, &parsed))
      return false;
    AppendFrame(kRecMsgBatch, payload, len);
    uint32_t seg = active_->id;
    for (uint32_t i = 0; i < n; i++) {
      IndexMsg(base_guid + i, std::move(parsed[i]), seg);
      stats_[kSsAppends]++;
    }
    if (base_guid + n > next_guid_) next_guid_ = base_guid + n;
    MaybeSync();
    return true;
  }

  // Single-message append (test surface + Python-plane callers).
  // ``cid``/``cl`` persist the publisher's clientid (flags bit5) so
  // no-local and from_ attribution survive a restart.
  uint64_t Append(uint64_t origin, uint8_t flags, const uint64_t* toks,
                  uint16_t ntok, const char* topic, uint16_t tlen,
                  const char* payload, uint32_t plen,
                  uint64_t trace = 0, const char* cid = nullptr,
                  uint8_t cl = 0) {
    if (cid == nullptr) cl = 0;
    std::string body;
    body.reserve(20 + 19 + 8 * ntok + tlen + 4 + plen + 1 + cl);
    // reserve the guid properly: a bare next_guid_ read could collide
    // with a concurrent AllocGuids from the host's flush
    AppendU64(&body, AllocGuids(1));
    AppendU64(&body, WallMs());
    AppendU32(&body, 1);
    AppendU64(&body, origin);
    body.push_back(static_cast<char>(flags | 1              // inline
                                     | (trace ? 0x10 : 0)
                                     | (cl ? 0x20 : 0)));
    AppendU16(&body, ntok);
    for (uint16_t i = 0; i < ntok; i++) AppendU64(&body, toks[i]);
    AppendU16(&body, tlen);
    body.append(topic, tlen);
    if (trace) AppendU64(&body, trace);
    if (cl) {
      body.push_back(static_cast<char>(cl));
      body.append(cid, cl);
    }
    AppendU32(&body, plen);
    body.append(payload, plen);
    uint64_t guid = RdU64(body.data());
    return AppendBatch(body.data(), body.size()) ? guid : 0;
  }

  // Consume markers; each hit is journaled. Thread-safe.
  uint32_t Consume(uint64_t token, const uint64_t* guids, uint32_t n) {
    std::lock_guard<std::mutex> lk(mu_);
    std::string body;
    uint32_t hits = 0;
    AppendU32(&body, 0);  // patched below
    for (uint32_t i = 0; i < n; i++) {
      if (ApplyConsume(token, guids[i])) {
        AppendU64(&body, token);
        AppendU64(&body, guids[i]);
        hits++;
      }
    }
    if (hits) {
      memcpy(&body[0], &hits, 4);
      AppendFrame(kRecConsume, body.data(), body.size());
      stats_[kSsConsumed] += hits;
      MaybeSync();
    }
    return hits;
  }

  // Pending messages for a token, guid order (= arrival order), as a
  // malloc'd blob of [u64 guid][u64 origin][u64 ts_ms][u8 flags]
  // [u16 tlen][topic] + (flags bit4 ? [u64 trace_id]) + (flags bit5 ?
  // [u8 cidlen][clientid]) + [u32 plen][payload] entries. Returns the
  // count.
  long Fetch(uint64_t token, uint8_t** out, size_t* out_len) {
    std::lock_guard<std::mutex> lk(mu_);
    std::string blob;
    long n = 0;
    auto pit = pending_.find(token);
    if (pit != pending_.end()) {
      for (auto& [guid, _] : pit->second) {
        auto mit = msgs_.find(guid);
        if (mit == msgs_.end()) continue;
        const StoredMsg& m = mit->second;
        AppendU64(&blob, guid);
        AppendU64(&blob, m.origin);
        AppendU64(&blob, m.ts_ms);
        blob.push_back(static_cast<char>((m.flags & 0x0E)
                                         | (m.trace ? 0x10 : 0)
                                         | (m.cid.empty() ? 0 : 0x20)));
        AppendU16(&blob, static_cast<uint16_t>(m.topic.size()));
        blob += m.topic;
        if (m.trace) AppendU64(&blob, m.trace);
        if (!m.cid.empty()) {
          blob.push_back(static_cast<char>(m.cid.size()));
          blob += m.cid;
        }
        AppendU32(&blob, static_cast<uint32_t>(m.payload.size()));
        blob += m.payload;
        n++;
      }
    }
    stats_[kSsReplayBytes] += blob.size();
    uint8_t* buf = static_cast<uint8_t*>(malloc(blob.size() ? blob.size() : 1));
    memcpy(buf, blob.data(), blob.size());
    *out = buf;
    *out_len = blob.size();
    return n;
  }

  long Pending(uint64_t token) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = pending_.find(token);
    return it == pending_.end() ? 0 : static_cast<long>(it->second.size());
  }

  // GC: unlink sealed all-consumed segments; when several sealed
  // segments hold only a thin live tail, re-home those messages into
  // the active segment (REWRITE record) and unlink the carcasses —
  // the "compaction of consumed markers" half of the contract.
  long Gc() {
    std::lock_guard<std::mutex> lk(mu_);
    long freed = 0;
    // pass 1: zero-live sealed segments go immediately
    for (auto it = segs_.begin(); it != segs_.end();) {
      Segment& s = it->second;
      if (&s != active_ && s.live == 0) {
        DropSeg(s);
        it = segs_.erase(it);
        freed++;
      } else {
        ++it;
      }
    }
    // pass 2: compaction — sealed segments whose combined live payload
    // is small get rewritten forward, then unlinked. Round 15 adds the
    // AGE trigger: a sealed segment whose live tail has sat past
    // compact_age_ms_ re-homes regardless of the thin-tail byte bound,
    // so one huge live message can no longer pin an otherwise-dead
    // segment across gc cycles forever (AppendFrame rolls as needed
    // when the aged rewrite exceeds the current segment's room).
    // Round 18: REGISTER/SESSION/TRUNK metadata counts as live too —
    // a segment whose only live records are metadata re-homes them
    // unconditionally (they are tiny) and unlinks, and any message
    // victim carrying metadata re-journals it before the unlink.
    if (segs_.size() > 1) {
      // hashed victim set: Gc holds the SAME mutex the poll thread's
      // FlushDurables needs (and FlushDirty orders PUBACKs behind it),
      // so these sweeps must stay O(M), never O(M*V)
      std::unordered_set<uint32_t> victims;
      std::unordered_set<uint32_t> aged;
      uint64_t now = WallMs();
      size_t live_bytes = 0, live_msgs = 0;
      // per-sealed-segment live MESSAGE counts: metadata-only segments
      // take the unconditional re-home path, not the thin/age rules
      std::unordered_map<uint32_t, size_t> seg_msgs;
      for (auto& [guid, m] : msgs_) seg_msgs[m.seg]++;
      std::unordered_set<uint32_t> meta_only;
      for (auto& [id, s] : segs_) {
        if (&s == active_ || s.live == 0) continue;
        if (seg_msgs.find(id) == seg_msgs.end()) {
          meta_only.insert(id);
          continue;
        }
        victims.insert(id);
        if (compact_age_ms_ && s.sealed_ms &&
            now >= s.sealed_ms + compact_age_ms_)
          aged.insert(id);
      }
      bool rewrote = false;
      if (!victims.empty()) {
        // per-segment live bytes alongside the combined totals (one
        // O(M) sweep): the age trigger needs each candidate's own
        // dead fraction, not just the pool-wide sum
        std::unordered_map<uint32_t, size_t> seg_live;
        for (auto& [guid, m] : msgs_) {
          if (victims.count(m.seg)) {
            size_t b = m.topic.size() + m.payload.size() + 64;
            live_bytes += b;
            seg_live[m.seg] += b;
            live_msgs++;
          }
        }
        // an aged segment is only a victim if it is MOSTLY DEAD (live
        // tail <= half its used bytes): the trigger exists for "one
        // live record pinning an otherwise-dead segment" — a fully
        // live sealed segment (a persistent subscriber's offline
        // backlog, the store's core workload) must NOT be re-homed
        // once a minute forever, and a freshly re-homed all-live
        // segment must not age straight back into the victim set
        for (auto it = aged.begin(); it != aged.end();) {
          auto sit = segs_.find(*it);
          if (sit == segs_.end() ||
              seg_live[*it] * 2 > sit->second.end)
            it = aged.erase(it);
          else
            ++it;
        }
        bool thin = victims.size() >= 2 && live_msgs &&
                    live_bytes < seg_bytes_ / 2;
        bool age_due = !aged.empty();
        if (!thin && age_due) {
          // age-triggered: re-home ONLY the expired mostly-dead
          // segments (a young sealed segment keeps waiting for the
          // thin-tail rule)
          victims.swap(aged);
          live_msgs = 0;
          for (auto& [guid, m] : msgs_)
            if (victims.count(m.seg)) live_msgs++;
        }
        if ((thin || age_due) && live_msgs) {
          std::string body;
          AppendU64(&body, WallMs());
          AppendU32(&body, static_cast<uint32_t>(live_msgs));
          for (auto& [guid, m] : msgs_) {
            if (!victims.count(m.seg)) continue;
            AppendU64(&body, guid);
            AppendU64(&body, m.origin);
            body.push_back(static_cast<char>(m.flags | 1));
            AppendU16(&body, static_cast<uint16_t>(m.toks.size()));
            for (uint64_t t : m.toks) AppendU64(&body, t);
            AppendU16(&body, static_cast<uint16_t>(m.topic.size()));
            body += m.topic;
            // bit4/bit5 survive in m.flags: recovery's ParseEntries
            // expects the trace id / clientid after the topic for
            // flagged entries
            if (m.flags & 0x10) AppendU64(&body, m.trace);
            if (m.flags & 0x20) {
              body.push_back(static_cast<char>(m.cid.size()));
              body += m.cid;
            }
            AppendU32(&body, static_cast<uint32_t>(m.payload.size()));
            body += m.payload;
          }
          AppendFrame(kRecRewrite, body.data(), body.size());
          uint32_t nseg = active_->id;
          for (auto& [guid, m] : msgs_) {
            if (victims.count(m.seg)) {
              m.seg = nseg;
              active_->live++;
              stats_[kSsRewrites]++;
            }
          }
          rewrote = true;
        } else {
          victims.clear();
        }
      }
      // unified unlink set: message victims (REWRITE written above)
      // plus metadata-only segments; live metadata homed in ANY of
      // them re-journals forward first — a sid→token mapping must
      // never die with its segment
      victims.insert(meta_only.begin(), meta_only.end());
      if (!victims.empty()) {
        rewrote = RehomeMeta(victims) || rewrote;
        // the REWRITE / re-journaled metadata must be ON DISK before
        // the victims are unlinked, regardless of the interval
        // cadence: a crash in the gap would lose records that were
        // already durably acked — strictly worse than the policy's
        // append-lag bound
        if (rewrote && active_ && active_->fd >= 0 &&
            fsync_ != kFsyncNever)
          SyncSeg(*active_);
        for (uint32_t id : victims) {
          auto it = segs_.find(id);
          if (it != segs_.end()) {
            DropSeg(it->second);
            segs_.erase(it);
            freed++;
          }
        }
      }
    }
    return freed;
  }

  int Sync() {
    std::lock_guard<std::mutex> lk(mu_);
    if (active_ && active_->fd >= 0) SyncSeg(*active_);
    return 0;
  }

  long Stat(int slot) {
    std::lock_guard<std::mutex> lk(mu_);
    if (slot < 0 || slot >= kSsStatCount) return -1;
    if (slot == kSsPending) {
      long n = 0;
      for (auto& [tok, m] : pending_) n += static_cast<long>(m.size());
      return n;
    }
    if (slot == kSsMessages) return static_cast<long>(msgs_.size());
    if (slot == kSsSegments) return static_cast<long>(segs_.size());
    if (slot == kSsSessions) return static_cast<long>(sess_.size());
    if (slot == kSsTrunkPending) {
      long n = 0;
      for (auto& [name, ring] : trunk_) n += static_cast<long>(ring.size());
      return n;
    }
    return static_cast<long>(stats_[slot]);
  }

 private:
  // -- little-endian scribblers -------------------------------------------
  static void AppendU16(std::string* b, uint16_t v) {
    b->append(reinterpret_cast<const char*>(&v), 2);
  }
  static void AppendU32(std::string* b, uint32_t v) {
    b->append(reinterpret_cast<const char*>(&v), 4);
  }
  static void AppendU64(std::string* b, uint64_t v) {
    b->append(reinterpret_cast<const char*>(&v), 8);
  }
  static uint16_t RdU16(const char* p) {
    uint16_t v;
    memcpy(&v, p, 2);
    return v;
  }
  static uint32_t RdU32(const char* p) {
    uint32_t v;
    memcpy(&v, p, 4);
    return v;
  }
  static uint64_t RdU64(const char* p) {
    uint64_t v;
    memcpy(&v, p, 8);
    return v;
  }

  // Decode n batch entries; explicit_guids covers the REWRITE layout
  // (guids written into *guids). Caller holds mu_; pure parsing into
  // locals, so it carries no lock annotation (nothing guarded is
  // touched — nativecheck's load-bearing contract).
  bool ParseEntries(const char* p, size_t len, uint32_t n, uint64_t ts,
                    bool explicit_guids, std::vector<uint64_t>* guids,
                    std::vector<StoredMsg>* out) {
    size_t pos = 0;
    const char* prev_pl = nullptr;
    uint32_t prev_len = 0;
    for (uint32_t i = 0; i < n; i++) {
      uint64_t guid = 0;
      if (explicit_guids) {
        if (pos + 8 > len) return false;
        guid = RdU64(p + pos);
        pos += 8;
      }
      if (pos + 11 > len) return false;
      StoredMsg m;
      m.origin = RdU64(p + pos);
      m.flags = static_cast<uint8_t>(p[pos + 8]);
      uint16_t ntok = RdU16(p + pos + 9);
      pos += 11;
      if (pos + 8ull * ntok + 2 > len) return false;
      m.toks.reserve(ntok);
      for (uint16_t k = 0; k < ntok; k++) {
        m.toks.push_back(RdU64(p + pos));
        pos += 8;
      }
      uint16_t tlen = RdU16(p + pos);
      pos += 2;
      if (pos + tlen > len) return false;
      m.topic.assign(p + pos, tlen);
      pos += tlen;
      if (m.flags & 0x10) {  // wire-v1 tracing extension (see header)
        if (pos + 8 > len) return false;
        m.trace = RdU64(p + pos);
        pos += 8;
      }
      if (m.flags & 0x20) {  // origin-clientid extension (round 18)
        if (pos + 1 > len) return false;
        uint8_t cl = static_cast<uint8_t>(p[pos]);
        pos += 1;
        if (pos + cl > len) return false;
        m.cid.assign(p + pos, cl);
        pos += cl;
      }
      if (m.flags & 1) {
        if (pos + 4 > len) return false;
        uint32_t pl = RdU32(p + pos);
        pos += 4;
        if (pos + pl > len) return false;
        m.payload.assign(p + pos, pl);
        prev_pl = p + pos;
        prev_len = pl;
        pos += pl;
      } else {
        if (!prev_pl) return false;  // dedup with no reference
        m.payload.assign(prev_pl, prev_len);
      }
      m.ts_ms = ts;
      if (guids) guids->push_back(guid);
      out->push_back(std::move(m));
    }
    return true;
  }

  // @locked(mu_)
  void IndexMsg(uint64_t guid, StoredMsg&& m, uint32_t seg) {
    if (m.toks.empty()) return;            // nothing to replay: skip
    if (msgs_.count(guid)) return;         // recovery: first record wins
    for (uint64_t tok : m.toks) pending_[tok][guid] = 1;
    m.seg = seg;
    auto sit = segs_.find(seg);
    if (sit != segs_.end()) sit->second.live++;
    stats_[kSsBytes] += m.topic.size() + m.payload.size();
    msgs_.emplace(guid, std::move(m));
  }

  // @locked(mu_) — clamped live-record counter delta for one segment
  void SegLive(uint32_t seg, int d) {
    auto it = segs_.find(seg);
    if (it == segs_.end()) return;
    if (d >= 0)
      it->second.live += static_cast<uint32_t>(d);
    else if (it->second.live >= static_cast<uint32_t>(-d))
      it->second.live -= static_cast<uint32_t>(-d);
    else
      it->second.live = 0;
  }

  // @locked(mu_) — journal + index one REGISTER record into the active
  // segment (fresh registration, recovery replays via ApplyRegister,
  // GC re-homes call this again)
  void JournalRegister(uint64_t tok, const std::string& sid) {
    std::string body;
    body.reserve(10 + sid.size());
    AppendU64(&body, tok);
    AppendU16(&body, static_cast<uint16_t>(sid.size()));
    body += sid;
    AppendFrame(kRecRegister, body.data(), body.size());
    ApplyRegister(tok, sid, active_ ? active_->id : 0);
  }

  // @locked(mu_)
  void ApplyRegister(uint64_t tok, const std::string& sid, uint32_t seg) {
    auto rit = reg_seg_.find(tok);
    if (rit != reg_seg_.end()) SegLive(rit->second, -1);
    token_of_[sid] = tok;
    sid_of_[tok] = sid;
    reg_seg_[tok] = seg;
    SegLive(seg, 1);
    if (tok >= next_token_) next_token_ = tok + 1;
  }

  // @locked(mu_)
  void ApplyUnregister(uint64_t tok) {
    auto sit = sid_of_.find(tok);
    if (sit != sid_of_.end()) {
      token_of_.erase(sit->second);
      sid_of_.erase(sit);
    }
    auto rit = reg_seg_.find(tok);
    if (rit != reg_seg_.end()) {
      SegLive(rit->second, -1);
      reg_seg_.erase(rit);
    }
    ApplySession(tok, nullptr, 0, 0);
    auto pit = pending_.find(tok);
    if (pit != pending_.end()) {
      std::vector<uint64_t> guids;
      guids.reserve(pit->second.size());
      for (auto& [g, _] : pit->second) guids.push_back(g);
      for (uint64_t g : guids) ApplyConsume(tok, g);
    }
  }

  // Callers hold mu_ (the AppendFrame caller-holds contract; this
  // helper touches no guarded field directly, so the checker derives
  // nothing from an annotation here). ONE serializer per record type,
  // shared by the fresh-write path and GC's RehomeMeta (a layout
  // change must never diverge between them — review finding).
  void JournalSession(uint64_t tok, const char* body, uint32_t blen) {
    std::string rec;
    rec.reserve(12 + blen);
    AppendU64(&rec, tok);
    AppendU32(&rec, blen);
    if (blen) rec.append(body, blen);
    AppendFrame(kRecSession, rec.data(), rec.size());
  }

  // callers hold mu_ (see JournalSession)
  void JournalTrunk(const std::string& name, uint64_t seq, uint8_t tf,
                    const char* data, size_t len) {
    std::string body;
    body.reserve(11 + name.size() + len);
    AppendU16(&body, static_cast<uint16_t>(name.size()));
    body += name;
    AppendU64(&body, seq);
    body.push_back(static_cast<char>(tf));
    body.append(data, len);
    AppendFrame(kRecTrunk, body.data(), body.size());
  }

  // @locked(mu_)
  void ApplySession(uint64_t tok, const char* body, uint32_t blen,
                    uint32_t seg) {
    auto it = sess_.find(tok);
    if (it != sess_.end()) {
      SegLive(it->second.seg, -1);
      sess_.erase(it);
    }
    if (blen == 0 || body == nullptr) return;
    SessRec r;
    r.body.assign(body, blen);
    r.seg = seg;
    SegLive(seg, 1);
    sess_.emplace(tok, std::move(r));
  }

  // @locked(mu_)
  void ApplyTrunk(const std::string& name, uint64_t seq, uint8_t tf,
                  const char* data, size_t len, uint32_t seg) {
    TrunkRec& r = trunk_[name][seq];
    if (!r.bytes.empty()) SegLive(r.seg, -1);  // superseded (recovery)
    r.bytes.assign(data, len);
    r.flags = tf;
    r.seg = seg;
    SegLive(seg, 1);
  }

  // @locked(mu_)
  void ApplyTrunkAck(const std::string& name, uint64_t seq) {
    auto it = trunk_.find(name);
    if (it == trunk_.end()) return;
    if (seq == kTrunkDropAll) {
      for (auto& [s, r] : it->second) SegLive(r.seg, -1);
      trunk_.erase(it);
      return;
    }
    auto rit = it->second.find(seq);
    if (rit == it->second.end()) return;
    SegLive(rit->second.seg, -1);
    it->second.erase(rit);
    if (it->second.empty()) trunk_.erase(it);
  }

  // @locked(mu_) — re-journal live REGISTER/SESSION/TRUNK records
  // homed in the victim segments into the active one (GC must never
  // unlink a sid→token mapping, a session catalog entry, or a trunk
  // replay record with the segment that happens to hold it). Returns
  // whether anything was journaled.
  bool RehomeMeta(const std::unordered_set<uint32_t>& victims) {
    bool any = false;
    for (auto& [tok, seg] : reg_seg_) {
      if (!victims.count(seg)) continue;
      auto sit = sid_of_.find(tok);
      if (sit == sid_of_.end()) continue;
      // updates reg_seg_'s VALUE in place (no rehash mid-iteration)
      JournalRegister(tok, sit->second);
      stats_[kSsMetaRewrites]++;
      any = true;
    }
    for (auto& [tok, rec] : sess_) {
      if (!victims.count(rec.seg)) continue;
      JournalSession(tok, rec.body.data(),
                     static_cast<uint32_t>(rec.body.size()));
      SegLive(rec.seg, -1);
      rec.seg = active_ ? active_->id : 0;
      SegLive(rec.seg, 1);
      stats_[kSsMetaRewrites]++;
      any = true;
    }
    for (auto& [name, ring] : trunk_) {
      for (auto& [seq, rec] : ring) {
        if (!victims.count(rec.seg)) continue;
        JournalTrunk(name, seq, rec.flags, rec.bytes.data(),
                     rec.bytes.size());
        SegLive(rec.seg, -1);
        rec.seg = active_ ? active_->id : 0;
        SegLive(rec.seg, 1);
        stats_[kSsMetaRewrites]++;
        any = true;
      }
    }
    return any;
  }

  // @locked(mu_)
  bool ApplyConsume(uint64_t token, uint64_t guid) {
    auto pit = pending_.find(token);
    if (pit == pending_.end() || !pit->second.erase(guid)) return false;
    if (pit->second.empty()) pending_.erase(pit);
    auto mit = msgs_.find(guid);
    if (mit != msgs_.end()) {
      auto& toks = mit->second.toks;
      toks.erase(std::remove(toks.begin(), toks.end(), token), toks.end());
      if (toks.empty()) {
        auto sit = segs_.find(mit->second.seg);
        if (sit != segs_.end() && sit->second.live) sit->second.live--;
        msgs_.erase(mit);
      }
    }
    return true;
  }

  // -- segments ------------------------------------------------------------

  // @locked(mu_) @blocking — open/ftruncate/mmap of a fresh segment
  // (amortized over a whole segment of appends; see waivers.py)
  void Roll(size_t min_bytes) {
    size_t cap = std::max(seg_bytes_, min_bytes);
    Segment s;
    s.id = next_seg_id_++;
    if (dir_.empty()) {
      s.base = static_cast<char*>(
          mmap(nullptr, cap, PROT_READ | PROT_WRITE,
               MAP_PRIVATE | MAP_ANONYMOUS, -1, 0));
    } else {
      char name[32];
      snprintf(name, sizeof(name), "/%08u.seg", s.id);
      std::string path = dir_ + name;
      // @fault(store_seg_open) — injected ENOSPC on the segment-open
      // seam: the real disk-full degradation machinery below runs
      bool inject = fault_.armed(fault::kSiteStoreSegOpen) &&
                    fault_.Fire(fault::kSiteStoreSegOpen) != 0;
      if (inject) errno = ENOSPC;
      s.fd = inject ? -1
                    : open(path.c_str(), O_RDWR | O_CREAT | O_CLOEXEC,
                           0644);
      if (s.fd < 0 || ftruncate(s.fd, static_cast<off_t>(cap)) != 0) {
        if (s.fd >= 0) close(s.fd);
        ok_ = false;
        // degrade to an anonymous segment so the plane keeps running —
        // COUNTED: the operator must learn restart survival is gone
        // (disk full etc.), since qos1 PUBACKs keep flowing
        stats_[kSsDegraded]++;
        s.fd = -1;
        s.base = static_cast<char*>(
            mmap(nullptr, cap, PROT_READ | PROT_WRITE,
                 MAP_PRIVATE | MAP_ANONYMOUS, -1, 0));
      } else {
        s.base = static_cast<char*>(
            mmap(nullptr, cap, PROT_READ | PROT_WRITE, MAP_SHARED,
                 s.fd, 0));
      }
    }
    if (s.base == MAP_FAILED) {
      s.base = nullptr;
      ok_ = false;
      stats_[kSsDegraded]++;
      return;
    }
    s.cap = cap;
    if (active_ && active_->fd >= 0 && fsync_ != kFsyncNever)
      SyncSeg(*active_);
    // the outgoing active segment is sealed NOW: the age-based
    // compaction clock starts here
    if (active_) active_->sealed_ms = WallMs();
    active_ = &segs_.emplace(s.id, s).first->second;
  }

  // @locked(mu_)
  void DropSeg(Segment& s) {
    if (s.base) munmap(s.base, s.cap);
    if (s.fd >= 0) {
      close(s.fd);
      char name[32];
      snprintf(name, sizeof(name), "/%08u.seg", s.id);
      unlink((dir_ + name).c_str());
    }
    stats_[kSsGcSegments]++;
  }

  // @locked(mu_)
  void AppendFrame(uint8_t type, const char* body, size_t blen) {
    size_t need = 8 + 1 + blen;
    if (!active_ || active_->end + need > active_->cap)
      Roll(need + 4096);
    // re-check the CAP too: a failed Roll (mmap exhaustion) leaves
    // active_ pointing at the old FULL segment, whose non-null base
    // alone would let the memcpy below write past the mapping
    if (!active_ || !active_->base || active_->end + need > active_->cap)
      return;  // allocation failed: drop (ok_/degraded already flag it)
    char* p = active_->base + active_->end;
    std::string payload;
    payload.reserve(1 + blen);
    payload.push_back(static_cast<char>(type));
    payload.append(body, blen);
    uint32_t crc = Crc32(payload.data(), payload.size());
    uint32_t len = static_cast<uint32_t>(payload.size());
    memcpy(p, &crc, 4);
    memcpy(p + 4, &len, 4);
    memcpy(p + 8, payload.data(), payload.size());
    active_->end += 8 + payload.size();
    dirty_ = true;
  }

  // @locked(mu_) @blocking — msync MS_SYNC is the fsync policy's disk
  // wait; the poll-plane path through FlushDurables is the documented
  // PUBACK-after-fsync contract (see waivers.py)
  void SyncSeg(Segment& s) {
    if (s.fd < 0 || !s.base) return;
    size_t pg = static_cast<size_t>(sysconf(_SC_PAGESIZE));
    size_t len = ((s.end + pg - 1) / pg) * pg;
    int rc;
    // @fault(store_msync) — injected EIO on the fsync seam; the REAL
    // msync return was previously ignored, which silently voided the
    // PUBACK-after-fsync contract on an erroring disk (round 15)
    if (fault_.armed(fault::kSiteStoreMsync) &&
        fault_.Fire(fault::kSiteStoreMsync)) {
      rc = -1;
      errno = EIO;
    } else {
      rc = msync(s.base, std::min(len, s.cap), MS_SYNC);
    }
    if (rc != 0) {
      // the durability this segment's PUBACKs assert is gone for the
      // failed stretch: count it (Python warns + folds the ledger) and
      // flip ok_ STICKY — a sealed segment whose sync failed is never
      // re-synced, so a later clean sync of a NEWER segment is no
      // evidence the failed stretch ever reached disk (review
      // finding); Roll's anonymous fallback is sticky the same way
      ok_ = false;
      stats_[kSsDegraded]++;
    }
    dirty_ = false;
  }

  // @locked(mu_)
  void MaybeSync() {
    if (!dirty_ || !active_ || active_->fd < 0) return;
    if (fsync_ == kFsyncBatch) {
      SyncSeg(*active_);
    } else if (fsync_ == kFsyncInterval) {
      uint64_t now = WallMs();
      if (now - last_sync_ms_ >= kFsyncIntervalMs) {
        last_sync_ms_ = now;
        SyncSeg(*active_);
      }
    }
  }

  // -- recovery ------------------------------------------------------------

  // @locked(mu_) @blocking — boot-time directory scan + mmap
  void Recover() {
    std::vector<uint32_t> ids;
    if (DIR* d = opendir(dir_.c_str())) {
      while (dirent* e = readdir(d)) {
        // exactly NNNNNNNN.seg — sscanf alone would accept any 12-char
        // name with a leading digit (its return value counts
        // conversions, not the literal suffix match), and a stray
        // editor backup must never be mmapped as a segment
        size_t nlen = strlen(e->d_name);
        if (nlen != 12 || strcmp(e->d_name + 8, ".seg") != 0) continue;
        bool digits = true;
        for (int i = 0; i < 8; i++)
          if (e->d_name[i] < '0' || e->d_name[i] > '9') digits = false;
        if (digits)
          ids.push_back(
              static_cast<uint32_t>(strtoul(e->d_name, nullptr, 10)));
      }
      closedir(d);
    }
    std::sort(ids.begin(), ids.end());
    for (uint32_t id : ids) {
      char name[32];
      snprintf(name, sizeof(name), "/%08u.seg", id);
      std::string path = dir_ + name;
      int fd = open(path.c_str(), O_RDWR | O_CLOEXEC);
      if (fd < 0) continue;
      struct stat st {};
      if (fstat(fd, &st) != 0 || st.st_size < 16) {
        close(fd);
        unlink(path.c_str());
        continue;
      }
      Segment s;
      s.id = id;
      s.fd = fd;
      s.cap = static_cast<size_t>(st.st_size);
      s.base = static_cast<char*>(
          mmap(nullptr, s.cap, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0));
      if (s.base == MAP_FAILED) {
        close(fd);
        continue;
      }
      // emplace BEFORE scanning: IndexMsg bumps seg_live through the
      // map, and a recovered segment missing from it would read live=0
      // — Gc() would then unlink segments still holding live messages
      Segment& ref = segs_.emplace(id, s).first->second;
      ScanSeg(&ref);
      if (id >= next_seg_id_) next_seg_id_ = id + 1;
      // the previous newest is sealed by this one arriving; its age
      // clock (compaction trigger) restarts at recovery — conservative
      if (active_) active_->sealed_ms = WallMs();
      active_ = &ref;  // newest scanned segment resumes as active
    }
    // resume appending AFTER the last valid frame of the newest segment
  }

  // @locked(mu_)
  void ScanSeg(Segment* s) {
    size_t pos = 0;
    while (pos + 9 <= s->cap) {
      uint32_t crc = RdU32(s->base + pos);
      uint32_t len = RdU32(s->base + pos + 4);
      if (len == 0 || len > s->cap - pos - 8) {
        // a zeroed header is the clean end of the log; anything else
        // is a torn partial write (e.g. truncation mid-frame)
        if (crc != 0 || len != 0) stats_[kSsTornDrops]++;
        break;
      }
      const char* payload = s->base + pos + 8;
      if (Crc32(payload, len) != crc) {
        stats_[kSsTornDrops]++;
        break;  // torn tail / corruption: drop this and the rest
      }
      ApplyRecord(static_cast<uint8_t>(payload[0]), payload + 1, len - 1,
                  s->id);
      pos += 8 + len;
    }
    s->end = pos;
  }

  // @locked(mu_)
  void ApplyRecord(uint8_t type, const char* body, size_t blen,
                   uint32_t seg) {
    if (type == kRecRegister && blen >= 10) {
      uint64_t tok = RdU64(body);
      uint16_t sl = RdU16(body + 8);
      if (10u + sl <= blen)
        ApplyRegister(tok, std::string(body + 10, sl), seg);
    } else if (type == kRecSession && blen >= 12) {
      uint64_t tok = RdU64(body);
      uint32_t bl = RdU32(body + 8);
      if (12u + bl <= blen) ApplySession(tok, body + 12, bl, seg);
    } else if (type == kRecUnregister && blen >= 8) {
      ApplyUnregister(RdU64(body));
    } else if (type == kRecTrunk && blen >= 11) {
      uint16_t nl = RdU16(body);
      if (2u + nl + 9 <= blen) {
        std::string name(body + 2, nl);
        uint64_t seq = RdU64(body + 2 + nl);
        uint8_t tf = static_cast<uint8_t>(body[2 + nl + 8]);
        ApplyTrunk(name, seq, tf, body + 2 + nl + 9,
                   blen - 2 - nl - 9, seg);
      }
    } else if (type == kRecTrunkAck && blen >= 10) {
      uint16_t nl = RdU16(body);
      if (2u + nl + 8 <= blen)
        ApplyTrunkAck(std::string(body + 2, nl), RdU64(body + 2 + nl));
    } else if (type == kRecMsgBatch && blen >= 20) {
      uint64_t base = RdU64(body);
      uint64_t ts = RdU64(body + 8);
      uint32_t n = RdU32(body + 16);
      std::vector<StoredMsg> parsed;
      if (ParseEntries(body + 20, blen - 20, n, ts, false, nullptr,
                       &parsed)) {
        for (uint32_t i = 0; i < n; i++)
          IndexMsg(base + i, std::move(parsed[i]), seg);
        if (base + n > next_guid_) next_guid_ = base + n;
      } else {
        stats_[kSsTornDrops]++;
      }
    } else if (type == kRecConsume && blen >= 4) {
      uint32_t n = RdU32(body);
      size_t pos = 4;
      for (uint32_t i = 0; i < n && pos + 16 <= blen; i++, pos += 16)
        ApplyConsume(RdU64(body + pos), RdU64(body + pos + 8));
    } else if (type == kRecRewrite && blen >= 12) {
      uint64_t ts = RdU64(body);
      uint32_t n = RdU32(body + 8);
      std::vector<StoredMsg> parsed;
      std::vector<uint64_t> guids;
      if (ParseEntries(body + 12, blen - 12, n, ts, true, &guids,
                       &parsed)) {
        for (uint32_t i = 0; i < n; i++) {
          IndexMsg(guids[i], std::move(parsed[i]), seg);
          if (guids[i] >= next_guid_) next_guid_ = guids[i] + 1;
        }
      }
    }
  }

  std::string dir_;        // immutable after construction
  size_t seg_bytes_;       // immutable after construction
  int fsync_;              // immutable after construction
  // faultline injector (all-atomic: arming never takes mu_; firing
  // happens under it with the syscall it replaces)
  fault::Injector fault_;
  uint64_t compact_age_ms_ = 60000;  // @guards(mu_) — 0 = age trigger off
  bool ok_ = true;         // @guards(mu_) — Roll flips it mid-run
  bool dirty_ = false;             // @guards(mu_)
  uint64_t last_sync_ms_ = 0;      // @guards(mu_)
  uint64_t next_guid_ = 1;         // @guards(mu_)
  uint64_t next_token_ = 1;        // @guards(mu_)
  uint32_t next_seg_id_ = 1;       // @guards(mu_)
  std::mutex mu_;
  // ordered: recovery + GC walk
  std::map<uint32_t, Segment> segs_;                        // @guards(mu_)
  Segment* active_ = nullptr;                               // @guards(mu_)
  std::unordered_map<std::string, uint64_t> token_of_;      // @guards(mu_)
  std::unordered_map<uint64_t, std::string> sid_of_;        // @guards(mu_)
  // token -> segment homing its current REGISTER record (GC re-home)
  std::unordered_map<uint64_t, uint32_t> reg_seg_;          // @guards(mu_)
  // session catalog (round 18): newest SESSION record per token
  struct SessRec {
    std::string body;
    uint32_t seg = 0;
  };
  std::unordered_map<uint64_t, SessRec> sess_;              // @guards(mu_)
  // trunk replay rings (round 18): peer name -> seq-ordered records
  std::unordered_map<std::string,
                     std::map<uint64_t, TrunkRec>> trunk_;  // @guards(mu_)
  std::unordered_map<uint64_t, StoredMsg> msgs_;            // @guards(mu_)
  // token -> ordered guid set (fetch replays in guid = arrival order)
  std::unordered_map<uint64_t,
                     std::map<uint64_t, uint8_t>> pending_; // @guards(mu_)
  uint64_t stats_[kSsStatCount] = {};                       // @guards(mu_)
};

}  // namespace store
}  // namespace emqx_native
