// Hierarchical timer wheel — the conn-scale plane's clock (round 16).
//
// Before this header every native-plane deadline was a SWEEP: the SN
// qos1 retransmit scan walked every tracked conn per poll cycle, the
// trunk ack watchdog walked every peer, and keepalive ran as a Python
// housekeep loop over ALL conns calling conn_idle_ms one by one — an
// O(N)-per-tick cost that is invisible at 10k conns and is THE
// bottleneck at the reference's headline scale (100M conns/cluster,
// PAPER.md § README:16; "1M mostly-idle devices per node" on the
// ROADMAP). This is the classic timing-wheel answer (Varghese &
// Lauck; the Linux timer wheel; Erlang's timer service behind the
// reference's keepalive): arm/cancel are O(1), and a poll cycle pays
// O(expired + cascades) — a million parked-and-silent conns cost the
// cycle nothing.
//
// Shape: kLevels levels of kSlots slots at kTickMs granularity.
// Level 0 spans 64 ticks (~1s at 16ms); each higher level is 64x
// coarser, so the horizon is ~3 days — clamped, never dropped. A
// timer lands in the coarsest-necessary level and CASCADES down one
// level each time the finer wheel completes a revolution; deadlines
// round UP to the next tick, so a timer never fires early and fires
// at most one tick late relative to the Advance() clock
// (tests/test_native_connscale.py pins this against a brute-force
// oracle at 10k timers).
//
// Ownership contract: one Wheel per shard Host, owned by that shard's
// poll thread like the match table — no locks, no atomics; control
// threads reach it only through the host's Op queue (ApplyPending).
// Handles are generation-checked (u32 index | u32 gen) so a stale
// cancel after the slot was recycled is a no-op, never a cross-timer
// cancellation: fire handlers routinely Drop() a conn whose OTHER
// timers expired in the same tick.
#pragma once

#include <cstdint>
#include <vector>

namespace emqx_native {
namespace wheel {

constexpr int kTickShift = 4;              // 16ms ticks
constexpr uint64_t kTickMs = 1ull << kTickShift;
constexpr int kSlotBits = 6;
constexpr int kSlots = 1 << kSlotBits;     // 64 slots per level
constexpr int kLevels = 4;                 // horizon 64^4 ticks ≈ 3.1d

class Wheel {
 public:
  explicit Wheel(uint64_t now_ms) : cur_(now_ms >> kTickShift) {
    for (int l = 0; l < kLevels; l++)
      for (int s = 0; s < kSlots; s++) slots_[l][s] = -1;
  }

  // Arm a timer: fire(key, kind) runs at the first Advance() whose
  // clock passes deadline_ms (never before it). Returns a nonzero
  // handle; the handle is CONSUMED by the fire (re-arm from the
  // handler) or released by Cancel().
  uint64_t Arm(uint64_t key, uint8_t kind, uint64_t deadline_ms) {
    int32_t i = AllocNode();
    Node& nd = pool_[i];
    nd.key = key;
    nd.kind = kind;
    nd.deadline = deadline_ms;
    Place(i, /*min_tick=*/cur_ + 1);
    armed_++;
    return (static_cast<uint64_t>(nd.gen) << 32) |
           (static_cast<uint32_t>(i) + 1);
  }

  // O(1) unlink. Generation-checked: a handle whose timer already
  // fired (or was cancelled) is a no-op even if the slot was reused.
  // @gen-checked
  bool Cancel(uint64_t h) {
    int32_t i = NodeOf(h);
    if (i < 0) return false;
    Unlink(i);
    FreeNode(i);
    armed_--;
    return true;
  }

  // Advance the wheel clock to now_ms, firing every expired timer
  // (handles auto-release before their fire runs, so handlers re-arm
  // freely). Handlers may Arm/Cancel other timers — including ones
  // expiring in this same batch, which then no-op on their lookup.
  template <class F>
  void Advance(uint64_t now_ms, F&& fire) {
    uint64_t target = now_ms >> kTickShift;
    while (cur_ < target) {
      cur_++;
      if ((cur_ & (kSlots - 1)) == 0) Cascade(1);
      int slot = static_cast<int>(cur_ & (kSlots - 1));
      int32_t i = slots_[0][slot];
      if (i < 0) continue;
      slots_[0][slot] = -1;
      scratch_.clear();
      while (i >= 0) {
        Node& nd = pool_[i];
        int32_t nx = nd.next;
        scratch_.push_back({nd.key, nd.kind});
        FreeNode(i);
        armed_--;
        i = nx;
      }
      for (const Due& d : scratch_) fire(d.key, d.kind);
    }
  }

  size_t armed() const { return armed_; }
  size_t pool_bytes() const { return pool_.capacity() * sizeof(Node); }

 private:
  struct Node {
    uint64_t key = 0;
    uint64_t deadline = 0;
    int32_t next = -1, prev = -1;
    int16_t slot = -1;      // level * kSlots + slot, -1 = detached
    uint8_t kind = 0;
    bool live = false;
    uint32_t gen = 1;
  };
  struct Due {
    uint64_t key;
    uint8_t kind;
  };

  // @gen-check — the ONE place a raw handle becomes a slot index:
  // the generation in the handle's high word must match the node's
  int32_t NodeOf(uint64_t h) const {
    if (!h) return -1;
    int32_t i = static_cast<int32_t>(h & 0xFFFFFFFFull) - 1;
    if (i < 0 || i >= static_cast<int32_t>(pool_.size())) return -1;
    const Node& nd = pool_[i];
    if (!nd.live || nd.gen != static_cast<uint32_t>(h >> 32)) return -1;
    return i;
  }

  int32_t AllocNode() {
    if (!free_.empty()) {
      int32_t i = free_.back();
      free_.pop_back();
      pool_[i].live = true;
      return i;
    }
    pool_.push_back(Node{});
    pool_.back().live = true;
    return static_cast<int32_t>(pool_.size() - 1);
  }

  // @gen-bump — recycling a slot MUST advance its generation
  void FreeNode(int32_t i) {
    Node& nd = pool_[i];
    nd.live = false;
    nd.gen++;                 // stale handles die here (ABA guard)
    nd.next = nd.prev = -1;
    nd.slot = -1;
    free_.push_back(i);
  }

  // Deadlines round UP to the owning tick (never early). `min_tick`
  // floors the placement: a fresh Arm cannot land before cur_ + 1
  // (that tick's slot already expired), while a CASCADE may re-place
  // a timer due exactly at cur_ — its level-0 slot expires later in
  // the same Advance step, so clamping it forward would fire one tick
  // late (the oracle caught exactly this off-by-one).
  void Place(int32_t i, uint64_t min_tick) {
    Node& nd = pool_[i];
    uint64_t t = (nd.deadline + kTickMs - 1) >> kTickShift;
    if (t < min_tick) t = min_tick;
    uint64_t delta = t - cur_;
    constexpr uint64_t kHorizon =
        1ull << (kSlotBits * kLevels);  // clamp, never drop
    if (delta >= kHorizon) t = cur_ + kHorizon - 1;
    int level = 0;
    while (level < kLevels - 1 &&
           (t - cur_) >= (1ull << (kSlotBits * (level + 1))))
      level++;
    int slot = static_cast<int>((t >> (kSlotBits * level)) & (kSlots - 1));
    nd.slot = static_cast<int16_t>(level * kSlots + slot);
    nd.prev = -1;
    nd.next = slots_[level][slot];
    if (nd.next >= 0) pool_[nd.next].prev = i;
    slots_[level][slot] = i;
  }

  void Unlink(int32_t i) {
    Node& nd = pool_[i];
    if (nd.slot < 0) return;
    if (nd.prev >= 0)
      pool_[nd.prev].next = nd.next;
    else
      slots_[nd.slot / kSlots][nd.slot % kSlots] = nd.next;
    if (nd.next >= 0) pool_[nd.next].prev = nd.prev;
    nd.slot = -1;
    nd.next = nd.prev = -1;
  }

  // One finer-wheel revolution completed: re-place the coarser level's
  // current slot down (timers now within the finer horizon descend;
  // recursion rolls further up when this level itself wrapped).
  void Cascade(int level) {
    if (level >= kLevels) return;
    int slot = static_cast<int>((cur_ >> (kSlotBits * level)) &
                                (kSlots - 1));
    if (slot == 0 && level + 1 < kLevels) Cascade(level + 1);
    int32_t i = slots_[level][slot];
    slots_[level][slot] = -1;
    while (i >= 0) {
      int32_t nx = pool_[i].next;
      pool_[i].next = pool_[i].prev = -1;
      pool_[i].slot = -1;
      Place(i, /*min_tick=*/cur_);
      i = nx;
    }
  }

  uint64_t cur_;
  size_t armed_ = 0;
  std::vector<Node> pool_;
  std::vector<int32_t> free_;
  std::vector<Due> scratch_;
  int32_t slots_[kLevels][kSlots];
};

// The ctypes parity surface (tests/test_native_connscale.py): runs a
// seeded op script against a fresh Wheel on the CALLER's thread and
// records every arm/cancel/advance/fire so the Python brute-force
// oracle can replay it exactly. Standalone — never touches a Host.
// @plane(control)
inline void SelfTestScript(uint64_t seed, uint32_t n_ops,
                           std::vector<uint8_t>* out) {
  auto put8 = [out](uint64_t v) {
    for (int i = 0; i < 8; i++)
      out->push_back(static_cast<uint8_t>((v >> (8 * i)) & 0xFF));
  };
  uint64_t x = seed ? seed : 0x9E3779B97F4A7C15ull;
  auto rnd = [&x]() {
    x ^= x >> 12;
    x ^= x << 25;
    x ^= x >> 27;
    return x * 0x2545F4914F6CDD1Dull;
  };
  uint64_t now = 1000000;
  Wheel w(now);
  std::vector<std::pair<uint64_t, uint64_t>> live;  // (key, handle)
  uint64_t next_key = 1;
  for (uint32_t op = 0; op < n_ops; op++) {
    uint64_t r = rnd();
    int what = static_cast<int>(r % 100);
    if (what < 55 || live.empty()) {
      uint64_t deadline = now + 1 + (rnd() % 200000);  // up to ~3.3min
      uint64_t key = next_key++;
      uint64_t h = w.Arm(key, 1, deadline);
      live.emplace_back(key, h);
      out->push_back(2);  // ARM record
      put8(key);
      put8(deadline);
    } else if (what < 70) {
      size_t pick = rnd() % live.size();
      out->push_back(3);  // CANCEL record
      put8(live[pick].first);
      w.Cancel(live[pick].second);
      live[pick] = live.back();
      live.pop_back();
    } else {
      now += rnd() % 30000;  // jump up to 30s (multi-level cascades)
      out->push_back(1);     // ADVANCE record
      put8(now);
      size_t fired_at = out->size();
      put8(0);  // fire-count placeholder
      uint64_t fired = 0;
      w.Advance(now, [&](uint64_t key, uint8_t) {
        put8(key);
        fired++;
        for (size_t i = 0; i < live.size(); i++)
          if (live[i].first == key) {
            live[i] = live.back();
            live.pop_back();
            break;
          }
      });
      for (int i = 0; i < 8; i++)
        (*out)[fired_at + i] =
            static_cast<uint8_t>((fired >> (8 * i)) & 0xFF);
    }
  }
  // final drain: every script deadline is <= now + 200000ms, so one
  // bounded jump past that flushes everything still armed
  now += 300000;
  out->push_back(1);
  put8(now);
  size_t fired_at = out->size();
  put8(0);
  uint64_t fired = 0;
  w.Advance(now, [&](uint64_t key, uint8_t) {
    put8(key);
    fired++;
  });
  for (int i = 0; i < 8; i++)
    (*out)[fired_at + i] = static_cast<uint8_t>((fired >> (8 * i)) & 0xFF);
}

}  // namespace wheel
}  // namespace emqx_native
