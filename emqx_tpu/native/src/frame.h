// Incremental MQTT frame splitter — the C++ twin of the Python
// Parser state machine in emqx_tpu/mqtt/frame.py (itself the analogue of
// the reference's varint remaining-length machine, emqx_frame.erl:163-217).
//
// This layer only *frames*: it finds packet boundaries and hands complete
// frames (fixed header byte + remaining-length + body) upward. Semantic
// packet parsing stays in Python / on device.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace emqx_native {

enum class FrameStatus : int {
  kOk = 0,
  kBadType = 1,       // fixed-header type nibble 0
  kVarintTooLong = 2, // >4 continuation bytes
  kTooLarge = 3,      // remaining length above max_size
};

// One connection's resumable framing state.
class Framer {
 public:
  explicit Framer(uint32_t max_size = 0x0FFFFFFF) : max_size_(max_size) {}

  // Feed a chunk; append each complete frame (header..body, verbatim
  // wire bytes) to `out`. Returns kOk or the first framing error, at
  // which point the connection must be dropped (state is poisoned).
  FrameStatus Feed(const uint8_t* data, size_t len,
                   std::vector<std::string>* out) {
    size_t pos = 0;
    while (pos < len) {
      switch (phase_) {
        case Phase::kHeader: {
          uint8_t h = data[pos++];
          if ((h >> 4) == 0) return FrameStatus::kBadType;
          frame_.clear();
          frame_.push_back(static_cast<char>(h));
          len_value_ = 0;
          len_mult_ = 1;
          phase_ = Phase::kLength;
          break;
        }
        case Phase::kLength: {
          uint8_t b = data[pos++];
          frame_.push_back(static_cast<char>(b));
          len_value_ += static_cast<uint32_t>(b & 0x7F) * len_mult_;
          if (b & 0x80) {
            if (len_mult_ > 128u * 128u * 128u)
              return FrameStatus::kVarintTooLong;
            len_mult_ *= 128;
          } else {
            if (len_value_ > max_size_) return FrameStatus::kTooLarge;
            need_ = len_value_;
            if (need_ == 0) {
              out->push_back(frame_);
              phase_ = Phase::kHeader;
            } else {
              phase_ = Phase::kBody;
            }
          }
          break;
        }
        case Phase::kBody: {
          size_t take = std::min(static_cast<size_t>(need_), len - pos);
          frame_.append(reinterpret_cast<const char*>(data + pos), take);
          pos += take;
          need_ -= static_cast<uint32_t>(take);
          if (need_ == 0) {
            out->push_back(frame_);
            frame_.clear();
            phase_ = Phase::kHeader;
          }
          break;
        }
      }
    }
    return FrameStatus::kOk;
  }

  // No partial frame buffered — the conn-scale park plane only
  // hibernates a conn whose framer sits at a packet boundary (a
  // parked conn's framer is dropped and rebuilt at inflation).
  bool idle() const { return phase_ == Phase::kHeader; }

 private:
  enum class Phase { kHeader, kLength, kBody };
  uint32_t max_size_;
  Phase phase_ = Phase::kHeader;
  std::string frame_;
  uint32_t len_value_ = 0;
  uint32_t len_mult_ = 1;
  uint32_t need_ = 0;
};

}  // namespace emqx_native
