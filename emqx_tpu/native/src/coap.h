// CoAP 1.0 (RFC 7252) for the native plane — the C++ twin of
// gateway/coap.py's Frame codec (which stays the asyncio oracle and
// the conformance reference; tests/test_native_coap.py drives BOTH
// planes through one shared vector set so the codecs cannot drift
// apart). Shared by host.cc (gateway side: datagram decode, CoAP<->
// MQTT translation, observe-notify encode) and loadgen.cc (client
// side: the CoAP publisher/observer fleet for the coap bench), so the
// two ends are framed by the same functions and a bug cannot hide
// behind a matching bug — the sn.h discipline applied to RFC 7252.
//
// Wire shape (RFC 7252 §3): ONE datagram carries ONE message —
//   [ver:2 type:2 tkl:4][code u8][mid u16 BE][token 0-8B]
//   [options: (delta:4 len:4)[ext-delta][ext-len][value]...]
//   [0xFF payload]
// Parse/serialize behaviors mirror the oracle EXACTLY, including its
// edge handling: options whose declared length overruns the datagram
// yield a clamped (short) value; a 13/14 length/delta extension byte
// past the end voids the message (the oracle raises mid-parse and the
// UDP listener drops the datagram); serialization emits options in
// stable number order with minimal 13/269 extensions.
#pragma once

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <vector>

namespace emqx_native {
namespace coap {

// message types (§3)
constexpr uint8_t kCon = 0;
constexpr uint8_t kNon = 1;
constexpr uint8_t kAck = 2;
constexpr uint8_t kRst = 3;

// method / response codes (class.detail -> byte), the oracle's set
constexpr uint8_t kEmpty = 0x00;
constexpr uint8_t kGet = 0x01;
constexpr uint8_t kPost = 0x02;
constexpr uint8_t kPut = 0x03;
constexpr uint8_t kDelete = 0x04;
constexpr uint8_t kCreated = 0x41;   // 2.01
constexpr uint8_t kDeleted = 0x42;   // 2.02
constexpr uint8_t kValid = 0x43;     // 2.03
constexpr uint8_t kChanged = 0x44;   // 2.04
constexpr uint8_t kContent = 0x45;   // 2.05
constexpr uint8_t kBadRequest = 0x80;    // 4.00
constexpr uint8_t kUnauthorized = 0x81;  // 4.01
constexpr uint8_t kNotFound = 0x84;      // 4.04
constexpr uint8_t kNotAllowed = 0x85;    // 4.05

// option numbers (§5.10 + RFC 7959/7641)
constexpr uint16_t kOptEtag = 4;
constexpr uint16_t kOptObserve = 6;
constexpr uint16_t kOptLocationPath = 8;
constexpr uint16_t kOptUriPath = 11;
constexpr uint16_t kOptContentFormat = 12;
constexpr uint16_t kOptUriQuery = 15;
constexpr uint16_t kOptBlock2 = 23;
constexpr uint16_t kOptBlock1 = 27;
constexpr uint16_t kOptSize2 = 28;
constexpr uint16_t kOptSize1 = 60;

// transport-machine constants (§4.8, the oracle's TransportManager):
// CON retransmit starts at ACK_TIMEOUT x ACK_RANDOM_FACTOR = 3s and
// doubles per try; MAX_RETRANSMIT tries then give-up. The dedup window
// is EXCHANGE_LIFETIME for CON requests, NON_LIFETIME for NONs.
constexpr uint64_t kAckTimeoutMs = 3000;  // 2.0s x 1.5 (oracle values)
constexpr uint8_t kMaxRetransmit = 4;
constexpr uint64_t kExchangeLifetimeMs = 247000;
constexpr uint64_t kNonLifetimeMs = 145000;

// The host frames outbound CoAP messages in its per-conn outbuf with a
// u16 length prefix (CoAP messages are not self-delimiting; the
// datagram boundary is the delimiter, re-established at flush), so no
// message may exceed 65535 wire bytes — also comfortably under the
// 65507-byte UDP payload ceiling. Deliveries that cannot fit are
// DROPPED at the translation seam (the sn.h oversize discipline):
// notify overhead = 4 (header) + 8 (token) + 4 (observe option) + 1
// (payload marker).
constexpr size_t kMaxMessage = 0xFFFF;
constexpr size_t kMaxPayload = kMaxMessage - 17;

struct CoapMsg {
  uint8_t type = kCon;
  uint8_t code = kEmpty;
  uint16_t mid = 0;
  std::string token;                                   // 0-8 bytes
  std::vector<std::pair<uint32_t, std::string>> options;
  std::string payload;

  const std::string* Opt(uint32_t number) const {
    for (const auto& [n, v] : options)
      if (n == number) return &v;
    return nullptr;
  }
};

// Decode one datagram. Mirrors the oracle's Frame.parse exactly:
// false = the datagram yields no message (short header, version != 1,
// tkl > 8, or a truncated 13/14 extension byte — where the oracle
// raises and its UDP listener drops the datagram).
inline bool Parse(const uint8_t* d, size_t len, CoapMsg* m) {
  if (len < 4) return false;
  uint8_t b0 = d[0];
  if ((b0 >> 6) != 1) return false;
  uint8_t tkl = b0 & 0xF;
  if (tkl > 8) return false;
  m->type = (b0 >> 4) & 0x3;
  m->code = d[1];
  m->mid = static_cast<uint16_t>((d[2] << 8) | d[3]);
  size_t off = 4;
  // a short token clamps like the oracle's slice (off stays in range)
  size_t tk = std::min<size_t>(tkl, len - off);
  m->token.assign(reinterpret_cast<const char*>(d + off), tk);
  off += tkl;
  m->options.clear();
  m->payload.clear();
  if (off > len) return true;  // token overran: no options, no payload
  uint32_t number = 0;
  while (off < len && d[off] != 0xFF) {
    uint32_t delta = d[off] >> 4;
    uint32_t ln = d[off] & 0xF;
    off += 1;
    // 13/14 extensions; a missing extension byte voids the message
    // (struct.unpack_from raises in the oracle)
    if (delta == 13) {
      if (off >= len) return false;
      delta = d[off] + 13;
      off += 1;
    } else if (delta == 14) {
      if (off + 2 > len) return false;
      delta = static_cast<uint32_t>((d[off] << 8) | d[off + 1]) + 269;
      off += 2;
    }
    if (ln == 13) {
      if (off >= len) return false;
      ln = d[off] + 13;
      off += 1;
    } else if (ln == 14) {
      if (off + 2 > len) return false;
      ln = static_cast<uint32_t>((d[off] << 8) | d[off + 1]) + 269;
      off += 2;
    }
    number += delta;
    // a value overrunning the datagram yields a clamped short value
    // and ends the scan (Python slice semantics: off jumps past len)
    size_t avail = off < len ? std::min<size_t>(ln, len - off) : 0;
    m->options.emplace_back(
        number,
        std::string(reinterpret_cast<const char*>(d + off), avail));
    off += ln;
  }
  if (off < len) {  // stopped at the 0xFF payload marker
    m->payload.assign(reinterpret_cast<const char*>(d + off + 1),
                      len - off - 1);
  }
  return true;
}

inline uint8_t ExtNibble(uint32_t value) {
  if (value < 13) return static_cast<uint8_t>(value);
  return value < 269 ? 13 : 14;
}

inline void PutExtBytes(std::string* out, uint32_t value) {
  if (value < 13) return;
  if (value < 269) {
    out->push_back(static_cast<char>(value - 13));
  } else {
    uint32_t v = value - 269;
    out->push_back(static_cast<char>(v >> 8));
    out->push_back(static_cast<char>(v & 0xFF));
  }
}

// Serialize one message; byte-identical to the oracle's
// Frame.serialize (stable sort by option number, minimal extensions,
// payload marker only when the payload is non-empty).
inline void Serialize(const CoapMsg& m, std::string* out) {
  out->push_back(static_cast<char>(
      (1 << 6) | (m.type << 4) | (m.token.size() & 0xF)));
  out->push_back(static_cast<char>(m.code));
  out->push_back(static_cast<char>(m.mid >> 8));
  out->push_back(static_cast<char>(m.mid & 0xFF));
  *out += m.token;
  // the oracle sorts with Python's STABLE sort; repeated numbers
  // (Uri-Path segments) must keep their relative order
  std::vector<const std::pair<uint32_t, std::string>*> opts;
  opts.reserve(m.options.size());
  for (const auto& o : m.options) opts.push_back(&o);
  std::stable_sort(opts.begin(), opts.end(),
                   [](const auto* a, const auto* b) {
                     return a->first < b->first;
                   });
  uint32_t prev = 0;
  for (const auto* o : opts) {
    uint8_t dn = ExtNibble(o->first - prev);
    uint8_t ln = ExtNibble(static_cast<uint32_t>(o->second.size()));
    out->push_back(static_cast<char>((dn << 4) | ln));
    PutExtBytes(out, o->first - prev);
    PutExtBytes(out, static_cast<uint32_t>(o->second.size()));
    *out += o->second;
    prev = o->first;
  }
  if (!m.payload.empty()) {
    out->push_back(static_cast<char>(0xFF));
    *out += m.payload;
  }
}

// Every Uri-Path segment joined with '/', the oracle's
// "/".join(path[1:]) shape — the caller strips the leading segment.
inline void JoinPath(const CoapMsg& m, std::vector<std::string_view>* segs) {
  segs->clear();
  for (const auto& [n, v] : m.options)
    if (n == kOptUriPath) segs->push_back(v);
}

// Uri-Query "k=v" lookup. LAST duplicate wins — the oracle's
// queries() builds a dict in option order, so later values overwrite
// earlier ones; a first-match here would resolve a DIFFERENT identity
// than the same datagram punted to the oracle (review finding).
inline bool Query(const CoapMsg& m, std::string_view key,
                  std::string_view* val) {
  bool found = false;
  for (const auto& [n, v] : m.options) {
    if (n != kOptUriQuery) continue;
    size_t eq = v.find('=');
    std::string_view k = eq == std::string::npos
                             ? std::string_view(v)
                             : std::string_view(v).substr(0, eq);
    if (k != key) continue;
    *val = eq == std::string::npos
               ? std::string_view()
               : std::string_view(v).substr(eq + 1);
    found = true;
  }
  return found;
}

// The Observe option decoded as the oracle's observe(): -1 = absent,
// 0 = present-but-empty (register), else the big-endian uint value.
inline long ObserveOf(const CoapMsg& m) {
  const std::string* v = m.Opt(kOptObserve);
  if (v == nullptr) return -1;
  long out = 0;
  for (unsigned char c : *v) out = (out << 8) | c;
  return out;
}

// Build one observe notification (CON for qos>=1 subscriptions, NON
// otherwise): 2.05 Content carrying the subscribe token, the
// observation's rolling 24-bit sequence (ALWAYS 3 bytes — oracle
// to_bytes(3) parity), and the payload.
inline void BuildNotify(std::string* out, uint8_t type, uint16_t mid,
                        const std::string& token, uint32_t seq,
                        std::string_view payload) {
  CoapMsg n;
  n.type = type;
  n.code = kContent;
  n.mid = mid;
  n.token = token;
  std::string sv;
  sv.push_back(static_cast<char>((seq >> 16) & 0xFF));
  sv.push_back(static_cast<char>((seq >> 8) & 0xFF));
  sv.push_back(static_cast<char>(seq & 0xFF));
  n.options.emplace_back(kOptObserve, std::move(sv));
  n.payload.assign(payload.data(), payload.size());
  Serialize(n, out);
}

// Plain-topic-vs-MQTT-filter match ('+'/'#' semantics, emqx_topic.erl
// rules) for resolving which observer a delivery notifies — the
// oracle's core.topic.match over the per-endpoint observer map.
inline bool TopicMatch(std::string_view topic, std::string_view filter) {
  size_t ti = 0, fi = 0;
  for (;;) {
    size_t te = topic.find('/', ti);
    size_t fe = filter.find('/', fi);
    std::string_view tw = topic.substr(
        ti, te == std::string_view::npos ? topic.size() - ti : te - ti);
    std::string_view fw = filter.substr(
        fi, fe == std::string_view::npos ? filter.size() - fi : fe - fi);
    if (fw == "#") return true;
    if (fw != "+" && fw != tw) return false;
    bool tlast = te == std::string_view::npos;
    bool flast = fe == std::string_view::npos;
    if (tlast && flast) return true;
    // "a/#" also matches "a": one trailing '#' level may remain
    if (tlast)
      return !flast && filter.substr(fe + 1) == "#";
    if (flast) return false;
    ti = te + 1;
    fi = fe + 1;
  }
}

}  // namespace coap
}  // namespace emqx_native
