// Snappy block-format codec (compress + decompress), C ABI for ctypes.
//
// The reference pulls compression in through the `snappyer` NIF (a C
// binding of google/snappy) for Kafka record batches (SURVEY.md §2.4);
// this is a from-scratch implementation of the same wire format
// (format_description.txt): varint uncompressed length, then a tag
// stream of literals and copies with 1/2/4-byte offsets.
//
// Greedy matcher over a 4-byte hash table — the same structure as the
// format's reference implementation, sized for broker payloads (KB,
// not GB): offsets fit 32 bits, one block per call.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline uint32_t load32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash32(uint32_t v, int shift) {
  return (v * 0x1e35a7bdu) >> shift;
}

inline uint8_t* emit_varint(uint8_t* dst, uint32_t n) {
  while (n >= 0x80) {
    *dst++ = static_cast<uint8_t>(n) | 0x80;
    n >>= 7;
  }
  *dst++ = static_cast<uint8_t>(n);
  return dst;
}

inline uint8_t* emit_literal(uint8_t* dst, const uint8_t* src, long len) {
  long n = len - 1;
  if (n < 60) {
    *dst++ = static_cast<uint8_t>(n << 2);
  } else {
    int bytes = (n < (1 << 8)) ? 1 : (n < (1 << 16)) ? 2
               : (n < (1 << 24)) ? 3 : 4;
    *dst++ = static_cast<uint8_t>((59 + bytes) << 2);
    for (int i = 0; i < bytes; i++) *dst++ = (n >> (8 * i)) & 0xff;
  }
  std::memcpy(dst, src, len);
  return dst + len;
}

// one copy element, 4 <= len <= 64
inline uint8_t* emit_copy_chunk(uint8_t* dst, uint32_t offset, long len) {
  if (len <= 11 && offset < 2048) {
    *dst++ = 0x01 | ((len - 4) << 2) | ((offset >> 8) << 5);
    *dst++ = offset & 0xff;
  } else if (offset < (1u << 16)) {
    *dst++ = 0x02 | ((len - 1) << 2);
    *dst++ = offset & 0xff;
    *dst++ = (offset >> 8) & 0xff;
  } else {
    *dst++ = 0x03 | ((len - 1) << 2);
    for (int i = 0; i < 4; i++) *dst++ = (offset >> (8 * i)) & 0xff;
  }
  return dst;
}

inline uint8_t* emit_copy(uint8_t* dst, uint32_t offset, long len) {
  // >64 splits; keep every chunk >= 4 by emitting 60s first
  while (len > 64) {
    dst = emit_copy_chunk(dst, offset, 60);
    len -= 60;
  }
  return emit_copy_chunk(dst, offset, len);
}

}  // namespace

extern "C" {

long emqx_snappy_max_compressed(long n) { return 32 + n + n / 6; }

// -> bytes written, or -1 if `cap` would be exceeded (the caller falls
// back; emits never write past dst+cap)
long emqx_snappy_compress(const uint8_t* src, long n, uint8_t* dst,
                          long cap) {
  if (cap < 8) return -1;
  uint8_t* out = emit_varint(dst, static_cast<uint32_t>(n));
  if (n == 0) return out - dst;
  const uint8_t* dend = dst + cap;

  int shift = 18;  // 16k-entry table
  std::vector<int32_t> table(1 << (32 - shift), -1);

  long i = 0, lit = 0;
  while (i + 4 <= n) {
    uint32_t v = load32(src + i);
    uint32_t h = hash32(v, shift);
    int32_t cand = table[h];
    table[h] = static_cast<int32_t>(i);
    if (cand >= 0 && load32(src + cand) == v) {
      long len = 4;
      while (i + len < n && src[cand + len] == src[i + len]) len++;
      // only cost-effective copies: a 5-byte copy4 tag for a 4-byte
      // match would EXPAND the stream (and break the size bound)
      if (static_cast<uint32_t>(i - cand) >= (1u << 16) && len < 8) {
        i++;
        continue;
      }
      // worst emit: literal (5-byte header) + split copies
      if (out + (i - lit) + 5 + (len / 60 + 1) * 5 > dend) return -1;
      if (lit < i) out = emit_literal(out, src + lit, i - lit);
      out = emit_copy(out, static_cast<uint32_t>(i - cand), len);
      i += len;
      lit = i;
    } else {
      i++;
    }
  }
  if (lit < n) {
    if (out + (n - lit) + 5 > dend) return -1;
    out = emit_literal(out, src + lit, n - lit);
  }
  return out - dst;
}

long emqx_snappy_uncompressed_length(const uint8_t* src, long n) {
  uint32_t len = 0;
  int shift = 0;
  long pos = 0;
  while (pos < n && shift < 35) {
    uint8_t b = src[pos++];
    len |= static_cast<uint32_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) return static_cast<long>(len);
    shift += 7;
  }
  return -1;
}

// -> bytes written, or -1 on malformed input / capacity overflow
long emqx_snappy_decompress(const uint8_t* src, long n, uint8_t* dst,
                            long cap) {
  long pos = 0;
  {  // skip the length varint (validated by caller via _uncompressed_length)
    while (pos < n && (src[pos] & 0x80)) pos++;
    if (pos >= n) return -1;
    pos++;
  }
  long w = 0;
  while (pos < n) {
    uint8_t tag = src[pos++];
    if ((tag & 0x03) == 0x00) {  // literal
      long len = (tag >> 2) + 1;
      if (len > 60) {
        int bytes = static_cast<int>(len - 60);
        if (pos + bytes > n) return -1;
        len = 0;
        for (int k = 0; k < bytes; k++)
          len |= static_cast<long>(src[pos + k]) << (8 * k);
        len += 1;
        pos += bytes;
      }
      if (pos + len > n || w + len > cap) return -1;
      std::memcpy(dst + w, src + pos, len);
      pos += len;
      w += len;
    } else {
      long len;
      uint32_t offset;
      if ((tag & 0x03) == 0x01) {
        if (pos + 1 > n) return -1;
        len = ((tag >> 2) & 0x07) + 4;
        offset = (static_cast<uint32_t>(tag >> 5) << 8) | src[pos];
        pos += 1;
      } else if ((tag & 0x03) == 0x02) {
        if (pos + 2 > n) return -1;
        len = (tag >> 2) + 1;
        offset = src[pos] | (static_cast<uint32_t>(src[pos + 1]) << 8);
        pos += 2;
      } else {
        if (pos + 4 > n) return -1;
        len = (tag >> 2) + 1;
        offset = src[pos] | (static_cast<uint32_t>(src[pos + 1]) << 8) |
                 (static_cast<uint32_t>(src[pos + 2]) << 16) |
                 (static_cast<uint32_t>(src[pos + 3]) << 24);
        pos += 4;
      }
      if (offset == 0 || offset > static_cast<uint32_t>(w) ||
          w + len > cap)
        return -1;
      // byte-by-byte: overlapping copies (offset < len) replicate
      for (long k = 0; k < len; k++) dst[w + k] = dst[w + k - offset];
      w += len;
    }
  }
  return w;
}

}  // extern "C"
