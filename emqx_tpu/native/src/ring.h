// Multi-core native plane (round 12): the lock-free cross-shard seam.
//
// A sharded host runs N independent epoll loops (one Host instance per
// shard, each with its own poll thread, lanes, ack windows, telemetry
// buffers and outbuf machinery — host.cc stays single-threaded per
// instance). The match table is LOGICALLY shared: every shard holds a
// full replica (Python broadcasts table ops to all shards, each shard
// applies them in its own ApplyPending, serialized with its own
// matching — the existing poll-thread-ownership discipline, N times).
// What crosses shards is DELIVERY: a publish matched on shard S whose
// subscriber connection lives on shard T rides one of these rings.
//
// Ring contract (the "must not take a lock on the hot path" clause):
//   - one SpscRing per ordered shard pair (N^2 rings, each
//     single-producer/single-consumer BY CONSTRUCTION: only S's poll
//     thread pushes on rings[S][T], only T's poll thread pops);
//   - a slot holds one sealed BATCH record in the trunk wire layout
//     (trunk.h AppendEntry pre-parse entries, payload-deduped), with a
//     [u64 target] prefix per entry so the consumer delivers by conn id
//     instead of re-matching — per-topic order per (publisher, target)
//     follows from the FIFO ring + the consumer's sequential decode,
//     exactly like a trunk link;
//   - bounded: when a ring cannot take this publish (free slots < 2 —
//     room for the open batch plus one mid-publish seal), the publish
//     degrades ring-full -> punt -> Python BEFORE any side effect,
//     mirroring the trunk's trunk-down ladder (host.cc TryFast).
//
// Teardown: the group OWNS the doorbell eventfds (a producer must be
// able to ring a shard whose Host died mid-race — writing to a closed,
// possibly-reused fd would be a use-after-close); Hosts only clear
// their alive flag. Python destroys every host BEFORE the group.
#pragma once

#include <sys/eventfd.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <string>

namespace emqx_native {
namespace ring {

constexpr int kMaxShards = 8;
// Slots per ring: each slot is one sealed batch (<= ~192KB, the tap
// flush cap), sealed once per poll cycle per destination plus at the
// byte cap — 256 batches of backlog per pair before the ladder punts.
constexpr size_t kRingSlots = 256;

// Bounded lock-free SPSC ring of sealed batch records. Single producer
// (the source shard's poll thread), single consumer (the destination
// shard's poll thread); head_/tail_ are the only shared state.
class SpscRing {
 public:
  // Producer only. False = full (caller counts shard_ring_full).
  bool Push(std::string&& rec) {
    size_t h = head_.load(std::memory_order_relaxed);
    size_t t = tail_.load(std::memory_order_acquire);
    if (h - t >= kRingSlots) return false;
    slots_[h & (kRingSlots - 1)] = std::move(rec);
    head_.store(h + 1, std::memory_order_release);
    return true;
  }

  // Consumer only.
  bool Pop(std::string* out) {
    size_t t = tail_.load(std::memory_order_relaxed);
    size_t h = head_.load(std::memory_order_acquire);
    if (t == h) return false;
    *out = std::move(slots_[t & (kRingSlots - 1)]);
    tail_.store(t + 1, std::memory_order_release);
    return true;
  }

  // Producer-side free-slot view: exact for the producer (only the
  // consumer ever grows it), which is what the pre-side-effect
  // admission check needs.
  size_t Free() const {
    return kRingSlots - (head_.load(std::memory_order_relaxed) -
                         tail_.load(std::memory_order_acquire));
  }

 private:
  // @published(head_, tail_) — slot data is made visible to the other
  // side ONLY by the index's release store: every slots_ write/read
  // must lexically precede the publish in Push/Pop
  std::string slots_[kRingSlots];
  // @atomic(acq_rel: producer release-publishes filled slots; consumer acquire-loads; own-side reads relaxed)
  alignas(64) std::atomic<size_t> head_{0};
  // @atomic(acq_rel: consumer release-publishes freed slots; producer acquire-loads; own-side reads relaxed)
  alignas(64) std::atomic<size_t> tail_{0};
};

// Shared by every Host of one sharded server. Created by Python before
// any host joins; destroyed after every host is destroyed.
struct ShardGroup {
  explicit ShardGroup(int n_shards) : n(n_shards) {
    for (int i = 0; i < kMaxShards; i++) {
      doorbell[i] = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
      alive[i].store(false, std::memory_order_relaxed);
    }
  }
  ~ShardGroup() {
    for (int i = 0; i < kMaxShards; i++)
      if (doorbell[i] >= 0) close(doorbell[i]);
  }
  ShardGroup(const ShardGroup&) = delete;
  ShardGroup& operator=(const ShardGroup&) = delete;

  // Wake the destination shard's epoll loop after a push. The group
  // owns the fd, so this is safe even when the target Host is gone
  // (the write lands on a live-but-unwatched eventfd).
  void RingDoorbell(int dst) {
    uint64_t one = 1;
    [[maybe_unused]] ssize_t r = write(doorbell[dst], &one, sizeof(one));
  }

  int n;
  SpscRing rings[kMaxShards][kMaxShards];  // [src][dst]
  int doorbell[kMaxShards];
  // set at join, cleared at ~Host
  // @atomic(acq_rel: join release-publishes the shard's readiness; producers acquire-load before pushing; ctor init relaxed)
  std::atomic<bool> alive[kMaxShards];
};

}  // namespace ring
}  // namespace emqx_native
