// Native subscription table: exact-match map + wildcard trie, mirrored
// from the Python broker tables (emqx_tpu/broker/broker.py) by the
// native server. This is the C++ twin of the host-oracle trie
// (emqx_tpu/router/trie.py — itself the emqx_trie.erl:113-160 walk),
// specialised for the PUBLISH fast path: entries carry the owning
// connection and delivery flags, and a *punt marker* entry means "this
// filter's subscriber cannot be served natively" (shared subscription,
// persistent session, non-native transport, cross-node route, v5
// subscription identifier). A publish whose match set contains any punt
// marker is forwarded to Python verbatim, so native fan-out is only
// ever performed when it is COMPLETE.
//
// Threading: mutated and read exclusively on the host's poll thread
// (Python-side calls enqueue ops that the loop applies in ApplyPending),
// so no locks here.
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace emqx_native {

struct SubEntry {
  uint64_t owner = 0;  // conn id for real entries; opaque token for punts
  uint8_t qos = 0;     // granted (subscription) max qos
  uint8_t flags = 0;   // kSubPunt / kSubNoLocal
};

constexpr uint8_t kSubPunt = 1;     // matched => forward frame to Python
constexpr uint8_t kSubNoLocal = 2;  // MQTT5 no-local: skip the publisher
// Rule tap (round 5): a rule-engine FROM filter compiled into the
// table as a NON-delivering entry. A matched tap neither punts nor
// receives the message — the frame is COPIED up to Python's rule
// runtime asynchronously while native fan-out proceeds, removing the
// broad-rule permit cliff (one FROM '#' rule used to de-permit the
// whole fast path).
constexpr uint8_t kSubRuleTap = 4;
// Remote entry (round 9): a cross-node route whose peer has a native
// trunk link — the third entry kind, sibling of the round-5 punt
// marker. A matched remote entry enqueues the publish onto that peer's
// per-topic-ordered trunk batch (host.cc TrunkEnqueue) instead of
// punting the frame to Python; when the trunk is down (or the qos1
// replay ring is full, or the publish is qos2) the entry behaves
// exactly like a punt marker and the Python forward_fn lane carries
// the message. owner = kTrunkOwnerBase + peer id.
constexpr uint8_t kSubRemote = 8;
// Durable entry (round 10): a persistent session's filter, served by
// the native durable plane instead of a punt marker — the FOURTH entry
// kind, sibling of punt/remote. A matched durable entry neither punts
// nor delivers directly: the publish is appended to the host-side
// message store (store.h) in the per-cycle batched record and shipped
// to Python as ONE kind-10 event, so the publisher and every fast
// subscriber STAY on the fast path while the persistent session gets
// its store marker + Python-side delivery (emqx_persistent_session
// :persist_message semantics below the GIL). owner = a store token
// registered per session id.
constexpr uint8_t kSubDurable = 16;

// A $share group on one filter, natively served: the Python server
// installs one of these ONLY when every member is a fast native
// connection and the node strategy is round_robin (emqx_shared_sub.erl
// :309-379); any other membership shape stays a punt marker. Dispatch
// advances the cursor and skips members whose connection is gone or
// backpressured — the nack/redispatch analogue (:190-217).
struct SharedGroup {
  uint64_t token = 0;              // group identity (interned by Python)
  uint32_t cursor = 0;
  std::vector<SubEntry> members;   // owner = conn id
};

// Split a topic/filter on '/'; MQTT keeps empty levels ("a//b" is three
// levels, the middle one empty) — emqx_topic.erl:words/1 semantics.
inline void SplitLevels(std::string_view s, std::vector<std::string_view>* out) {
  out->clear();
  size_t start = 0;
  for (size_t i = 0; i <= s.size(); i++) {
    if (i == s.size() || s[i] == '/') {
      out->push_back(s.substr(start, i - start));
      start = i + 1;
    }
  }
}

class SubTable {
 public:
  // Insert or update (owner, filter). A second add with the same owner
  // and filter updates qos/flags in place (resubscribe upgrades).
  void Add(uint64_t owner, const std::string& filter, uint8_t qos,
           uint8_t flags) {
    if (filter.find('+') == std::string::npos &&
        filter.find('#') == std::string::npos) {
      if (Upsert(&exact_[filter], owner, qos, flags)) entry_count_++;
      return;
    }
    SplitLevels(filter, &scratch_levels_);
    Node* n = &root_;
    for (size_t i = 0; i < scratch_levels_.size(); i++) {
      std::string_view w = scratch_levels_[i];
      if (w == "#") {
        // '#' is only valid as the last level; store at the node ABOVE
        if (Upsert(&n->hash, owner, qos, flags)) entry_count_++;
        return;
      }
      if (w == "+") {
        if (!n->plus) n->plus = std::make_unique<Node>();
        n = n->plus.get();
      } else {
        auto& kid = n->kids[std::string(w)];
        if (!kid) kid = std::make_unique<Node>();
        n = kid.get();
      }
    }
    if (Upsert(&n->here, owner, qos, flags)) entry_count_++;
  }

  // Remove (owner, filter); returns whether an entry was removed.
  bool Remove(uint64_t owner, const std::string& filter) {
    if (filter.find('+') == std::string::npos &&
        filter.find('#') == std::string::npos) {
      auto it = exact_.find(filter);
      if (it == exact_.end()) return false;
      bool hit = Erase(&it->second, owner);
      if (hit) entry_count_--;
      if (it->second.empty()) exact_.erase(it);
      return hit;
    }
    SplitLevels(filter, &scratch_levels_);
    Node* n = &root_;
    for (size_t i = 0; i < scratch_levels_.size(); i++) {
      std::string_view w = scratch_levels_[i];
      if (w == "#") {
        bool hit = Erase(&n->hash, owner);
        if (hit) entry_count_--;
        return hit;
      }
      if (w == "+") {
        if (!n->plus) return false;
        n = n->plus.get();
      } else {
        auto it = n->kids.find(std::string(w));
        if (it == n->kids.end()) return false;
        n = it->second.get();
      }
    }
    bool hit = Erase(&n->here, owner);
    if (hit) entry_count_--;
    return hit;
    // empty interior nodes are left in place: subscription churn
    // re-creates them constantly and the per-node footprint is tiny
  }

  // Shared-group membership management: token identifies the group,
  // owner the member connection. Empty groups are removed.
  void SharedAdd(uint64_t token, uint64_t owner, const std::string& filter,
                 uint8_t qos, uint8_t flags) {
    SharedGroup* g = FindGroup(filter, token, /*create=*/true);
    if (g) (void)Upsert(&g->members, owner, qos, flags);
  }

  bool SharedRemove(uint64_t token, uint64_t owner,
                    const std::string& filter) {
    SharedGroup* g = FindGroup(filter, token, /*create=*/false);
    if (!g) return false;
    bool hit = Erase(&g->members, owner);
    if (g->members.empty()) DropGroup(filter, token);
    return hit;
  }

  // Append every entry matching `topic` to *out, and every natively
  // served shared group to *groups (mutable: dispatch advances their
  // cursors). The caller guarantees the topic is a plain name (no
  // wildcards, no leading '$' — the fast path punts those before
  // matching, which also gives the MQTT rule that root wildcards must
  // not match $-topics for free).
  void Match(std::string_view topic, std::vector<const SubEntry*>* out,
             std::vector<SharedGroup*>* groups = nullptr) {
    key_scratch_.assign(topic.data(), topic.size());
    auto it = exact_.find(key_scratch_);
    if (it != exact_.end())
      for (const auto& e : it->second) out->push_back(&e);
    if (groups) {
      auto git = exact_groups_.find(key_scratch_);
      if (git != exact_groups_.end())
        for (auto& g : git->second) groups->push_back(&g);
    }
    SplitLevels(topic, &match_levels_);
    MatchNode(&root_, 0, out, groups);
  }

  // Entries + shared groups registered under EXACTLY this filter — the
  // device lane's delivery lookup. The device kernel already did the
  // wildcard walk and returned matched filter STRINGS; delivery then
  // needs only each filter's terminal vectors: an O(1) hash probe for
  // plain names, an O(depth) path walk (no branching) for wildcard
  // filters, instead of the full per-message trie match.
  void MatchFilter(std::string_view filter,
                   std::vector<const SubEntry*>* out,
                   std::vector<SharedGroup*>* groups = nullptr) {
    key_scratch_.assign(filter.data(), filter.size());
    if (key_scratch_.find('+') == std::string::npos &&
        key_scratch_.find('#') == std::string::npos) {
      auto it = exact_.find(key_scratch_);
      if (it != exact_.end())
        for (const auto& e : it->second) out->push_back(&e);
      if (groups) {
        auto git = exact_groups_.find(key_scratch_);
        if (git != exact_groups_.end())
          for (auto& g : git->second) groups->push_back(&g);
      }
      return;
    }
    SplitLevels(key_scratch_, &scratch_levels_);
    Node* n = &root_;
    for (size_t i = 0; i < scratch_levels_.size(); i++) {
      std::string_view w = scratch_levels_[i];
      if (w == "#") {
        for (const auto& e : n->hash) out->push_back(&e);
        if (groups)
          for (auto& g : n->hash_groups) groups->push_back(&g);
        return;
      }
      if (w == "+") {
        if (!n->plus) return;
        n = n->plus.get();
      } else {
        auto it = n->kids.find(std::string(w));
        if (it == n->kids.end()) return;
        n = it->second.get();
      }
    }
    for (const auto& e : n->here) out->push_back(&e);
    if (groups)
      for (auto& g : n->here_groups) groups->push_back(&g);
  }

  size_t exact_count() const { return exact_.size(); }

  // True when no plain (non-shared) entries exist anywhere — interior
  // trie nodes left by removals don't count. O(1) via entry_count_.
  bool Empty() const { return entry_count_ == 0; }

 private:
  struct Node {
    std::unordered_map<std::string, std::unique_ptr<Node>> kids;
    std::unique_ptr<Node> plus;
    std::vector<SubEntry> here;  // filters ending exactly at this node
    std::vector<SubEntry> hash;  // filters ending in '#' one level below
    std::vector<SharedGroup> here_groups;
    std::vector<SharedGroup> hash_groups;
  };

  // Walk to the filter's terminal vectors; create the path on demand.
  // Returns (plain, groups) pointers via out-params; null when absent.
  template <bool Create>
  bool Terminal(const std::string& filter,
                std::vector<SharedGroup>** groups) {
    if (filter.find('+') == std::string::npos &&
        filter.find('#') == std::string::npos) {
      if (Create) {
        *groups = &exact_groups_[filter];
        return true;
      }
      auto it = exact_groups_.find(filter);
      if (it == exact_groups_.end()) return false;
      *groups = &it->second;
      return true;
    }
    SplitLevels(filter, &scratch_levels_);
    Node* n = &root_;
    for (size_t i = 0; i < scratch_levels_.size(); i++) {
      std::string_view w = scratch_levels_[i];
      if (w == "#") {
        *groups = &n->hash_groups;
        return true;
      }
      if (w == "+") {
        if (!n->plus) {
          if (!Create) return false;
          n->plus = std::make_unique<Node>();
        }
        n = n->plus.get();
      } else {
        auto it = n->kids.find(std::string(w));
        if (it == n->kids.end()) {
          if (!Create) return false;
          auto& kid = n->kids[std::string(w)];
          kid = std::make_unique<Node>();
          n = kid.get();
          continue;
        }
        n = it->second.get();
      }
    }
    *groups = &n->here_groups;
    return true;
  }

  SharedGroup* FindGroup(const std::string& filter, uint64_t token,
                         bool create) {
    std::vector<SharedGroup>* vec = nullptr;
    bool ok = create ? Terminal<true>(filter, &vec)
                     : Terminal<false>(filter, &vec);
    if (!ok || !vec) return nullptr;
    for (auto& g : *vec)
      if (g.token == token) return &g;
    if (!create) return nullptr;
    vec->push_back(SharedGroup{token, 0, {}});
    return &vec->back();
  }

  void DropGroup(const std::string& filter, uint64_t token) {
    std::vector<SharedGroup>* vec = nullptr;
    if (!Terminal<false>(filter, &vec) || !vec) return;
    for (size_t i = 0; i < vec->size(); i++) {
      if ((*vec)[i].token == token) {
        (*vec)[i] = std::move(vec->back());
        vec->pop_back();
        return;
      }
    }
  }

  // Returns true when a NEW entry was inserted (false = qos/flags
  // update in place) so callers can keep entry_count_ exact.
  static bool Upsert(std::vector<SubEntry>* v, uint64_t owner, uint8_t qos,
                     uint8_t flags) {
    for (auto& e : *v) {
      if (e.owner == owner) {
        e.qos = qos;
        e.flags = flags;
        return false;
      }
    }
    v->push_back(SubEntry{owner, qos, flags});
    return true;
  }

  static bool Erase(std::vector<SubEntry>* v, uint64_t owner) {
    for (size_t i = 0; i < v->size(); i++) {
      if ((*v)[i].owner == owner) {
        (*v)[i] = v->back();
        v->pop_back();
        return true;
      }
    }
    return false;
  }

  void MatchNode(Node* n, size_t i, std::vector<const SubEntry*>* out,
                 std::vector<SharedGroup*>* groups) {
    // "a/#" matches "a", "a/b", ... — the '#' list at node a covers the
    // remainder including zero further levels (emqx_trie 'match #')
    for (const auto& e : n->hash) out->push_back(&e);
    if (groups)
      for (auto& g : n->hash_groups) groups->push_back(&g);
    if (i == match_levels_.size()) {
      for (const auto& e : n->here) out->push_back(&e);
      if (groups)
        for (auto& g : n->here_groups) groups->push_back(&g);
      return;
    }
    // assign() reuses the scratch capacity: the per-message hot loop
    // must not heap-allocate per level just to query the kids map
    key_scratch_.assign(match_levels_[i].data(), match_levels_[i].size());
    auto it = n->kids.find(key_scratch_);
    if (it != n->kids.end()) MatchNode(it->second.get(), i + 1, out, groups);
    if (n->plus) MatchNode(n->plus.get(), i + 1, out, groups);
  }

  Node root_;
  size_t entry_count_ = 0;
  std::unordered_map<std::string, std::vector<SubEntry>> exact_;
  std::unordered_map<std::string, std::vector<SharedGroup>> exact_groups_;
  std::vector<std::string_view> scratch_levels_;
  std::vector<std::string_view> match_levels_;
  std::string key_scratch_;
};

// ---------------------------------------------------------------------------
// Host-side retained snapshot (round 11): the INVERSE trie problem —
// SubTable matches a topic NAME against stored FILTERS; this matches a
// subscription FILTER against stored topic NAMES, which is exactly the
// retainer's lookup (services/retainer.py, the Python oracle and the
// authoritative store). The Python server mirrors every retainer
// store/delete/expire into this table via poll-thread-applied ops (the
// match-table mutation discipline: swap-on-update serialized with
// matching), so SUBSCRIBE-triggered retained delivery resolves and
// writes below the GIL for TCP, WS, and SN subscribers alike.
//
// Threading: poll-thread-owned, like SubTable.

struct RetainEntry {
  std::string topic;
  std::string payload;
  uint8_t qos = 0;
  // absolute wall-clock expiry (ms since epoch, 0 = never): the
  // EFFECTIVE deadline — Python folds the per-message expiry property
  // and the store-wide default into one number at mirror time, so the
  // C++ check is a single compare
  uint64_t deadline_ms = 0;
  bool dollar = false;  // topic starts with '$' (root-wildcard guard)
};

class RetainTable {
 public:
  void Set(const std::string& topic, std::string_view payload, uint8_t qos,
           uint64_t deadline_ms) {
    SplitLevels(topic, &levels_);
    Node* n = &root_;
    for (std::string_view w : levels_) {
      auto& kid = n->kids[std::string(w)];
      if (!kid) kid = std::make_unique<Node>();
      n = kid.get();
    }
    if (!n->here) {
      n->here = std::make_unique<RetainEntry>();
      count_++;
    }
    n->here->topic = topic;
    n->here->payload.assign(payload.data(), payload.size());
    n->here->qos = qos;
    n->here->deadline_ms = deadline_ms;
    n->here->dollar = !topic.empty() && topic[0] == '$';
  }

  bool Del(const std::string& topic) {
    SplitLevels(topic, &levels_);
    Node* n = &root_;
    for (std::string_view w : levels_) {
      key_.assign(w.data(), w.size());
      auto it = n->kids.find(key_);
      if (it == n->kids.end()) return false;
      n = it->second.get();
    }
    if (!n->here) return false;
    n->here.reset();
    count_--;
    // interior nodes stay (the SubTable removal discipline: retained
    // churn re-creates them constantly, the footprint is tiny)
    return true;
  }

  // Every live (unexpired) retained topic matching `filter`, in trie
  // order. MQTT 4.7.2: a root-level wildcard never exposes '$'-topics.
  void Match(std::string_view filter, uint64_t now_ms,
             std::vector<const RetainEntry*>* out) {
    SplitLevels(filter, &match_levels_);
    bool guard = !match_levels_.empty() &&
                 (match_levels_[0] == "+" || match_levels_[0] == "#");
    MatchNode(&root_, 0, guard, now_ms, out);
  }

  size_t size() const { return count_; }

 private:
  struct Node {
    std::unordered_map<std::string, std::unique_ptr<Node>> kids;
    std::unique_ptr<RetainEntry> here;  // topic ending exactly here
  };

  void Emit(const Node* n, bool guard, uint64_t now_ms,
            std::vector<const RetainEntry*>* out) {
    const RetainEntry* e = n->here.get();
    if (!e) return;
    if (guard && e->dollar) return;
    if (e->deadline_ms && now_ms >= e->deadline_ms) return;  // expired:
    // skipped here, DELETED when the Python retainer's own lazy
    // expiry/sweep fires the delete observer
    out->push_back(e);
  }

  void Collect(const Node* n, bool guard, uint64_t now_ms,
               std::vector<const RetainEntry*>* out) {
    Emit(n, guard, now_ms, out);
    for (const auto& [w, kid] : n->kids)
      Collect(kid.get(), guard, now_ms, out);
  }

  void MatchNode(const Node* n, size_t i, bool guard, uint64_t now_ms,
                 std::vector<const RetainEntry*>* out) {
    if (i == match_levels_.size()) {
      Emit(n, guard, now_ms, out);
      return;
    }
    std::string_view w = match_levels_[i];
    if (w == "#") {
      // '#' covers the remainder INCLUDING zero further levels
      // ("a/#" matches "a") — emqx_topic.erl match semantics, same as
      // the retainer oracle's depth >= need mask
      Collect(n, guard, now_ms, out);
      return;
    }
    if (w == "+") {
      for (const auto& [word, kid] : n->kids) {
        if (i == 0 && !word.empty() && word[0] == '$') continue;
        MatchNode(kid.get(), i + 1, guard, now_ms, out);
      }
      return;
    }
    key_.assign(w.data(), w.size());
    auto it = n->kids.find(key_);
    if (it != n->kids.end()) MatchNode(it->second.get(), i + 1, guard,
                                       now_ms, out);
  }

  Node root_;
  size_t count_ = 0;
  std::vector<std::string_view> levels_;
  std::vector<std::string_view> match_levels_;
  std::string key_;
};

}  // namespace emqx_native
