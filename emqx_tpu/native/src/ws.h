// RFC6455 for the native plane — the C++ twin of broker/ws.py (which
// stays the slow-plane oracle and conformance reference). Shared by
// host.cc (server side: upgrade handshake + masked-client decode +
// binary egress) and loadgen.cc (client side: request + masked egress +
// unmasked decode), so the two ends are framed by the same state
// machine and a bug cannot hide behind a matching bug.
//
// Design notes:
//   - the decoder STREAMS data-frame payload bytes to the caller as
//     they arrive (unmasked incrementally — the mask key is positional,
//     so no whole-frame buffering): MQTT-over-WS packets need not align
//     with WS frame boundaries (MQTT 5 §6.0), and the byte stream feeds
//     the MQTT Framer exactly like TCP bytes do. Fragmented data
//     messages therefore "reassemble" for free — the fragments' payload
//     bytes flow to the sink in order — while opcode sequencing
//     (continuation-without-start, interleaved messages, fragmented
//     control frames, RSV bits) is still validated per RFC;
//   - control frames (<=125 bytes) ARE buffered whole: ping payloads
//     echo into pongs and close frames carry a status code;
//   - SHA1 lives here only for the Sec-WebSocket-Accept digest (RFC6455
//     §4.2.2); it is not a general-purpose hash surface.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>

namespace emqx_native {
namespace ws {

constexpr uint8_t kOpCont = 0x0;
constexpr uint8_t kOpText = 0x1;
constexpr uint8_t kOpBinary = 0x2;
constexpr uint8_t kOpClose = 0x8;
constexpr uint8_t kOpPing = 0x9;
constexpr uint8_t kOpPong = 0xA;

constexpr const char* kGuid = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11";

// -- SHA1 (for the accept key only) -----------------------------------------

inline void Sha1(const uint8_t* data, size_t len, uint8_t out[20]) {
  uint32_t h[5] = {0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476,
                   0xC3D2E1F0};
  uint64_t total = static_cast<uint64_t>(len) * 8;
  // message + 0x80 + zero pad + 8-byte big-endian bit length
  size_t padded = ((len + 8) / 64 + 1) * 64;
  std::string buf(reinterpret_cast<const char*>(data), len);
  buf.push_back(static_cast<char>(0x80));
  buf.resize(padded, '\0');
  for (int i = 0; i < 8; i++)
    buf[padded - 1 - i] = static_cast<char>((total >> (8 * i)) & 0xFF);
  auto rol = [](uint32_t v, int n) { return (v << n) | (v >> (32 - n)); };
  for (size_t off = 0; off < padded; off += 64) {
    uint32_t w[80];
    for (int i = 0; i < 16; i++)
      w[i] = (static_cast<uint8_t>(buf[off + 4 * i]) << 24) |
             (static_cast<uint8_t>(buf[off + 4 * i + 1]) << 16) |
             (static_cast<uint8_t>(buf[off + 4 * i + 2]) << 8) |
             static_cast<uint8_t>(buf[off + 4 * i + 3]);
    for (int i = 16; i < 80; i++)
      w[i] = rol(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1);
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4];
    for (int i = 0; i < 80; i++) {
      uint32_t f, k;
      if (i < 20) {
        f = (b & c) | (~b & d);
        k = 0x5A827999;
      } else if (i < 40) {
        f = b ^ c ^ d;
        k = 0x6ED9EBA1;
      } else if (i < 60) {
        f = (b & c) | (b & d) | (c & d);
        k = 0x8F1BBCDC;
      } else {
        f = b ^ c ^ d;
        k = 0xCA62C1D6;
      }
      uint32_t t = rol(a, 5) + f + e + k + w[i];
      e = d;
      d = c;
      c = rol(b, 30);
      b = a;
      a = t;
    }
    h[0] += a;
    h[1] += b;
    h[2] += c;
    h[3] += d;
    h[4] += e;
  }
  for (int i = 0; i < 5; i++) {
    out[4 * i] = (h[i] >> 24) & 0xFF;
    out[4 * i + 1] = (h[i] >> 16) & 0xFF;
    out[4 * i + 2] = (h[i] >> 8) & 0xFF;
    out[4 * i + 3] = h[i] & 0xFF;
  }
}

inline std::string Base64(const uint8_t* data, size_t len) {
  static const char tbl[] =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  out.reserve((len + 2) / 3 * 4);
  for (size_t i = 0; i < len; i += 3) {
    uint32_t v = data[i] << 16;
    if (i + 1 < len) v |= data[i + 1] << 8;
    if (i + 2 < len) v |= data[i + 2];
    out.push_back(tbl[(v >> 18) & 63]);
    out.push_back(tbl[(v >> 12) & 63]);
    out.push_back(i + 1 < len ? tbl[(v >> 6) & 63] : '=');
    out.push_back(i + 2 < len ? tbl[v & 63] : '=');
  }
  return out;
}

inline std::string AcceptKey(std::string_view client_key) {
  std::string joined(client_key);
  joined += kGuid;
  uint8_t digest[20];
  Sha1(reinterpret_cast<const uint8_t*>(joined.data()), joined.size(),
       digest);
  return Base64(digest, 20);
}

// -- handshake ---------------------------------------------------------------

// Parse one HTTP/1.1 upgrade request (bytes through the blank line).
// Returns true when it is a well-formed GET websocket upgrade; fills
// the client key, the request path (query string stripped) and whether
// the `mqtt` subprotocol was offered. Header names are
// case-insensitive; values case-insensitively substring-matched the
// same way broker/ws.py's oracle does.
inline bool ParseUpgradeRequest(std::string_view req, std::string* key,
                                std::string* path, bool* mqtt_proto) {
  *mqtt_proto = false;
  size_t line_end = req.find("\r\n");
  if (line_end == std::string_view::npos) return false;
  std::string_view start = req.substr(0, line_end);
  size_t sp1 = start.find(' ');
  size_t sp2 = start.rfind(' ');
  if (sp1 == std::string_view::npos || sp2 <= sp1) return false;
  if (start.substr(0, sp1) != "GET") return false;
  std::string_view target = start.substr(sp1 + 1, sp2 - sp1 - 1);
  size_t q = target.find('?');
  path->assign(target.substr(0, q));
  auto lower = [](std::string s) {
    for (char& c : s)
      if (c >= 'A' && c <= 'Z') c += 32;
    return s;
  };
  bool upgrade_ws = false, conn_upgrade = false, have_key = false;
  size_t pos = line_end + 2;
  while (pos < req.size()) {
    size_t eol = req.find("\r\n", pos);
    if (eol == std::string_view::npos) eol = req.size();
    std::string_view line = req.substr(pos, eol - pos);
    pos = eol + 2;
    size_t colon = line.find(':');
    if (colon == std::string_view::npos) continue;
    std::string name = lower(std::string(line.substr(0, colon)));
    std::string_view val = line.substr(colon + 1);
    while (!val.empty() && (val.front() == ' ' || val.front() == '\t'))
      val.remove_prefix(1);
    while (!val.empty() && (val.back() == ' ' || val.back() == '\r'))
      val.remove_suffix(1);
    if (name == "upgrade") {
      upgrade_ws = lower(std::string(val)).find("websocket") !=
                   std::string::npos;
    } else if (name == "connection") {
      conn_upgrade = lower(std::string(val)).find("upgrade") !=
                     std::string::npos;
    } else if (name == "sec-websocket-key") {
      key->assign(val);
      have_key = !key->empty();
    } else if (name == "sec-websocket-protocol") {
      if (lower(std::string(val)).find("mqtt") != std::string::npos)
        *mqtt_proto = true;
    }
  }
  return upgrade_ws && conn_upgrade && have_key;
}

inline std::string BuildUpgradeResponse(const std::string& accept,
                                        bool mqtt_proto) {
  std::string r =
      "HTTP/1.1 101 Switching Protocols\r\n"
      "Upgrade: websocket\r\n"
      "Connection: Upgrade\r\n"
      "Sec-WebSocket-Accept: " + accept + "\r\n";
  if (mqtt_proto) r += "Sec-WebSocket-Protocol: mqtt\r\n";
  r += "\r\n";
  return r;
}

inline std::string Build400() {
  return "HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n\r\n";
}

inline std::string BuildUpgradeRequest(const std::string& host,
                                       const std::string& path,
                                       const std::string& key) {
  return "GET " + path + " HTTP/1.1\r\n"
         "Host: " + host + "\r\n"
         "Upgrade: websocket\r\n"
         "Connection: Upgrade\r\n"
         "Sec-WebSocket-Key: " + key + "\r\n"
         "Sec-WebSocket-Version: 13\r\n"
         "Sec-WebSocket-Protocol: mqtt\r\n\r\n";
}

// -- frame encode ------------------------------------------------------------

// Append one frame header (FIN=1). mask_key != nullptr sets the mask
// bit and appends the key; the CALLER XORs the payload it then appends
// (clients mask, servers never do — RFC6455 §5.3).
inline void AppendFrameHeader(std::string* out, uint8_t opcode, size_t len,
                              const uint8_t* mask_key = nullptr) {
  out->push_back(static_cast<char>(0x80 | opcode));
  uint8_t mbit = mask_key ? 0x80 : 0;
  if (len < 126) {
    out->push_back(static_cast<char>(mbit | len));
  } else if (len < 65536) {
    out->push_back(static_cast<char>(mbit | 126));
    out->push_back(static_cast<char>(len >> 8));
    out->push_back(static_cast<char>(len & 0xFF));
  } else {
    out->push_back(static_cast<char>(mbit | 127));
    for (int i = 7; i >= 0; i--)
      out->push_back(static_cast<char>(
          (static_cast<uint64_t>(len) >> (8 * i)) & 0xFF));
  }
  if (mask_key)
    out->append(reinterpret_cast<const char*>(mask_key), 4);
}

// -- incremental decoder -----------------------------------------------------

enum class WsStatus : int {
  kOk = 0,
  kProtoError = 1,   // RSV bits / opcode sequence / mask rule violated
  kCtrlTooBig = 2,   // control frame payload over 125 bytes
  kAborted = 3,      // the data sink asked to stop (downstream error)
};

// Resumable frame state machine. Data-frame payloads stream to
// `on_data(chunk, len) -> bool` (false aborts); complete control frames
// land in `on_ctrl(opcode, payload, len) -> bool`. `data` is mutable:
// masked payload bytes unmask IN PLACE (word-at-a-time), so the hot
// path pays one XOR pass and zero copies between the socket buffer and
// the MQTT framer.
class WsDecoder {
 public:
  explicit WsDecoder(bool require_mask) : require_mask_(require_mask) {}

  template <typename DataFn, typename CtrlFn>
  WsStatus Feed(uint8_t* data, size_t len, DataFn&& on_data,
                CtrlFn&& on_ctrl) {
    size_t pos = 0;
    while (pos < len) {
      switch (phase_) {
        case Phase::kB0: {
          uint8_t b0 = data[pos++];
          if (b0 & 0x70) return WsStatus::kProtoError;  // RSV set
          fin_ = b0 & 0x80;
          opcode_ = b0 & 0x0F;
          is_ctrl_ = opcode_ >= 0x8;
          if (is_ctrl_) {
            if (!fin_) return WsStatus::kProtoError;  // fragmented ctrl
            if (opcode_ != kOpClose && opcode_ != kOpPing &&
                opcode_ != kOpPong)
              return WsStatus::kProtoError;
          } else if (opcode_ == kOpCont) {
            if (!in_msg_) return WsStatus::kProtoError;
          } else if (opcode_ == kOpText || opcode_ == kOpBinary) {
            if (in_msg_) return WsStatus::kProtoError;  // interleaved
            in_msg_ = !fin_;
          } else {
            return WsStatus::kProtoError;
          }
          if (!is_ctrl_ && opcode_ == kOpCont) in_msg_ = !fin_;
          phase_ = Phase::kB1;
          break;
        }
        case Phase::kB1: {
          uint8_t b1 = data[pos++];
          masked_ = b1 & 0x80;
          if (require_mask_ && !masked_) return WsStatus::kProtoError;
          uint8_t n = b1 & 0x7F;
          if (is_ctrl_ && n > 125) return WsStatus::kCtrlTooBig;
          if (n < 126) {
            need_ = n;
            ext_need_ = 0;
            phase_ = masked_ ? Phase::kMask : Phase::kPayload;
          } else {
            ext_need_ = n == 126 ? 2 : 8;
            need_ = 0;
            phase_ = Phase::kExtLen;
          }
          mask_got_ = 0;
          mask_off_ = 0;
          if (phase_ == Phase::kPayload && need_ == 0) {
            WsStatus st = FinishEmpty(on_data, on_ctrl);
            if (st != WsStatus::kOk) return st;
          }
          break;
        }
        case Phase::kExtLen: {
          need_ = (need_ << 8) | data[pos++];
          if (--ext_need_ == 0) {
            phase_ = masked_ ? Phase::kMask : Phase::kPayload;
            if (phase_ == Phase::kPayload && need_ == 0) {
              WsStatus st = FinishEmpty(on_data, on_ctrl);
              if (st != WsStatus::kOk) return st;
            }
          }
          break;
        }
        case Phase::kMask: {
          mask_[mask_got_++] = data[pos++];
          if (mask_got_ == 4) {
            phase_ = Phase::kPayload;
            if (need_ == 0) {
              WsStatus st = FinishEmpty(on_data, on_ctrl);
              if (st != WsStatus::kOk) return st;
            }
          }
          break;
        }
        case Phase::kPayload: {
          size_t take = len - pos;
          if (take > need_) take = static_cast<size_t>(need_);
          uint8_t* chunk = data + pos;
          if (masked_) {
            // in-place unmask, 8 bytes per XOR once key-phase-aligned
            size_t i = 0;
            uint32_t ph = mask_off_;
            while (i < take && (ph & 3)) {
              chunk[i++] ^= mask_[ph & 3];
              ph++;
            }
            if (take >= i + 8) {
              uint64_t key8;
              uint8_t kb[8];
              for (int b = 0; b < 8; b++) kb[b] = mask_[b & 3];
              memcpy(&key8, kb, 8);
              for (; i + 8 <= take; i += 8) {
                uint64_t v;
                memcpy(&v, chunk + i, 8);
                v ^= key8;
                memcpy(chunk + i, &v, 8);
              }
            }
            // word loop consumed multiples of 4: phase is 0 here
            for (uint32_t t = 0; i < take; i++, t++)
              chunk[i] ^= mask_[t & 3];
            mask_off_ = (mask_off_ + take) & 3;
          }
          if (is_ctrl_) {
            ctrl_buf_.append(reinterpret_cast<const char*>(chunk), take);
          } else {
            if (!on_data(reinterpret_cast<const char*>(chunk), take))
              return WsStatus::kAborted;
          }
          pos += take;
          need_ -= take;
          if (need_ == 0) {
            if (is_ctrl_) {
              bool keep = on_ctrl(opcode_, ctrl_buf_.data(),
                                  ctrl_buf_.size());
              ctrl_buf_.clear();
              if (!keep) return WsStatus::kAborted;
            }
            phase_ = Phase::kB0;
          }
          break;
        }
      }
    }
    return WsStatus::kOk;
  }

 private:
  template <typename DataFn, typename CtrlFn>
  WsStatus FinishEmpty(DataFn&& on_data, CtrlFn&& on_ctrl) {
    // zero-length payload completes the frame without a kPayload pass
    if (is_ctrl_) {
      if (!on_ctrl(opcode_, ctrl_buf_.data(), size_t{0}))
        return WsStatus::kAborted;
    } else {
      if (!on_data("", size_t{0})) return WsStatus::kAborted;
    }
    phase_ = Phase::kB0;
    return WsStatus::kOk;
  }

  // Decoder sits at a frame AND message boundary — the park plane's
  // hibernation precondition for WS conns (the decoder is dropped and
  // rebuilt at inflation, so mid-frame state must not exist).
 public:
  bool idle() const { return phase_ == Phase::kB0 && !in_msg_; }

 private:
  enum class Phase { kB0, kB1, kExtLen, kMask, kPayload };
  bool require_mask_;
  Phase phase_ = Phase::kB0;
  bool fin_ = false, masked_ = false, is_ctrl_ = false, in_msg_ = false;
  uint8_t opcode_ = 0;
  uint64_t need_ = 0;
  int ext_need_ = 0;
  uint8_t mask_[4] = {};
  int mask_got_ = 0;
  uint32_t mask_off_ = 0;
  std::string ctrl_buf_;   // control-frame payload accumulation
};

}  // namespace ws
}  // namespace emqx_native
