// Parked-conn hibernation + accept-storm governance — the per-conn
// memory diet of the conn-scale plane (round 16).
//
// The reference broker reaches 100M conns/cluster by HIBERNATING idle
// connection processes (emqx_connection.erl enters erlang hibernate
// after an idle stretch, dropping the process heap to a continuation).
// Our analogue: an idle conn's full `Conn` struct — framer buffer,
// outbuf, permit set, flight recorder, and above all the lazily-grown
// AckState (20KB of window bitmaps once any QoS1/2 delivery touched
// the conn) — collapses into a `Parked` record of a couple hundred
// bytes holding exactly what re-inflation needs: the fd, the wire
// flags, the keepalive clock, and a SPARSE summary of any mid-flight
// ack window (the flight recorder's lazy-alloc discipline generalized
// to the whole conn). The fd stays registered in epoll under the same
// tag, so the FIRST BYTE from the peer re-inflates the conn before
// any fast-path work — hibernation is invisible on the wire.
//
// Records live in a slab (fixed block pool, stable u32 slots, free
// list) so a million parked conns are a handful of large allocations
// instead of a million heap nodes, and park/inflate churn never
// fragments the poll thread's arena.
//
// The AcceptGovernor is the accept-storm rung of the degradation
// ladder: admission is decided in the accept loop BEFORE any conn
// side effect (id mint, table insert, OPEN event). Backlog pressure
// (per-cycle accept burst) DEFERS — the kernel listen backlog holds
// the remainder for the next cycle, no side effects at all; a parked-
// memory budget breach SHEDS — close-with-ledger, visible as
// `messages.ledger.accept_shed` and the `conns_shed` stat slot.
//
// Ownership: everything here is owned by one shard's poll thread
// (the wheel.h contract); control threads configure it through the
// host Op queue.
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <utility>
#include <vector>

namespace emqx_native {
namespace park {

// Fixed-size block pool with stable u32 slots. Free() resets the
// object so its heap (vectors, strings) releases immediately; the
// block spine itself is never returned (parked herds re-grow).
template <typename T>
class Slab {
 public:
  static constexpr size_t kBlock = 1024;

  uint32_t Alloc() {
    if (!free_.empty()) {
      uint32_t i = free_.back();
      free_.pop_back();
      return i;
    }
    if (top_ == blocks_.size() * kBlock)
      blocks_.emplace_back(new T[kBlock]);
    return top_++;
  }

  T& at(uint32_t i) { return blocks_[i / kBlock][i % kBlock]; }

  void Free(uint32_t i) {
    at(i) = T();
    free_.push_back(i);
  }

  size_t live() const { return top_ - free_.size(); }
  size_t spine_bytes() const {
    return blocks_.size() * kBlock * sizeof(T) +
           free_.capacity() * sizeof(uint32_t);
  }

 private:
  std::vector<std::unique_ptr<T[]>> blocks_;
  std::vector<uint32_t> free_;
  size_t top_ = 0;
};

// Parked-record flags.
constexpr uint8_t kPkFast = 1;    // fast path was enabled
constexpr uint8_t kPkWs = 2;      // WebSocket transport (codec was idle)
constexpr uint8_t kPkSynth = 4;   // synthetic conn (fd < 0, bench/test)

// The hibernated conn. The ack-window summary is SPARSE: `infl` packs
// (pid - kNativePidBase) | qos2_bit << 16 | rel_bit << 17 per
// in-flight delivery, `awrel` lists publisher awaiting-rel pids — a
// parked conn with a mid-flight qos1 window re-inflates with the
// window intact (tests/test_native_connscale.py pins the PUBACK after
// park/inflate landing on the right slot).
struct Parked {
  int fd = -1;
  uint8_t flags = 0;
  uint8_t proto_ver = 4;
  uint16_t next_pid = 0;
  uint32_t keepalive_ms = 0;     // effective deadline (1.5x keepalive)
  uint32_t max_inflight = 0;
  uint64_t last_rx_ms = 0;
  // wheel handle — survives hibernation; @gen-handle: flows only into
  // generation-checked wheel consumers (a recycled slot must no-op)
  uint64_t tm_keepalive = 0;
  std::vector<uint32_t> infl;    // sparse in-flight window summary
  std::vector<uint16_t> awrel;   // publisher qos2 awaiting-rel pids
  std::vector<std::string> own_subs;
  std::vector<std::pair<uint64_t, std::string>> own_shared;
};

// The record target is "a few hundred bytes": the struct itself must
// stay small enough that a million parked conns are slab spine + the
// (usually one-element) sub vectors.
static_assert(sizeof(Parked) <= 192, "parked record outgrew its diet");

// Approximate resident bytes of one record (struct + tracked heap) —
// the parked-memory gauge the accept governor budgets against and the
// bench's bytes/conn-parked numerator.
inline size_t RecordBytes(const Parked& p) {
  size_t n = sizeof(Parked);
  n += p.infl.capacity() * sizeof(uint32_t);
  n += p.awrel.capacity() * sizeof(uint16_t);
  for (const std::string& s : p.own_subs)
    n += sizeof(std::string) + s.capacity();
  for (const auto& [tok, s] : p.own_shared)
    n += sizeof(uint64_t) + sizeof(std::string) + s.capacity();
  return n;
}

// Accept-storm governance: the ladder rung decided in the accept loop
// before side effects. Defer = backlog pressure (stop accepting this
// cycle, the kernel backlog queues); shed = memory budget breach
// (close-with-ledger). Poll-thread-owned; configured via the Op queue.
class AcceptGovernor {
 public:
  void Configure(uint32_t burst_max, uint64_t mem_budget_bytes) {
    burst_max_ = burst_max;
    mem_budget_ = mem_budget_bytes;
  }

  void BeginCycle() { cycle_accepts_ = 0; }

  // Backlog pressure: past the per-cycle burst the remainder of the
  // kernel backlog waits for the next cycle — no side effects, no
  // shed. 0 = unlimited.
  bool Defer() const {
    return burst_max_ != 0 && cycle_accepts_ >= burst_max_;
  }

  // The accept-shed admission decision, taken BEFORE any conn side
  // effect; `est_conn_bytes` is the host's current conn-memory
  // estimate (resident + parked). 0 budget = always admit.
  // @admit-check
  bool Admit(uint64_t est_conn_bytes) {
    cycle_accepts_++;
    return mem_budget_ == 0 || est_conn_bytes <= mem_budget_;
  }

  uint32_t burst_max() const { return burst_max_; }
  uint64_t mem_budget() const { return mem_budget_; }

 private:
  uint32_t burst_max_ = 0;
  uint64_t mem_budget_ = 0;
  uint32_t cycle_accepts_ = 0;
};

}  // namespace park
}  // namespace emqx_native
