// Native cluster trunk (round 9): the inter-node message plane.
//
// Two native hosts talk to each other over plain TCP "trunk" links so a
// cross-node publish never touches either node's Python plane for QoS0
// (and QoS1 rides with a bounded replay ring).  This is the gen_rpc
// forwarding lane of the reference (emqx_broker.erl:302-324 casting
// `dispatch` on a per-topic-ordered client pool, emqx_rpc.erl:74-84)
// moved below the GIL, the way rounds 6-8 moved acks, WS and telemetry
// there.
//
// Wire format (symmetric; in practice each direction of forwarding uses
// its own dialed link — A dials B to forward A->B):
//
//   [u32 len][u8 type][body]          little-endian, len covers type+body
//
//   type 2 = BATCH  body = [u64 seq][u32 n] + n entries, one entry per
//                   forwarded publish in the kind-6 pre-parse layout:
//                   [u64 origin][u8 flags][u16 tlen][topic]
//                   + (flags bit0 ? [u32 plen][payload] : payload
//                   identical to the PREVIOUS entry in this batch).
//                   flags bits 1-2 = qos, bit 3 = publisher DUP.
//                   One batch per poll cycle per peer (the EmitTap /
//                   FlushAcks batching discipline applied to the wire);
//                   TCP framing + the receiver's sequential decode give
//                   per-topic order for free.
//   type 3 = ACK    body = [u64 seq] — the receiver acks each batch
//                   AFTER local fan-out. Acks retire EXACTLY the ring
//                   entry they name (round 15): a cumulative trim let
//                   an up-but-black link (a TCP partition, not a
//                   close) lose acked qos1 silently — batches written
//                   into the void were retired by the first
//                   post-heal ack for a LATER seq. Now an ack for a
//                   seq ahead of the ring front is evidence the link
//                   skipped data and kills it ("ack_gap"), and a link
//                   whose front entry goes unacked past the ack
//                   timeout dies too ("ack_timeout") — both deaths
//                   redial + replay the ring, so loss becomes dups.
//                   The sender also uses the ack for the
//                   enqueue->peer-ack RTT stage.
//
// Reliability ladder (host.cc wires the seams):
//   - QoS0: fire-and-forget; batches are not retained once written.
//   - QoS1: every flushed batch containing elevated-qos entries keeps a
//     qos1-only copy in a bounded per-peer unacked ring; on reconnect
//     the ring replays before new traffic (at-least-once across a link
//     death — duplicates allowed, loss not).  A full ring degrades NEW
//     qos1 publishes to the Python forward lane.
//   - QoS2: never trunks — exactly-once spans two nodes' session state
//     and stays on the Python lane (the remote entry punts).
#pragma once

#include <cstdint>
#include <cstring>
#include <deque>
#include <string>
#include <string_view>

namespace emqx_native {
namespace trunk {

constexpr uint8_t kRecBatch = 2;
constexpr uint8_t kRecAck = 3;
// HELLO (round 13, wire version negotiation): body = [u8 version].
// The dialer sends its version on connect BEFORE any batch; the
// receiver answers with its own. Either side missing the exchange
// (an old peer ignores unknown record types and sends none) leaves
// the negotiated version at 0, and the dialer then emits v0 entries —
// trace ids are STRIPPED (losslessly: topic/payload untouched), never
// put on a wire the peer cannot parse.
constexpr uint8_t kRecHello = 4;
// Version 1 adds the per-entry trace-id extension: entry flags bit 4
// set means a [u64 trace_id] follows the topic bytes (before the
// payload section). Both sides must have negotiated >= 1 to use it.
constexpr uint8_t kWireVersion = 1;

// PROTOCOL-level size bounds, deliberately independent of either
// node's max_packet_size: a record sized by the sender's config but
// validated against the receiver's would poison mismatched clusters
// (the oversized replay record re-killing the link on every redial).
// Publishes whose entry exceeds kMaxEntryBytes never trunk — they
// degrade to the Python forward lane like any other punt.
constexpr size_t kMaxEntryBytes = 128 * 1024;
constexpr size_t kMaxRecordBytes = 512 * 1024;

// Frame one trunk record onto a socket buffer.
inline void AppendRecord(std::string* out, uint8_t type, const char* body,
                         size_t blen) {
  uint32_t len = static_cast<uint32_t>(1 + blen);
  char hdr[5];
  memcpy(hdr, &len, 4);
  hdr[4] = static_cast<char>(type);
  out->append(hdr, 5);
  out->append(body, blen);
}

// Append one pre-parse entry ([origin][flags][topic][trace?][payload?])
// to a batch body under construction.  ``inline_payload=false`` emits
// the dedup form (payload identical to the previous entry in this
// batch). ``trace != 0`` sets flags bit 4 and appends the [u64
// trace_id] after the topic bytes (the wire-v1 tracing extension —
// callers pass 0 on links whose negotiated version is below 1).
inline void AppendEntry(std::string* out, uint64_t origin, uint8_t qos,
                        bool dup, bool inline_payload,
                        std::string_view topic, std::string_view payload,
                        uint64_t trace = 0) {
  char hdr[11];
  memcpy(hdr, &origin, 8);
  hdr[8] = static_cast<char>((inline_payload ? 1 : 0) | (qos << 1) |
                             (dup ? 8 : 0) | (trace ? 0x10 : 0));
  uint16_t tl = static_cast<uint16_t>(topic.size());
  memcpy(hdr + 9, &tl, 2);
  out->append(hdr, 11);
  out->append(topic.data(), topic.size());
  if (trace) out->append(reinterpret_cast<const char*>(&trace), 8);
  if (inline_payload) {
    uint32_t pl = static_cast<uint32_t>(payload.size());
    out->append(reinterpret_cast<const char*>(&pl), 4);
    out->append(payload.data(), payload.size());
  }
}

// Re-encode one framed qos1 replay record at wire v0: clear each
// entry's trace flag (bit 4) and drop its [u64 trace_id] — the
// lossless strip (topic/payload untouched) TrunkEnqueue applies to
// LIVE entries on v0 links, applied at REPLAY time to a shadow built
// on a v1 link whose reconnect negotiated lower. Replay-shadow
// entries are always payload-inline. Any parse inconsistency returns
// the input unchanged (the caller built this record; a malformed one
// is an upstream bug, and v0 peers reject oversized/garbled records
// at the link layer anyway).
inline std::string StripTraceRecord(const std::string& rec) {
  if (rec.size() < 5 + 12 || static_cast<uint8_t>(rec[4]) != kRecBatch)
    return rec;
  const char* body = rec.data() + 5;
  size_t blen = rec.size() - 5;
  uint32_t n = 0;
  memcpy(&n, body + 8, 4);
  std::string out_body;
  out_body.reserve(blen);
  out_body.append(body, 12);  // [u64 seq][u32 n] unchanged
  size_t pos = 12;
  for (uint32_t i = 0; i < n; i++) {
    if (pos + 11 > blen) return rec;
    char hdr[11];
    memcpy(hdr, body + pos, 11);
    uint8_t flags = static_cast<uint8_t>(hdr[8]);
    uint16_t tlen = 0;
    memcpy(&tlen, hdr + 9, 2);
    hdr[8] = static_cast<char>(flags & ~0x10);
    pos += 11;
    if (pos + tlen > blen) return rec;
    out_body.append(hdr, 11);
    out_body.append(body + pos, tlen);
    pos += tlen;
    if (flags & 0x10) {
      if (pos + 8 > blen) return rec;
      pos += 8;  // the dropped trace id
    }
    if (flags & 1) {
      if (pos + 4 > blen) return rec;
      uint32_t pl = 0;
      memcpy(&pl, body + pos, 4);
      if (pos + 4 + pl > blen) return rec;
      out_body.append(body + pos, 4 + pl);
      pos += 4 + pl;
    }
  }
  std::string out;
  AppendRecord(&out, kRecBatch, out_body.data(), out_body.size());
  return out;
}

// One trunk TCP socket (dialer or accepted), poll-thread-owned.
struct Sock {
  int fd = -1;
  bool dialer = false;      // we dialed it (it carries OUR batches out)
  bool connecting = false;  // nonblocking connect still in flight
  uint64_t peer_id = 0;     // dialer only: which peer this link serves
  std::string inbuf;        // partial trunk records
  std::string outbuf;       // unsent bytes (partial-write backlog)
  size_t outpos = 0;
  // highest BATCH seq applied on this sock (receiver side): seqs must
  // strictly ascend per link — a regressed/duplicate seq is a poisoned
  // stream and kills the sock ("seq_regress", round 15). Gaps are
  // legal (replay skips acked/empty batches; down-window seals burn
  // seqs), so only monotonicity is enforced here; loss detection is
  // the SENDER's ack_gap/ack_timeout job.
  uint64_t last_seq = 0;
};

// A flushed-but-unacked batch (the QoS1 replay ring entry).
struct Unacked {
  uint64_t seq = 0;
  uint64_t t0_ns = 0;       // flush stamp (0 = telemetry off)
  // coarse flush/replay stamp for the silent-link watchdog (round 15):
  // refreshed at replay so a ring carried across a down window does
  // not trip the timeout the instant the link comes back up
  uint64_t flush_ms = 0;
  // pre-framed qos1-only wire record for this batch ("" = batch held
  // no elevated-qos entries; nothing to replay, ring entry exists only
  // for the RTT stage). Built at the HIGHEST wire version the entries
  // carry (sampled trace ids persist in the shadow, round 14): replay
  // emits it verbatim on a >= v1 link and re-encodes it at v0 —
  // StripTraceRecord — when the reconnected peer negotiated lower.
  std::string q1_record;
  bool has_trace = false;   // any entry carries the bit-4 extension
};

// Per-peer trunk state: link identity + the batch under construction.
struct Peer {
  uint64_t sock_tag = 0;    // live dialer sock tag (0 = no link)
  bool up = false;          // connected; remote entries forward here
  // negotiated wire version for the CURRENT link (reset to 0 on every
  // link death; re-negotiated by the HELLO exchange per connection)
  uint8_t wire_ver = 0;
  std::string addr;         // redial target (Python drives redial)
  uint16_t port = 0;
  // stable store key for the persisted replay ring (round 18): the
  // peer's NODE NAME, set by trunk_ident — peer ids renumber across
  // restarts, so the ring must key on something that survives them.
  // Empty = no ident yet; the host falls back to "peer:<id>" (raw
  // single-process tests).
  std::string store_name;
  // the persisted ring was merged into `unacked` (or this peer started
  // journaling fresh) — guards against a later load duplicating entries
  bool ring_loaded = false;
  // HELLO sent on the live link, answer (or the bounded grace
  // deadline, for old peers that never answer) still pending: the
  // qos1 replay + the UP event wait for the negotiated version, so a
  // replayed batch can keep its trace annotation on v1 links
  bool hello_pending = false;
  uint64_t hello_deadline_ms = 0;
  std::string batch;        // BATCH entries accumulated this cycle
  uint32_t batch_n = 0;
  uint32_t q0_n = 0;        // qos0 entries in `batch` (shed accounting)
  std::string q1_batch;     // qos1-only copies (full payloads, no dedup)
  uint32_t q1_n = 0;
  bool q1_has_trace = false;  // q1_batch holds >= 1 bit-4 trace entry
  std::string prev_payload; // payload-dedup reference (batch-scoped)
  bool have_prev = false;
  uint64_t next_seq = 1;
  std::deque<Unacked> unacked;
  // ack-watchdog wheel handle (round 16): the per-poll TrunkAckScan
  // sweep moved onto the host's timer wheel — armed when the ring
  // front gains its watchdog reference (first unacked entry, replay
  // re-stamp), re-armed from the fire against the live front
  // @gen-handle
  uint64_t tm_ack = 0;
};

}  // namespace trunk
}  // namespace emqx_native
