// faultline (round 15): deterministic fault injection at the native
// plane's syscall seams.
//
// Every failure the plane had ever been tested against was a clean
// kill: SIGKILL closes sockets with a FIN/RST, so the half-open-link
// machinery (HELLO grace, redial backoff, the qos1 replay shadow under
// a link that is up-but-black) shipped unexercised. This header is the
// missing lever: NAMED fault sites compiled into the hot paths, each a
// SINGLE relaxed-atomic load + branch when disarmed, armed from Python
// via ``emqx_host_fault_arm(site, mode, n_or_prob, seed, key)``.
//
// Site catalog (keep in sync with native/__init__.py FAULT_SITES —
// tests/test_stats_lint.py enforces the mechanical mapping, and the
// nativecheck ``fault`` rule enforces that every site below has an
// annotated C++ fire site exercised by at least one test):
//
//   conn_read / conn_write / conn_accept   client-socket recv/send/accept
//   trunk_read / trunk_write / trunk_accept / trunk_connect
//                                          trunk-link syscall seams
//   store_msync / store_seg_open           durable-store fsync + segment
//                                          open (EIO / ENOSPC)
//   ring_seal                              cross-shard ring: forced full
//   ring_doorbell                          cross-shard wakeup suppressed
//   housekeep_clock                        ConnIdleMs reads a skewed clock
//
// Modes (what an armed site does when it fires):
//
//   errno      fail the call with the site's canonical errno
//              (ECONNRESET sockets, EIO msync, ENOSPC segment-open)
//   short      send() writes only a prefix of the requested bytes
//              (the partial-write backlog machinery under test)
//   blackhole  a TCP partition rather than a close: writes claim full
//              success while the bytes vanish; reads drain-and-discard
//              and report "nothing arrived". The socket stays
//              ESTABLISHED — no FIN/RST ever surfaces, which is
//              exactly the half-open shape SIGKILL tests cannot make.
//   full       ring seal: the admission check reports no room
//              (forced ring_full -> punt -> Python ladder)
//   skew       housekeep clock: n_or_prob milliseconds are ADDED to
//              the idle clock (keepalive scans see the future)
//
// Determinism contract: ``n_or_prob`` selects the firing schedule —
//   0        fire on EVERY hit while armed (partitions persist);
//   n >= 1   fire on exactly the next floor(n) hits, then auto-disarm;
//   0 < p <1 fire each hit with probability p drawn from xorshift64
//            seeded by ``seed`` — same seed + same hit order = the
//            bit-identical firing sequence, so chaos runs REPLAY.
// ``key`` scopes a site to one object: conn id for conn_* sites, peer
// id for trunk_* sites (dialer legs — accepted socks have no peer
// identity and never match a scoped arm), destination shard + 1 for
// ring_* sites. key 0 arms the site for every object.
//
// Threading: arming uses only atomics and may race the poll thread
// freely (DRIVER_FAULT hammers exactly that under ASan+TSan); the
// firing decision is single-consumer per site in practice (poll
// thread, or the store mutex for store sites), which is what the
// replay-determinism pin relies on.
#pragma once

#include <atomic>
#include <cstdint>

namespace emqx_native {
namespace fault {

// keep in sync with native/__init__.py FAULT_SITES (stats-lint rule)
enum Site {
  kSiteConnRead = 0,
  kSiteConnWrite,
  kSiteConnAccept,
  kSiteTrunkRead,
  kSiteTrunkWrite,
  kSiteTrunkAccept,
  kSiteTrunkConnect,
  kSiteStoreMsync,
  kSiteStoreSegOpen,
  kSiteRingSeal,
  kSiteRingDoorbell,
  kSiteHousekeepClock,
  kSiteCount
};

// keep in sync with native/__init__.py FAULT_MODES
enum Mode {
  kModeOff = 0,
  kModeErrno,
  kModeShort,
  kModeBlackhole,
  kModeFull,
  kModeSkew,
};

struct SiteState {
  // 0 = disarmed: THE hot branch. Arm publishes the schedule fields
  // below with its release store; Fire's acquire load pairs with it
  // (armed()'s relaxed peek only gates whether to pay Fire at all).
  // @atomic(acq_rel: Arm release-publishes the schedule fields; Fire acquire-loads before reading them)
  std::atomic<uint32_t> mode{0};
  // @atomic(relaxed: written before mode's release publish, read after Fire's acquire) 0 = any object
  std::atomic<uint64_t> key{0};
  // @atomic(relaxed: single consumer per site in practice; -1 = until disarmed) countdown
  std::atomic<int64_t> remaining{-1};
  // @atomic(relaxed: published by mode, read-only after arm) 0 = always; else 2^-32 units
  std::atomic<uint32_t> prob{0};
  // @atomic(relaxed: xorshift64 state, single consumer per site keeps replay deterministic)
  std::atomic<uint64_t> prng{0};
  // @atomic(relaxed: raw n_or_prob magnitude, read by Param for skew ms)
  std::atomic<int64_t> param{0};
  // @atomic(relaxed: monotone fire counter, cross-thread gauge read)
  std::atomic<uint64_t> fired{0};
};

class Injector {
 public:
  // The disarmed fast path: one relaxed atomic load + branch. Call
  // sites gate on this before paying Fire()'s decision cost.
  // -DEMQX_NO_FAULTLINE compiles the whole layer out (constant false
  // folds every branch away) — the bench's "disarmed sites are free"
  // baseline arm (EMQX_NATIVE_NOFAULT=1 builds that variant).
  bool armed(int site) const {
#ifdef EMQX_NO_FAULTLINE
    (void)site;
    return false;
#else
    return sites_[site].mode.load(std::memory_order_relaxed) != 0;
#endif
  }

  // Arm ``site`` (mode kModeOff disarms). See the header comment for
  // the n_or_prob / seed / key contract. Thread-safe; resets the
  // firing schedule (countdown + PRNG) every call.
  void Arm(int site, int mode, double n_or_prob, uint64_t seed,
           uint64_t key) {
    if (site < 0 || site >= kSiteCount) return;
    SiteState& st = sites_[site];
    st.key.store(key, std::memory_order_relaxed);
    st.param.store(static_cast<int64_t>(n_or_prob),
                   std::memory_order_relaxed);
    if (mode == kModeSkew || n_or_prob <= 0.0 || mode == kModeOff) {
      // skew carries its magnitude in n_or_prob: fire every hit
      st.remaining.store(-1, std::memory_order_relaxed);
      st.prob.store(0, std::memory_order_relaxed);
    } else if (n_or_prob >= 1.0) {
      st.remaining.store(static_cast<int64_t>(n_or_prob),
                         std::memory_order_relaxed);
      st.prob.store(0, std::memory_order_relaxed);
    } else {
      st.remaining.store(-1, std::memory_order_relaxed);
      st.prob.store(
          static_cast<uint32_t>(n_or_prob * 4294967296.0),
          std::memory_order_relaxed);
      st.prng.store(seed ? seed : 0x9E3779B97F4A7C15ull,
                    std::memory_order_relaxed);
    }
    st.mode.store(static_cast<uint32_t>(mode < 0 ? 0 : mode),
                  std::memory_order_release);
  }

  // Armed-path decision for one hit: returns the mode when the fault
  // fires (and counts it), 0 otherwise. ``key`` identifies the object
  // at the call site (see the scoping contract above).
  int Fire(int site, uint64_t key = 0) {
    SiteState& st = sites_[site];
    uint32_t m = st.mode.load(std::memory_order_acquire);
    if (m == 0) return 0;
    uint64_t want = st.key.load(std::memory_order_relaxed);
    if (want != 0 && key != want) return 0;
    uint32_t prob = st.prob.load(std::memory_order_relaxed);
    if (prob) {
      // xorshift64*: one consumer per site, so relaxed load/store is
      // a deterministic sequence given the seed and hit order
      uint64_t x = st.prng.load(std::memory_order_relaxed);
      x ^= x >> 12;
      x ^= x << 25;
      x ^= x >> 27;
      st.prng.store(x, std::memory_order_relaxed);
      uint32_t draw =
          static_cast<uint32_t>((x * 0x2545F4914F6CDD1Dull) >> 32);
      if (draw >= prob) return 0;
    }
    int64_t rem = st.remaining.load(std::memory_order_relaxed);
    if (rem >= 0) {
      if (rem == 0) {
        st.mode.store(0, std::memory_order_release);  // spent: disarm
        return 0;
      }
      st.remaining.store(rem - 1, std::memory_order_relaxed);
    }
    st.fired.fetch_add(1, std::memory_order_relaxed);
    return static_cast<int>(m);
  }

  int64_t Param(int site) const {
    return sites_[site].param.load(std::memory_order_relaxed);
  }

  uint64_t FiredCount(int site) const {
    if (site < 0 || site >= kSiteCount) return 0;
    return sites_[site].fired.load(std::memory_order_relaxed);
  }

 private:
  SiteState sites_[kSiteCount];
};

}  // namespace fault
}  // namespace emqx_native
